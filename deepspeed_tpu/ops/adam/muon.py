"""Muon optimizer (momentum + Newton-Schulz orthogonalized update).

Fills the ``"optimizer": {"type": "Muon"}`` config path.  The orthogonalization
is five Newton-Schulz iterations — pure matmuls, so it runs on the MXU at
bf16-friendly precision; this is the TPU-idiomatic shape of the algorithm
(no SVD, no host round-trip).

Matrix-shaped parameters ([m, n], and stacked [L, m, n] layer params via
vmap) get the orthogonalized update; vectors/scalars (biases, norm scales)
AND embedding/lm-head tables fall back to plain momentum SGD, matching the
usual Muon deployment where non-hidden-layer params use a different rule
(orthogonalizing the embedding update distorts token-frequency-dependent
magnitudes).  The exclusion is path-based (``exclude`` predicate; default
matches "embed"/"head"/"tok" path components).
"""

from __future__ import annotations

import re
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

# Quintic Newton-Schulz coefficients (public Muon constants): maximize the
# slope at zero so singular values converge to ~1 in few iterations.
_NS_A, _NS_B, _NS_C = 3.4445, -4.7750, 2.0315


def _newton_schulz(g: jnp.ndarray, steps: int = 5, eps: float = 1e-7) -> jnp.ndarray:
    """Approximately orthogonalize a single [m, n] matrix."""
    transpose = g.shape[0] > g.shape[1]
    x = g.T if transpose else g
    x = x / (jnp.linalg.norm(x) + eps)

    def body(x, _):
        a = x @ x.T
        b = _NS_B * a + _NS_C * (a @ a)
        return _NS_A * x + b @ x, None

    x, _ = jax.lax.scan(body, x, None, length=steps)
    return x.T if transpose else x


def orthogonalize(g: jnp.ndarray, steps: int = 5) -> jnp.ndarray:
    """Newton-Schulz orthogonalization for [m, n] or stacked [L, m, n]."""
    if g.ndim == 2:
        return _newton_schulz(g, steps)
    if g.ndim == 3:
        return jax.vmap(lambda m: _newton_schulz(m, steps))(g)
    raise ValueError(f"orthogonalize expects 2D/3D, got {g.ndim}D")


class MuonState(NamedTuple):
    count: jnp.ndarray
    momentum: Any


_DEFAULT_EXCLUDE = re.compile(r"embed|head|tok|wte|wpe", re.IGNORECASE)


def _default_exclude(path: str) -> bool:
    return bool(_DEFAULT_EXCLUDE.search(path))


def muon(learning_rate: Union[float, Callable] = 2e-2, weight_decay: float = 0.0,
         momentum: float = 0.95, nesterov: bool = True, ns_steps: int = 5,
         exclude: Optional[Callable[[str], bool]] = _default_exclude,
         ) -> optax.GradientTransformation:
    """Muon as an optax GradientTransformation.

    ``exclude(path) -> True`` routes that parameter to plain momentum SGD
    instead of the orthogonalized update (embeddings/heads by default).
    """

    def init(params):
        return MuonState(
            count=jnp.zeros((), jnp.int32),
            momentum=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params))

    def update(grads, state, params=None):
        # 0-based schedule evaluation, matching optax.scale_by_schedule.
        lr = learning_rate(state.count) if callable(learning_rate) else learning_rate
        count = state.count + 1

        def leaf(g, buf, p, excluded):
            g32 = g.astype(jnp.float32)
            buf = momentum * buf + g32
            eff = g32 + momentum * buf if nesterov else buf
            if eff.ndim in (2, 3) and not excluded:
                o = orthogonalize(eff, ns_steps)
                # scale so update RMS matches Adam-style magnitudes across
                # aspect ratios (public Muon scaling rule)
                o = o * jnp.sqrt(jnp.maximum(1.0, eff.shape[-2] / eff.shape[-1]))
            else:
                o = eff
            upd = -lr * (o + weight_decay * p.astype(jnp.float32))
            return upd.astype(p.dtype), buf

        flat_pp, treedef = jax.tree_util.tree_flatten_with_path(params)
        paths = [jax.tree_util.keystr(kp) for kp, _ in flat_pp]
        flat_p = [v for _, v in flat_pp]
        flat_g = treedef.flatten_up_to(grads)
        flat_b = treedef.flatten_up_to(state.momentum)
        outs = [leaf(g, b, p, exclude(path) if exclude else False)
                for g, b, p, path in zip(flat_g, flat_b, flat_p, paths)]
        updates = jax.tree_util.tree_unflatten(treedef, [u for u, _ in outs])
        bufs = jax.tree_util.tree_unflatten(treedef, [b for _, b in outs])
        return updates, MuonState(count=count, momentum=bufs)

    return optax.GradientTransformation(init, update)
