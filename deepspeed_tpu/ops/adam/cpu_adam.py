"""DeepSpeedCPUAdam: host optimizer step over offloaded fp32 states.

Reference parity: ``deepspeed/ops/adam/cpu_adam.py`` (``DeepSpeedCPUAdam``
with ``adamw_mode``; SURVEY.md §2.1) — the optimizer the engine swaps in when
``zero_optimization.offload_optimizer.device == "cpu"``.  States live in host
numpy; the C++ kernel (csrc/cpu_adam) does the math, sharded across a thread
pool (the reference's OpenMP parallel-for).  Falls back to a pure-numpy step
if the native build is unavailable.
"""

from __future__ import annotations

import ctypes
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger

_MIN_CHUNK = 1 << 16


def _lib():
    from deepspeed_tpu.ops.op_builder.native import CPUAdamBuilder

    return CPUAdamBuilder().load()


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


class DeepSpeedCPUAdam:
    """Adam/AdamW over a list of host fp32 arrays (one 'param group')."""

    def __init__(self, params: Optional[List[np.ndarray]] = None, lr: float = 1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8, weight_decay: float = 0.0,
                 amsgrad: bool = False, adamw_mode: bool = True,
                 fp32_optimizer_states: bool = True, num_threads: int = 0):
        if amsgrad:
            raise NotImplementedError("amsgrad not supported (reference parity)")
        self.lr = lr
        self.betas = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.step_count = 0
        self.state: Dict[int, Dict[str, np.ndarray]] = {}
        self.params = [np.ascontiguousarray(p, dtype=np.float32) for p in (params or [])]
        try:
            self._native = _lib()
        except Exception as e:  # pragma: no cover
            logger.warning("cpu_adam native lib unavailable (%s); numpy fallback", e)
            self._native = None
        import os

        self._pool = ThreadPoolExecutor(max_workers=num_threads or min(16, os.cpu_count() or 1))

    def _ensure_state(self, i: int, p: np.ndarray):
        if i not in self.state:
            self.state[i] = {"exp_avg": np.zeros_like(p),
                             "exp_avg_sq": np.zeros_like(p)}

    def _native_step(self, p, g, m, v, step):
        n = p.size
        b1, b2 = self.betas
        lib = self._native

        def run(lo, hi):
            lib.ds_adam_step(
                ctypes.c_int64(hi - lo),
                ctypes.c_void_p(p.ctypes.data + 4 * lo),
                ctypes.c_void_p(g.ctypes.data + 4 * lo),
                ctypes.c_void_p(m.ctypes.data + 4 * lo),
                ctypes.c_void_p(v.ctypes.data + 4 * lo),
                ctypes.c_int64(step), ctypes.c_float(self.lr), ctypes.c_float(b1),
                ctypes.c_float(b2), ctypes.c_float(self.eps),
                ctypes.c_float(self.weight_decay), ctypes.c_int(int(self.adamw_mode)))

        workers = self._pool._max_workers
        if n <= _MIN_CHUNK or workers == 1:
            run(0, n)
            return
        chunk = (n + workers - 1) // workers
        futs = [self._pool.submit(run, lo, min(lo + chunk, n))
                for lo in range(0, n, chunk)]
        for f in futs:
            f.result()

    def _numpy_step(self, p, g, m, v, step):
        b1, b2 = self.betas
        if self.adamw_mode:
            p *= 1.0 - self.lr * self.weight_decay
        elif self.weight_decay:
            g = g + self.weight_decay * p
        m *= b1
        m += (1 - b1) * g
        v *= b2
        v += (1 - b2) * np.square(g)
        bc1 = 1 - b1 ** step
        bc2 = 1 - b2 ** step
        p -= (self.lr / bc1) * m / (np.sqrt(v) / np.sqrt(bc2) + self.eps)

    def step(self, grads: Optional[List[np.ndarray]] = None, lr: Optional[float] = None):
        """In-place update of self.params given matching grads."""
        if lr is not None:
            self.lr = lr
        if grads is None:
            raise ValueError("pass grads=[...] matching params")
        self.step_count += 1
        for i, (p, g) in enumerate(zip(self.params, grads)):
            self._ensure_state(i, p)
            g = np.ascontiguousarray(g, dtype=np.float32).reshape(-1)
            pf = p.reshape(-1)
            st = self.state[i]
            m, v = st["exp_avg"].reshape(-1), st["exp_avg_sq"].reshape(-1)
            if self._native is not None:
                self._native_step(pf, g, m, v, self.step_count)
            else:
                self._numpy_step(pf, g, m, v, self.step_count)
        return self.params
