"""Async file I/O (reference: ``deepspeed/ops/aio`` over ``csrc/aio/``).

``AsyncIOBuilder().load()`` compiles/loads the C++ library (csrc/aio); the
``aio_handle`` class mirrors the reference handle API: ``async_pread`` /
``async_pwrite`` submit, ``wait()`` drains (returns error count, 0 = ok).
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.op_builder.native import AsyncIOBuilder


class aio_handle:
    """Handle over the native thread-pool async IO engine."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 single_submit: bool = False, overlap_events: bool = True,
                 num_threads: int = 4, use_direct: bool = False):
        self._lib = AsyncIOBuilder().load()
        self._h = self._lib.ds_aio_handle_new(
            block_size, queue_depth, int(single_submit), int(overlap_events),
            num_threads, int(use_direct))
        if not self._h:
            raise RuntimeError("failed to create aio handle")
        # Buffers whose raw pointers are enqueued to worker threads; kept
        # alive here until wait() so an ascontiguousarray temporary (or a
        # caller buffer the caller drops) is not freed mid-I/O.
        self._pending: list[np.ndarray] = []

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> None:
        buffer = np.ascontiguousarray(buffer)
        self._pending.append(buffer)
        self._lib.ds_aio_pwrite_async(self._h, path.encode(),
                                      buffer.ctypes.data_as(ctypes.c_void_p),
                                      buffer.nbytes, offset)

    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> None:
        assert buffer.flags["C_CONTIGUOUS"], "read target must be contiguous"
        self._pending.append(buffer)
        self._lib.ds_aio_pread_async(self._h, path.encode(),
                                     buffer.ctypes.data_as(ctypes.c_void_p),
                                     buffer.nbytes, offset)

    def wait(self) -> int:
        rc = int(self._lib.ds_aio_wait(self._h))
        self._pending.clear()
        return rc

    def sync_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pwrite(buffer, path, offset)
        return self.wait()

    def sync_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pread(buffer, path, offset)
        return self.wait()

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ds_aio_handle_free(self._h)
                self._h = None
        except Exception:
            pass


__all__ = ["aio_handle", "AsyncIOBuilder"]
