"""DeepSpeedCPULion: host Lion step over offloaded fp32 states.

Reference parity: ``deepspeed/ops/lion/cpu_lion.py`` (verified API at
SURVEY.md (L2:93)).  The C step is compiled into csrc/cpu_adam
(``ds_lion_step``); this wrapper makes it reachable from the offload path
(VERDICT r2 row 50).
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class DeepSpeedCPULion:
    def __init__(self, params: Optional[List[np.ndarray]] = None, lr: float = 1e-4,
                 betas=(0.9, 0.99), weight_decay: float = 0.0):
        self.lr = lr
        self.betas = betas
        self.weight_decay = weight_decay
        self.step_count = 0
        self.params = [np.ascontiguousarray(p, np.float32) for p in (params or [])]
        self.state: Dict[int, Dict[str, np.ndarray]] = {}
        try:
            from deepspeed_tpu.ops.op_builder.native import CPUAdamBuilder

            self._native = CPUAdamBuilder().load()
        except Exception as e:  # pragma: no cover
            logger.warning("cpu_lion native lib unavailable (%s); numpy fallback", e)
            self._native = None

    def _native_step(self, p: np.ndarray, g: np.ndarray, m: np.ndarray):
        b1, b2 = self.betas
        self._native.ds_lion_step(
            ctypes.c_int64(p.size),
            p.ctypes.data_as(ctypes.c_void_p), g.ctypes.data_as(ctypes.c_void_p),
            m.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_float(self.lr), ctypes.c_float(b1), ctypes.c_float(b2),
            ctypes.c_float(self.weight_decay))

    def _numpy_step(self, p, g, m):
        b1, b2 = self.betas
        update = np.sign(b1 * m + (1 - b1) * g)
        if self.weight_decay:
            update = update + self.weight_decay * p
        p -= self.lr * update
        m *= b2
        m += (1 - b2) * g

    def step(self, grads: Optional[List[np.ndarray]] = None):
        self.step_count += 1
        for i, p in enumerate(self.params):
            if i not in self.state:
                self.state[i] = {"exp_avg": np.zeros_like(p)}
            g = np.ascontiguousarray(grads[i], np.float32).reshape(p.shape)
            m = self.state[i]["exp_avg"]
            if self._native is not None:
                self._native_step(p.reshape(-1), g.reshape(-1), m.reshape(-1))
            else:
                self._numpy_step(p, g, m)
