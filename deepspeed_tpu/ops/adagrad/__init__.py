"""DeepSpeedCPUAdagrad: host Adagrad step over offloaded fp32 states.

Reference parity: ``deepspeed/ops/adagrad/cpu_adagrad.py`` (verified API at
SURVEY.md (L2:79)).  The C step is compiled into csrc/cpu_adam
(``ds_adagrad_step``); this wrapper makes it reachable from the offload
path (VERDICT r2 row 50).
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import logger


class DeepSpeedCPUAdagrad:
    def __init__(self, params: Optional[List[np.ndarray]] = None, lr: float = 1e-2,
                 eps: float = 1e-10, weight_decay: float = 0.0):
        self.lr = lr
        self.eps = eps
        self.weight_decay = weight_decay
        self.step_count = 0
        self.params = [np.ascontiguousarray(p, np.float32) for p in (params or [])]
        self.state: Dict[int, Dict[str, np.ndarray]] = {}
        try:
            from deepspeed_tpu.ops.op_builder.native import CPUAdamBuilder

            self._native = CPUAdamBuilder().load()
        except Exception as e:  # pragma: no cover
            logger.warning("cpu_adagrad native lib unavailable (%s); numpy fallback", e)
            self._native = None

    def _native_step(self, p: np.ndarray, g: np.ndarray, sq: np.ndarray):
        self._native.ds_adagrad_step(
            ctypes.c_int64(p.size),
            p.ctypes.data_as(ctypes.c_void_p), g.ctypes.data_as(ctypes.c_void_p),
            sq.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_float(self.lr), ctypes.c_float(self.eps),
            ctypes.c_float(self.weight_decay))

    def _numpy_step(self, p, g, sq):
        if self.weight_decay:
            g = g + self.weight_decay * p
        sq += g * g
        p -= self.lr * g / (np.sqrt(sq) + self.eps)

    def step(self, grads: Optional[List[np.ndarray]] = None):
        self.step_count += 1
        for i, p in enumerate(self.params):
            if i not in self.state:
                self.state[i] = {"exp_avg_sq": np.zeros_like(p)}
            g = np.ascontiguousarray(grads[i], np.float32).reshape(p.shape)
            sq = self.state[i]["exp_avg_sq"]
            if self._native is not None:
                self._native_step(p.reshape(-1), g.reshape(-1), sq.reshape(-1))
            else:
                self._numpy_step(p, g, sq)
