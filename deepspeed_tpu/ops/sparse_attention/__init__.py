"""Block-sparse attention.

Reference: ``deepspeed/ops/sparse_attention/`` (SURVEY.md §2.1 "Sparse
attention") — Triton block-sparse matmul/softmax kernels driven by a
``SparsityConfig`` family (fixed, bigbird, bslongformer, variable).

TPU-native shape: the sparsity layout is a STATIC [nq, nk] block mask built
host-side by the same config family; compute gathers only the allowed KV
blocks per query block (static max-degree padding keeps shapes fixed for
XLA) and runs an online-softmax over them — block-skipping delivers the
FLOP/memory win the Triton kernels got, without materializing [S, S].
"""

from deepspeed_tpu.ops.sparse_attention.sparsity_config import (  # noqa: F401
    BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
    FixedSparsityConfig, SparsityConfig, VariableSparsityConfig)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (  # noqa: F401
    SparseSelfAttention, block_sparse_attention)
