"""Block-sparse attention compute + SparseSelfAttention wrapper.

Reference: ``deepspeed/ops/sparse_attention/{matmul,softmax,sparse_self_attention}.py``
— Triton SDD/DSD block matmuls around a block softmax.  TPU-native: gather
the allowed KV blocks per query block (static max-degree from the layout,
padded; XLA-friendly fixed shapes) and run an online softmax over the
gathered blocks.  FLOPs and HBM traffic scale with the number of ALLOWED
blocks, not S².
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@functools.partial(jax.jit, static_argnums=(4, 5, 9, 10))
def _bsa(q, k, v, gather_idx, block: int, causal: bool, rpe=None,
         key_padding_mask=None, attn_mask=None,
         key_padding_mask_mode: str = "add", attn_mask_mode: str = "mul"):
    """q/k/v: [B, H, S, D]; gather_idx: [H, nq, deg] int32 (padded with -1).

    Computes, per query block, attention over its ``deg`` gathered KV blocks.
    Optional score modifiers (reference ``sparse_self_attention.py`` /
    ``softmax.py`` semantics, applied pre-softmax on the gathered blocks):
    ``rpe`` [H, S, S] or [S, S] additive relative-position bias;
    ``key_padding_mask`` [B, S] over keys; ``attn_mask`` [S, S] — each mask
    "add"ed to or "mul"tiplied into the scores per its mode."""
    B, H, S, D = q.shape
    nq = S // block
    deg = gather_idx.shape[-1]
    qb = q.reshape(B, H, nq, block, D)
    kb = k.reshape(B, H, nq, block, D)
    vb = v.reshape(B, H, nq, block, D)
    scale = 1.0 / (D ** 0.5)

    idx = jnp.maximum(gather_idx, 0)                              # [H, nq, deg]
    valid = gather_idx >= 0                                       # [H, nq, deg]

    def gather_blocks(xb):
        # xb: [B, H, nk, block, D] -> [B, H, nq, deg, block, D]
        return jax.vmap(lambda xh, ih: xh[:, ih], in_axes=(1, 0),
                        out_axes=1)(xb, idx)

    kg = gather_blocks(kb)
    vg = gather_blocks(vb)
    # scores: [B, H, nq, block, deg, block]
    s = jnp.einsum("bhqid,bhqkjd->bhqikj", qb.astype(jnp.float32),
                   kg.astype(jnp.float32)) * scale
    qpos = (jnp.arange(nq)[:, None] * block
            + jnp.arange(block)[None, :])                         # [nq, block]
    kpos = (idx[..., None] * block
            + jnp.arange(block)[None, None, None])                # [H,nq,deg,block]
    if rpe is not None:
        rpe = jnp.asarray(rpe, jnp.float32)
        if rpe.ndim == 2:
            r = rpe[qpos[None, :, :, None, None], kpos[:, :, None, :, :]]
        else:                                                     # [H, S, S]
            r = rpe[jnp.arange(H)[:, None, None, None, None],
                    qpos[None, :, :, None, None], kpos[:, :, None, :, :]]
        s = s + r[None]                                           # bias is additive
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask, jnp.float32)
        kg_mask = kpm[:, kpos]                     # [B, H, nq, deg, block]
        kg_mask = kg_mask[:, :, :, None, :, :]     # broadcast over q rows
        if key_padding_mask_mode == "add":
            s = s + kg_mask
        else:
            s = s * kg_mask
    if attn_mask is not None:
        am = jnp.asarray(attn_mask, jnp.float32)
        amg = am[qpos[None, :, :, None, None],
                 kpos[:, :, None, :, :]]           # [H, nq, block, deg, block]
        if attn_mask_mode == "add":
            s = s + amg[None]
        else:
            s = s * amg[None]
    s = jnp.where(valid[None, :, :, None, :, None], s, NEG_INF)
    if causal:
        mask = qpos[None, :, :, None, None] >= kpos[:, :, None, :, :]
        s = jnp.where(mask[None], s, NEG_INF)
    s_flat = s.reshape(B, H, nq, block, deg * block)
    m = jnp.max(s_flat, axis=-1, keepdims=True)
    p = jnp.exp(s_flat - m)
    p = jnp.where(s_flat <= NEG_INF / 2, 0.0, p)
    denom = jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-30)
    p = (p / denom).reshape(B, H, nq, block, deg, block)
    out = jnp.einsum("bhqikj,bhqkjd->bhqid", p, vg.astype(jnp.float32))
    return out.reshape(B, H, S, D).astype(q.dtype)


def block_sparse_attention(q, k, v, layout: np.ndarray, block: int,
                           causal: bool = False, rpe=None,
                           key_padding_mask=None, attn_mask=None,
                           key_padding_mask_mode: str = "add",
                           attn_mask_mode: str = "mul"):
    """Attention restricted to the layout's allowed blocks.

    layout: [H, nq, nk] (numpy, static).  Compute cost is
    O(max_degree / nk) of dense attention.  ``rpe`` /
    ``key_padding_mask`` / ``attn_mask`` follow the reference's
    pre-softmax add/mul semantics (see :func:`_bsa`).
    """
    H, nq, nk = layout.shape
    deg = max(1, int(layout.sum(axis=-1).max()))
    gather = np.full((H, nq, deg), -1, np.int32)
    for h in range(H):
        for i in range(nq):
            cols = np.nonzero(layout[h, i])[0]
            gather[h, i, :len(cols)] = cols
    return _bsa(q, k, v, jnp.asarray(gather), block, causal, rpe,
                key_padding_mask, attn_mask, key_padding_mask_mode,
                attn_mask_mode)


class SparseSelfAttention:
    """Reference-parity wrapper: config in, attention callable out."""

    def __init__(self, sparsity_config, key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul", max_seq_length: int = 2048):
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError(f"key_padding_mask_mode must be 'add' or 'mul', "
                             f"got {key_padding_mask_mode!r}")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError(f"attn_mask_mode must be 'add' or 'mul', got "
                             f"{attn_mask_mode!r}")
        self.sparsity_config = sparsity_config
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self._layouts = {}

    def _layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        S = query.shape[-2]
        layout = self._layout(S)
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        return block_sparse_attention(
            query, key, value, layout, self.sparsity_config.block,
            causal=causal, rpe=rpe, key_padding_mask=key_padding_mask,
            attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode)
