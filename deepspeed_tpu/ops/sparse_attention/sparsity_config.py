"""Sparsity layout builders (reference: sparsity_config.py class family).

Each config emits a static numpy block mask ``layout [num_heads, nq, nk]``
(1 = compute the block).  Names, parameters, and pattern semantics follow
the reference: ``Fixed`` (local + periodic global columns), ``BigBird``
(random + window + global), ``BSLongformer`` (sliding window + global
indices), ``Variable`` (custom local windows + globals), ``Dense``.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np


class SparsityConfig:
    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), np.int8)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _apply_causal(self, layout: np.ndarray) -> np.ndarray:
        n = layout.shape[-1]
        return layout * np.tril(np.ones((n, n), np.int8))


class DenseSparsityConfig(SparsityConfig):
    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local blocks + periodic global columns (reference Fixed pattern)."""

    def __init__(self, num_heads: int, block: int = 16, num_local_blocks: int = 4,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1, **kw):
        super().__init__(num_heads, block, **kw)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[-1]
        L = self.num_local_blocks
        for h in range(self.num_heads):
            pat = (h % self.num_different_global_patterns
                   if self.different_layout_per_head else 0)
            for i in range(n):
                blk = i // L
                # local window: blocks in the same local chunk
                lo, hi = blk * L, min(n, (blk + 1) * L)
                layout[h, i, lo:hi] = 1
                # global columns: last num_global_blocks of each prior chunk
                for c in range(blk + 1):
                    gstart = min(n, (c + 1) * L) - self.num_global_blocks - pat
                    gstart = max(0, gstart)
                    gend = min(n, gstart + self.num_global_blocks)
                    layout[h, i, gstart:gend] = 1
                if self.horizontal_global_attention:
                    g = min(n, (blk + 1) * L) - self.num_global_blocks
                    if max(0, g) <= i < max(0, g) + self.num_global_blocks:
                        layout[h, i, :] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global blocks (reference BigBird)."""

    def __init__(self, num_heads: int, block: int = 16, num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3, num_global_blocks: int = 1,
                 attention: str = "bidirectional", seed: int = 0, **kw):
        super().__init__(num_heads, block, **kw)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[-1]
        rng = np.random.default_rng(self.seed)
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_heads):
            for i in range(n):
                layout[h, i, max(0, i - w):min(n, i + w + 1)] = 1
                cand = rng.choice(n, size=min(n, self.num_random_blocks),
                                  replace=False)
                layout[h, i, cand] = 1
            layout[h, :, :self.num_global_blocks] = 1
            layout[h, :self.num_global_blocks, :] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """sliding window + user-specified global block indices."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional", **kw):
        super().__init__(num_heads, block, **kw)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[-1]
        w = self.num_sliding_window_blocks // 2
        for i in range(n):
            layout[:, i, max(0, i - w):min(n, i + w + 1)] = 1
        ends = (self.global_block_end_indices
                or [g + 1 for g in self.global_block_indices])
        for g, e in zip(self.global_block_indices, ends):
            layout[:, :, g:e] = 1
            layout[:, g:e, :] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """custom local window sizes + global blocks (reference Variable)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_local_blocks: Optional[List[int]] = None,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 horizontal_global_attention: bool = False, **kw):
        super().__init__(num_heads, block, **kw)
        self.local_windows = num_local_blocks or [4]
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[-1]
        start = 0
        wi = 0
        while start < n:
            w = self.local_windows[min(wi, len(self.local_windows) - 1)]
            end = min(n, start + w)
            layout[:, start:end, start:end] = 1
            start = end
            wi += 1
        layout[:, :, :self.num_global_blocks] = 1
        if self.horizontal_global_attention:
            layout[:, :self.num_global_blocks, :] = 1
        if self.attention == "unidirectional":
            layout = self._apply_causal(layout)
        return layout
