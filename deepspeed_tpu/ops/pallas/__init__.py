from deepspeed_tpu.ops.pallas.flash_attention import flash_attention, mha_reference
from deepspeed_tpu.ops.pallas.fused_adam import fused_adam_update
from deepspeed_tpu.ops.pallas.layer_norm import layer_norm, rms_norm
from deepspeed_tpu.ops.pallas.quantizer import (dequantize, pack_int4, quantize,
                                                unpack_int4)
from deepspeed_tpu.ops.pallas.rope import apply_rotary_pos_emb, rope_angles
from deepspeed_tpu.ops.pallas.softmax import bias_act, scaled_masked_softmax

__all__ = ["flash_attention", "mha_reference", "fused_adam_update", "layer_norm",
           "rms_norm", "apply_rotary_pos_emb", "rope_angles", "bias_act",
           "scaled_masked_softmax", "quantize", "dequantize", "pack_int4",
           "unpack_int4"]
