"""Shared helpers for Pallas TPU kernels.

TPU-native analog of the reference's ``csrc/includes/`` shared headers
(SURVEY.md §2.2 "Common headers"): dispatch policy, tiling helpers, and the
interpret-mode switch that lets every kernel run (and be parity-tested)
on the CPU backend.
"""

from __future__ import annotations

import functools
import os

import jax

# Resolution order for each op's implementation:
#   "pallas"  - compiled Pallas kernel (TPU)
#   "interpret" - Pallas kernel in interpreter mode (CPU tests)
#   "xla"     - pure jnp reference (always available; XLA fuses well)
_FORCE = os.environ.get("DSTPU_KERNEL_IMPL")  # override for debugging/benchmarks


@functools.lru_cache(maxsize=None)
def default_impl() -> str:
    if _FORCE:
        return _FORCE
    return "pallas" if jax.default_backend() == "tpu" else "xla"


def resolve_impl(impl: str | None) -> str:
    return impl if impl is not None else default_impl()


def interpret_flag(impl: str) -> bool:
    return impl == "interpret"


def round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def pick_block(n: int, preferred: int, minimum: int = 128) -> int:
    """Largest divisor-of-n block <= preferred, else n itself (small inputs)."""
    if n <= preferred:
        return n
    for b in range(preferred, minimum - 1, -minimum):
        if n % b == 0:
            return b
    return n
