"""Fused LayerNorm and RMSNorm Pallas kernels with custom VJP.

TPU-native replacement for the reference's ``csrc/transformer/normalize_kernels.cu``
(training LayerNorm fwd/bwd) and ``csrc/transformer/inference/csrc/layer_norm.cu``
+ ``rms_norm.cu`` (SURVEY.md §2.2): one row-blocked kernel per pass instead of
warp-shuffle reductions — the VPU reduces across the feature (lane) dimension
natively.  The backward recomputes row statistics from x instead of saving
them (one extra VPU reduction over data already in VMEM, in exchange for no
1-D stat tensors in HBM — Mosaic wants ≥2-D tiles, and the memory saving is
the same trade the reference kernels make with their "stochastic mode").
Backward weight-gradients are produced as per-block partials and summed
outside the kernel (grid-parallel, no atomics).

Every entry point takes ``impl`` ∈ {None, "pallas", "interpret", "xla"}; the
jnp path is the numerics reference for parity tests (SURVEY.md §4(b)).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.common import interpret_flag, pick_block, resolve_impl

# 512-row tiles: fewer grid steps than 256 while the bwd kernel's blocks and
# fp32 temporaries stay inside the scoped-VMEM budget even when fused into a
# large training program (1024 rows compiles standalone but trips the scoped
# limit inside the full step at n=768).  Wider features shrink the rows: the
# Mosaic compile hard-fails past ~512K elements per block there (measured on
# v5e: 256x4096 and 128x8192 die, 128x4096 and 64x8192 compile), so past
# n=2048 the cap is area-based.
_BLOCK_ROWS = 512
_WIDE_BLOCK_ELEMS = 512 * 1024


def _rows_blocks(rows: int, n: int, wide_at: int = 2048):
    """LayerNorm's backward carries more fp32 temporaries than RMSNorm's, so
    it switches to the area-based cap one width step earlier
    (``wide_at=1024``)."""
    cap = (_BLOCK_ROWS if n <= wide_at
           else max(8, (_WIDE_BLOCK_ELEMS // max(n, 1)) // 8 * 8))
    br = pick_block(rows, cap, minimum=8) if rows >= 8 else rows
    return br, rows // br if rows % br == 0 else 1


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def _ln_fwd_kernel(x_ref, g_ref, b_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    xhat = xc * jax.lax.rsqrt(var + eps)
    y = xhat * g_ref[0].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)


def _ln_bwd_kernel(x_ref, g_ref, dy_ref, dx_ref, dg_ref, db_ref, *, eps):
    # dg/db are a single (1, n) block shared across the (sequential) TPU grid:
    # zero on the first step, accumulate in VMEM on every step.
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy, axis=-1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = ((wdy - c1 - xhat * c2) * rstd).astype(dx_ref.dtype)
    dg_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[:] += jnp.sum(dy, axis=0, keepdims=True)


def _rms_fwd_kernel(x_ref, g_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    y_ref[:] = (x * rstd * g_ref[0].astype(jnp.float32)).astype(y_ref.dtype)


def _rms_bwd_kernel(x_ref, g_ref, dy_ref, dx_ref, dg_ref, *, eps):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_ref[:] = jnp.zeros_like(dg_ref)

    x = x_ref[:].astype(jnp.float32)
    dy = dy_ref[:].astype(jnp.float32)
    g = g_ref[0].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    xhat = x * rstd
    wdy = dy * g
    c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
    dx_ref[:] = ((wdy - xhat * c2) * rstd).astype(dx_ref.dtype)
    dg_ref[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)


# ---------------------------------------------------------------------------
# LayerNorm public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x, gamma, beta, eps: float = 1e-5, impl: Optional[str] = None):
    """Fused LayerNorm over the last dim.  fp32 statistics regardless of
    input dtype (matching the reference kernel's accumulation behavior)."""
    impl = resolve_impl(impl)
    if impl == "xla":
        return _ln_xla(x, gamma, beta, eps)
    orig = x.shape
    n = orig[-1]
    x2 = x.reshape(-1, n)
    rows = x2.shape[0]
    br, grid = _rows_blocks(rows, n, wide_at=1024)
    y = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=(grid,),
        in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                  pl.BlockSpec((1, n), lambda i: (0, 0)),
                  pl.BlockSpec((1, n), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
        interpret=interpret_flag(impl),
    )(x2, gamma.reshape(1, n), beta.reshape(1, n))
    return y.reshape(orig)


def _ln_xla(x, gamma, beta, eps):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)).astype(x.dtype)


def _layer_norm_fwd_vjp(x, gamma, beta, eps, impl):
    return layer_norm(x, gamma, beta, eps, impl), (x, gamma)


def _layer_norm_bwd_vjp(eps, impl, res, dy):
    x, gamma = res
    impl = resolve_impl(impl)
    orig = x.shape
    n = orig[-1]
    x2 = x.reshape(-1, n)
    dy2 = dy.reshape(-1, n)
    if impl == "xla":
        xf = x2.astype(jnp.float32)
        dyf = dy2.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        xc = xf - mean
        rstd = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
        xhat = xc * rstd
        wdy = dyf * gamma.astype(jnp.float32)
        c1 = jnp.mean(wdy, axis=-1, keepdims=True)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        dx = ((wdy - c1 - xhat * c2) * rstd).astype(x.dtype)
        dg = jnp.sum(dyf * xhat, axis=0)
        db = jnp.sum(dyf, axis=0)
    else:
        rows = x2.shape[0]
        br, grid = _rows_blocks(rows, n, wide_at=1024)
        dx, dg_part, db_part = pl.pallas_call(
            functools.partial(_ln_bwd_kernel, eps=eps),
            grid=(grid,),
            in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                      pl.BlockSpec((1, n), lambda i: (0, 0)),
                      pl.BlockSpec((br, n), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                       pl.BlockSpec((1, n), lambda i: (0, 0)),
                       pl.BlockSpec((1, n), lambda i: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, n), x.dtype),
                       jax.ShapeDtypeStruct((1, n), jnp.float32),
                       jax.ShapeDtypeStruct((1, n), jnp.float32)],
            interpret=interpret_flag(impl),
        )(x2, gamma.reshape(1, n), dy2)
        dg, db = dg_part[0], db_part[0]
    return dx.reshape(orig), dg.astype(gamma.dtype), db.astype(gamma.dtype)


layer_norm.defvjp(_layer_norm_fwd_vjp, _layer_norm_bwd_vjp)


# ---------------------------------------------------------------------------
# RMSNorm public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def rms_norm(x, gamma, eps: float = 1e-6, impl: Optional[str] = None):
    """Fused RMSNorm (reference: inference ``rms_norm.cu``; used by Llama)."""
    impl = resolve_impl(impl)
    if impl == "xla":
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        return (xf * jax.lax.rsqrt(ms + eps) * gamma.astype(jnp.float32)).astype(x.dtype)
    orig = x.shape
    n = orig[-1]
    x2 = x.reshape(-1, n)
    rows = x2.shape[0]
    br, grid = _rows_blocks(rows, n)
    y = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(grid,),
        in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                  pl.BlockSpec((1, n), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
        interpret=interpret_flag(impl),
    )(x2, gamma.reshape(1, n))
    return y.reshape(orig)


def _rms_norm_fwd_vjp(x, gamma, eps, impl):
    return rms_norm(x, gamma, eps, impl), (x, gamma)


def _rms_norm_bwd_vjp(eps, impl, res, dy):
    x, gamma = res
    impl = resolve_impl(impl)
    orig = x.shape
    n = orig[-1]
    x2 = x.reshape(-1, n)
    dy2 = dy.reshape(-1, n)
    if impl == "xla":
        xf = x2.astype(jnp.float32)
        dyf = dy2.astype(jnp.float32)
        rstd = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
        xhat = xf * rstd
        wdy = dyf * gamma.astype(jnp.float32)
        c2 = jnp.mean(wdy * xhat, axis=-1, keepdims=True)
        dx = ((wdy - xhat * c2) * rstd).astype(x.dtype)
        dg = jnp.sum(dyf * xhat, axis=0)
    else:
        rows = x2.shape[0]
        br, grid = _rows_blocks(rows, n)
        dx, dg_part = pl.pallas_call(
            functools.partial(_rms_bwd_kernel, eps=eps),
            grid=(grid,),
            in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                      pl.BlockSpec((1, n), lambda i: (0, 0)),
                      pl.BlockSpec((br, n), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                       pl.BlockSpec((1, n), lambda i: (0, 0))],
            out_shape=[jax.ShapeDtypeStruct((rows, n), x.dtype),
                       jax.ShapeDtypeStruct((1, n), jnp.float32)],
            interpret=interpret_flag(impl),
        )(x2, gamma.reshape(1, n), dy2)
        dg = dg_part[0]
    return dx.reshape(orig), dg.astype(gamma.dtype)


rms_norm.defvjp(_rms_norm_fwd_vjp, _rms_norm_bwd_vjp)
