"""Fused scaled-masked softmax + fused bias/activation epilogues.

TPU-native replacements for the reference's ``csrc/transformer/softmax_kernels.cu``
(fused scale+mask+softmax), ``gelu_kernels.cu`` (fused bias+GeLU) and the
inference ``gelu.cu`` bias+act variants (SURVEY.md §2.2).  On TPU most of
these fuse under XLA automatically; the Pallas forms exist for parity,
deterministic fusion, and as building blocks for the transformer layer op.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.common import interpret_flag, pick_block, resolve_impl

NEG_INF = -1e30


def _softmax_kernel(x_ref, y_ref, *, scale):
    x = x_ref[:].astype(jnp.float32) * scale
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def _masked_softmax_kernel(x_ref, mask_ref, y_ref, *, scale):
    x = x_ref[:].astype(jnp.float32) * scale
    x = jnp.where(mask_ref[:] != 0, x, NEG_INF)
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    y_ref[:] = (e / jnp.sum(e, axis=-1, keepdims=True)).astype(y_ref.dtype)


def scaled_masked_softmax(x, mask=None, scale: float = 1.0, impl: Optional[str] = None):
    """Softmax over the last dim with optional pre-scale and boolean keep-mask
    (1 = attend, 0 = masked out)."""
    impl = resolve_impl(impl)
    if impl == "xla":
        xf = x.astype(jnp.float32) * scale
        if mask is not None:
            xf = jnp.where(mask != 0, xf, NEG_INF)
        return jax.nn.softmax(xf, axis=-1).astype(x.dtype)
    orig = x.shape
    n = orig[-1]
    x2 = x.reshape(-1, n)
    rows = x2.shape[0]
    br = pick_block(rows, 256, minimum=8) if rows >= 8 else rows
    grid = rows // br if rows % br == 0 else 1
    if grid == 1:
        br = rows
    if mask is None:
        y = pl.pallas_call(
            functools.partial(_softmax_kernel, scale=scale),
            grid=(grid,),
            in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((br, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
            interpret=interpret_flag(impl),
        )(x2)
    else:
        mask2 = jnp.broadcast_to(mask, orig).reshape(-1, n).astype(jnp.int32)
        y = pl.pallas_call(
            functools.partial(_masked_softmax_kernel, scale=scale),
            grid=(grid,),
            in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                      pl.BlockSpec((br, n), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((br, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
            interpret=interpret_flag(impl),
        )(x2, mask2)
    return y.reshape(orig)


def _bias_act_kernel(x_ref, b_ref, y_ref, *, act):
    x = x_ref[:].astype(jnp.float32) + b_ref[0].astype(jnp.float32)
    if act == "gelu":
        y = jax.nn.gelu(x, approximate=True)
    elif act == "relu":
        y = jnp.maximum(x, 0.0)
    elif act == "silu":
        y = x * jax.nn.sigmoid(x)
    else:
        y = x
    y_ref[:] = y.astype(y_ref.dtype)


def bias_act(x, bias, act: str = "gelu", impl: Optional[str] = None):
    """Fused bias-add + activation (reference: fused_bias_gelu/relu/silu)."""
    impl = resolve_impl(impl)
    if impl == "xla":
        xf = x.astype(jnp.float32) + bias.astype(jnp.float32)
        if act == "gelu":
            y = jax.nn.gelu(xf, approximate=True)
        elif act == "relu":
            y = jnp.maximum(xf, 0.0)
        elif act == "silu":
            y = xf * jax.nn.sigmoid(xf)
        else:
            y = xf
        return y.astype(x.dtype)
    orig = x.shape
    n = orig[-1]
    x2 = x.reshape(-1, n)
    rows = x2.shape[0]
    br = pick_block(rows, 256, minimum=8) if rows >= 8 else rows
    grid = rows // br if rows % br == 0 else 1
    if grid == 1:
        br = rows
    y = pl.pallas_call(
        functools.partial(_bias_act_kernel, act=act),
        grid=(grid,),
        in_specs=[pl.BlockSpec((br, n), lambda i: (i, 0)),
                  pl.BlockSpec((1, n), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((br, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, n), x.dtype),
        interpret=interpret_flag(impl),
    )(x2, bias.reshape(1, n))
    return y.reshape(orig)
