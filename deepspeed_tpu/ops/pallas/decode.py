"""Fused per-layer decode kernels (single-token generation fast path).

TPU-native counterpart of the reference's fused inference kernels
(``(R) csrc/transformer/inference/csrc/``: ``pt_binding.cpp`` dispatching
fused layer_norm/rms_norm, qkv_gemm, rotary, attention with the workspace KV
cache, residual+bias, and the MLP gemm chain; SURVEY.md §2.2 "Inference
kernels").  At s=1 the per-token cost is dominated not by FLOPs but by the
number of device kernel launches the unfused HLO chain emits (~25/layer);
these kernels collapse each layer to four launches:

- :func:`fused_norm_qkv`   — norm → QKV projection (one concatenated matmul)
- :func:`flash_decode`     — online-softmax attention over the KV cache in a
  single kernel, length-aware via scalar-prefetched position (the DMA index
  map clamps beyond ``pos`` so HBM traffic tracks the generated length)
- :func:`fused_proj_norm`  — attention out-projection → residual add → norm
- :func:`fused_mlp`        — (gated) MLP → residual add, blocked over the
  FFN dim so VMEM holds one weight tile at a time

Each op keeps a pure-jnp reference (the CPU path and the parity target); the
Pallas kernels run in interpret mode on CPU for tests, matching the dispatch
policy in :mod:`deepspeed_tpu.ops.pallas.common`.

All softmax/norm/accumulation math is fp32; matmul operands stay in the
serving dtype (bf16) for MXU rate, accumulating fp32 — the same contract as
the training kernels in this package.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.common import interpret_flag, resolve_impl

NEG_INF = -1e30

# VMEM weight-tile budget per grid step (bytes). ~6MB leaves room for the
# double-buffered next tile + activations inside the ~16MB/core VMEM.
_TILE_BYTES = 6 * 2**20


def _col_block(d_in: int, n_cols: int, itemsize: int = 2) -> int:
    """Largest 128-multiple column block with d_in*block*itemsize under the
    tile budget, and dividing n_cols (falls back to n_cols for small ops)."""
    cap = max(128, _TILE_BYTES // max(1, d_in * itemsize) // 128 * 128)
    if n_cols <= cap:
        return n_cols
    for b in range(cap, 127, -128):
        if n_cols % b == 0:
            return b
    return n_cols


def _normalize(x32, scale, bias, kind: str, eps: float):
    """fp32 norm over the last axis; ``bias`` ignored for rmsnorm."""
    if kind == "rmsnorm":
        var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + eps)
        return y * scale
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    xc = x32 - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps)
    return y * scale + bias


def _act(name: str, x):
    if name == "silu":
        return jax.nn.silu(x)
    if name == "gelu":
        return jax.nn.gelu(x, approximate=True)
    if name == "gelu_exact":
        return jax.nn.gelu(x, approximate=False)
    if name == "relu":
        return jax.nn.relu(x)
    raise ValueError(f"unsupported activation {name}")


# ---------------------------------------------------------------------------
# fused_norm_qkv: x [B, D] -> norm -> @ wqkv [D, N] (+ bqkv) -> [B, N]
# ---------------------------------------------------------------------------

def _deq(w, ws, dtype):
    """int8 payload * per-out-channel scale -> compute dtype (the in-kernel
    form of ``QTensor.astype``; reference ``(R) dequantize.cu`` role)."""
    return (w.astype(jnp.float32) * ws).astype(dtype)


def _norm_qkv_ref(x, scale, bias, wqkv, bqkv, *, kind, eps, wscale=None):
    h = _normalize(x.astype(jnp.float32), scale.astype(jnp.float32),
                   bias.astype(jnp.float32), kind, eps).astype(x.dtype)
    if wscale is not None:
        wqkv = _deq(wqkv, wscale.reshape(1, -1), x.dtype)
    y = jax.lax.dot_general(h, wqkv, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if bqkv is not None:
        y = y + bqkv.astype(jnp.float32)
    return y.astype(x.dtype)


def _norm_qkv_kernel(x_ref, s_ref, b_ref, w_ref, ws_ref, bq_ref, o_ref,
                     h_scr, *, kind, eps, has_bias, quant):
    @pl.when(pl.program_id(0) == 0)
    def _norm():
        x32 = x_ref[:].astype(jnp.float32)
        h = _normalize(x32, s_ref[:].astype(jnp.float32),
                       b_ref[:].astype(jnp.float32), kind, eps)
        h_scr[:] = h.astype(h_scr.dtype)

    w = _deq(w_ref[:], ws_ref[:], h_scr.dtype) if quant else w_ref[:]
    y = jax.lax.dot_general(h_scr[:], w, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if has_bias:
        y = y + bq_ref[:].astype(jnp.float32)
    o_ref[:] = y.astype(o_ref.dtype)


def fused_norm_qkv(x, scale, bias, wqkv, bqkv=None, *, kind: str = "layernorm",
                   eps: float = 1e-5, wscale=None, impl: Optional[str] = None):
    """x: [B, D]; wqkv: [D, N]; returns [B, N] in x.dtype.  ``wscale``
    [N]-broadcastable fp32 marks ``wqkv`` as int8 (dequant in-kernel).

    Reference: fused ln/rmsnorm + qkv_gemm of ``(R)
    csrc/transformer/inference`` (one launch instead of norm + 3 GEMVs)."""
    impl = resolve_impl(impl)
    if bias is None:
        bias = jnp.zeros_like(scale)
    if impl == "xla":
        return _norm_qkv_ref(x, scale, bias, wqkv, bqkv, kind=kind, eps=eps,
                             wscale=wscale)
    B, D = x.shape
    N = wqkv.shape[1]
    quant = wscale is not None
    # quant sizing counts the in-kernel fp32 dequant intermediate, not the
    # int8 payload — a payload-sized block would overflow VMEM at 1B+ scale
    bn = _col_block(D, N, 4 if quant else wqkv.dtype.itemsize)
    has_bias = bqkv is not None
    bq = (bqkv if has_bias else jnp.zeros((N,), x.dtype)).reshape(1, N)
    ws = (wscale if quant else jnp.ones((N,), jnp.float32)).reshape(1, N)
    kernel = functools.partial(_norm_qkv_kernel, kind=kind, eps=eps,
                               has_bias=has_bias, quant=quant)
    return pl.pallas_call(
        kernel,
        grid=(N // bn,),
        in_specs=[pl.BlockSpec((B, D), lambda j: (0, 0)),
                  pl.BlockSpec((1, D), lambda j: (0, 0)),
                  pl.BlockSpec((1, D), lambda j: (0, 0)),
                  pl.BlockSpec((D, bn), lambda j: (0, j)),
                  pl.BlockSpec((1, bn), lambda j: (0, j)),
                  pl.BlockSpec((1, bn), lambda j: (0, j))],
        out_specs=pl.BlockSpec((B, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((B, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((B, D), x.dtype)],
        interpret=interpret_flag(impl),
    )(x, scale.reshape(1, D), bias.reshape(1, D), wqkv, ws, bq)


# ---------------------------------------------------------------------------
# flash_decode: q [B, H, Dh] x cache [B, Hkv, Smax, Dh] -> [B, H, Dh]
# ---------------------------------------------------------------------------

def _flash_decode_ref(q, kcache, vcache, pos, *, scale, alibi=False):
    """Masked dense attention over the whole cache (parity target).
    ``pos`` is a scalar or a per-row [B] vector of depths."""
    B, H, Dh = q.shape
    Hkv, Smax = kcache.shape[1], kcache.shape[2]
    rep = H // Hkv
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1), (B,))
    qf = q.astype(jnp.float32).reshape(B, Hkv, rep, Dh)
    kf = kcache.astype(jnp.float32)
    vf = vcache.astype(jnp.float32)
    s = jnp.einsum("bgrd,bgkd->bgrk", qf, kf) * scale
    key_pos = jnp.arange(Smax)
    if alibi:
        from deepspeed_tpu.models.layers import alibi_slopes

        rel = (key_pos[None, :] - pos[:, None]).astype(jnp.float32)
        s = s + (alibi_slopes(H).reshape(1, Hkv, rep, 1)
                 * rel[:, None, None, :])
    mask = key_pos[None, :] <= pos[:, None]
    s = jnp.where(mask[:, None, None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bgrk,bgkd->bgrd", p, vf)
    return o.reshape(B, H, Dh).astype(q.dtype)


def _flash_decode_kernel(pos_ref, q_ref, k_ref, v_ref, slope_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, scale, block, nb, rep,
                         hkv, alibi):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # grid axis 0 walks (batch, kv-head) pairs; each batch row has its own
    # position (continuous batching) — the scalar-prefetch buffer holds [B]
    pos = pos_ref[pl.program_id(0) // hkv]

    @pl.when(j * block <= pos)
    def _compute():
        q = q_ref[0].astype(jnp.float32)            # [rep, Dh]
        k = k_ref[0].astype(jnp.float32)            # [block, Dh]
        v = v_ref[0].astype(jnp.float32)            # [block, Dh]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        key_pos = j * block + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if alibi:
            s = s + slope_ref[0] * (key_pos - pos).astype(jnp.float32)
        s = jnp.where(key_pos <= pos, s, NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(j == nb - 1)
    def _finish():
        l = l_scr[:]
        o_ref[0] = (acc_scr[:] / jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def _alibi_or_zero_slopes(B, H, Hkv, rep, alibi):
    if alibi:
        from deepspeed_tpu.models.layers import alibi_slopes

        return jnp.tile(alibi_slopes(H).reshape(Hkv, rep, 1),
                        (B, 1, 1)).reshape(B * Hkv, rep, 1)
    return jnp.zeros((B * Hkv, rep, 1), jnp.float32)


def _flash_decode_paged_kernel(pos_ref, pt_ref, q_ref, k_ref, v_ref,
                               slope_ref, o_ref, m_scr, l_scr, acc_scr, **kw):
    # the page table is consumed by the index maps (it picks WHICH physical
    # page each block fetch DMAs); the in-kernel math is position-logical
    # and identical to the contiguous kernel
    del pt_ref
    _flash_decode_kernel(pos_ref, q_ref, k_ref, v_ref, slope_ref, o_ref,
                         m_scr, l_scr, acc_scr, **kw)


def _flash_decode_paged(q, kcache, vcache, pos, page_table, *, scale,
                        layer: Optional[int], alibi: bool, impl: str):
    """Decode attention over the PAGED pool (``serving/paged_kv.py``):
    caches [P, Hkv, page, Dh] (or stacked [L, P, Hkv, page, Dh] with
    ``layer=l``), ``page_table`` [B, maxp] int32 naming each row's
    physical page per logical block.  The kernel's DMA block IS the page:
    the block index map indirects through the scalar-prefetched table
    (``pt_ref[row, min(j, pos // page)]``), so each block-sized fetch
    lands on the right physical page and — exactly as in the contiguous
    kernel — blocks past each row's ``pos`` are neither fetched nor
    computed.  The XLA path gathers the logical per-slot view and runs
    the dense reference (the fallback for CPU tests and non-tile-aligned
    page sizes)."""
    B, H, Dh = q.shape
    kc = kcache if layer is None else kcache[layer]
    vc = vcache if layer is None else vcache[layer]
    Hkv, page = kc.shape[1], kc.shape[2]
    if impl == "xla" or page % 128:
        from deepspeed_tpu.models.decoding import paged_logical_view

        return _flash_decode_ref(q, paged_logical_view(kc, page_table),
                                 paged_logical_view(vc, page_table), pos,
                                 scale=scale, alibi=alibi)
    rep = H // Hkv
    maxp = page_table.shape[1]
    BG = B * Hkv
    q4 = q.reshape(BG, rep, Dh)
    if layer is None:
        P = kcache.shape[0]
        k3 = kcache.reshape(P * Hkv, page, Dh)
        v3 = vcache.reshape(P * Hkv, page, Dh)
        base = 0
    else:
        P = kcache.shape[1]
        k3 = kcache.reshape(kcache.shape[0] * P * Hkv, page, Dh)
        v3 = vcache.reshape(vcache.shape[0] * P * Hkv, page, Dh)
        base = layer * P * Hkv
    slopes = _alibi_or_zero_slopes(B, H, Hkv, rep, alibi)
    kernel = functools.partial(_flash_decode_paged_kernel, scale=scale,
                               block=page, nb=maxp, rep=rep, hkv=Hkv,
                               alibi=alibi)

    def page_map(b, j, pos_ref, pt_ref):
        row = b // Hkv
        jl = jnp.minimum(j, pos_ref[row] // page)   # per-row DMA clamp
        return base + pt_ref[row, jl] * Hkv + b % Hkv, 0, 0

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(BG, maxp),
        in_specs=[pl.BlockSpec((1, rep, Dh), lambda b, j, p, t: (b, 0, 0)),
                  pl.BlockSpec((1, page, Dh), page_map),
                  pl.BlockSpec((1, page, Dh), page_map),
                  pl.BlockSpec((1, rep, 1), lambda b, j, p, t: (b, 0, 0))],
        out_specs=pl.BlockSpec((1, rep, Dh), lambda b, j, p, t: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((rep, 1), jnp.float32),
                        pltpu.VMEM((rep, 1), jnp.float32),
                        pltpu.VMEM((rep, Dh), jnp.float32)],
    )
    o = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((BG, rep, Dh), q.dtype),
        interpret=interpret_flag(impl),
    )(pos, page_table.astype(jnp.int32), q4, k3, v3, slopes)
    return o.reshape(B, H, Dh)


def flash_decode(q, kcache, vcache, pos, *, sm_scale: Optional[float] = None,
                 block: int = 256, layer: Optional[int] = None,
                 alibi: bool = False, impl: Optional[str] = None,
                 page_table=None):
    """Single-launch decode attention.  q: [B, H, Dh]; caches:
    [B, Hkv, Smax, Dh] — or, with ``layer=l``, stacked [L, B, Hkv, Smax, Dh]
    read at static layer offset ``l`` through the index map (no cache slice
    materializes); ``pos`` the (traced) absolute position of the query — a
    scalar shared by the batch, or an int32 [B] vector of per-row depths
    (continuous batching: each slot masks and clamps independently).
    ``page_table`` [B, maxp] switches to the paged pool layout
    ([P, Hkv, page, Dh] physical pages; see :func:`_flash_decode_paged`).

    The block index map clamps to the position's block PER ROW, so cache
    blocks past each row's ``pos`` are neither fetched nor computed — the
    single-kernel form of the length-aware flash-decode loop (reference:
    ``(R) softmax.cu`` + attention in the inference workspace)."""
    impl = resolve_impl(impl)
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32).reshape(-1),
                           (q.shape[0],))
    if page_table is not None:
        return _flash_decode_paged(q, kcache, vcache, pos, page_table,
                                   scale=scale, layer=layer, alibi=alibi,
                                   impl=impl)
    if layer is None:
        kc, vc = kcache, vcache
        off = 0
    else:
        kc, vc = kcache[layer], vcache[layer]
        off = layer  # the xla path slices; the pallas path offsets the map
    Smax = kc.shape[2]
    # odd cache lengths (not a block multiple) would hand the kernel a
    # non-tile-aligned block — route them to the dense reference, the same
    # policy the unfused decode uses for small caches
    if impl == "xla" or Smax % block:
        return _flash_decode_ref(q, kc, vc, pos, scale=scale, alibi=alibi)
    B, H, Dh = q.shape
    Hkv = kc.shape[1]
    rep = H // Hkv
    blk = block
    nb = Smax // blk
    slopes = _alibi_or_zero_slopes(B, H, Hkv, rep, alibi)
    BG = B * Hkv
    q4 = q.reshape(BG, rep, Dh)
    if layer is None:
        k3 = kcache.reshape(BG, Smax, Dh)
        v3 = vcache.reshape(BG, Smax, Dh)
    else:
        k3 = kcache.reshape(kcache.shape[0] * BG, Smax, Dh)
        v3 = vcache.reshape(vcache.shape[0] * BG, Smax, Dh)
    base = off * BG
    kernel = functools.partial(_flash_decode_kernel, scale=scale, block=blk,
                               nb=nb, rep=rep, hkv=Hkv, alibi=alibi)
    # index maps see scalar-prefetch refs AFTER the grid indices (the kernel
    # body sees them first); b // Hkv recovers the batch row, whose own
    # position bounds the DMA clamp (per-row length awareness)
    clamp = lambda b, j, pos_ref: (base + b,
                                   jnp.minimum(j, pos_ref[b // Hkv] // blk), 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(BG, nb),
        in_specs=[pl.BlockSpec((1, rep, Dh), lambda b, j, pos_ref: (b, 0, 0)),
                  pl.BlockSpec((1, blk, Dh), clamp),
                  pl.BlockSpec((1, blk, Dh), clamp),
                  pl.BlockSpec((1, rep, 1), lambda b, j, pos_ref: (b, 0, 0))],
        out_specs=pl.BlockSpec((1, rep, Dh), lambda b, j, pos_ref: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((rep, 1), jnp.float32),
                        pltpu.VMEM((rep, 1), jnp.float32),
                        pltpu.VMEM((rep, Dh), jnp.float32)],
    )
    o = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((BG, rep, Dh), q.dtype),
        interpret=interpret_flag(impl),
    )(pos, q4, k3, v3, slopes)
    return o.reshape(B, H, Dh)


# ---------------------------------------------------------------------------
# fused_proj_norm: ctx @ wo (+bo) + resid -> r; norm(r | resid) -> h
# ---------------------------------------------------------------------------

def _proj_norm_ref(ctx, resid, wo, bo, scale, bias, *, kind, eps, parallel,
                   wscale=None):
    if wscale is not None:
        wo = _deq(wo, wscale.reshape(1, -1), ctx.dtype)
    o = jax.lax.dot_general(ctx, wo, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if bo is not None:
        o = o + bo.astype(jnp.float32)
    r32 = resid.astype(jnp.float32) + o
    nsrc = resid.astype(jnp.float32) if parallel else r32
    h = _normalize(nsrc, scale.astype(jnp.float32),
                   bias.astype(jnp.float32), kind, eps)
    return r32.astype(ctx.dtype), h.astype(ctx.dtype)


def _proj_norm_kernel(ctx_ref, res_ref, wo_ref, ws_ref, bo_ref, s_ref, b_ref,
                      r_ref, h_ref, *, kind, eps, parallel, has_bias, quant):
    wo = _deq(wo_ref[:], ws_ref[:], ctx_ref.dtype) if quant else wo_ref[:]
    o = jax.lax.dot_general(ctx_ref[:], wo, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if has_bias:
        o = o + bo_ref[:].astype(jnp.float32)
    res32 = res_ref[:].astype(jnp.float32)
    r32 = res32 + o
    nsrc = res32 if parallel else r32
    h = _normalize(nsrc, s_ref[:].astype(jnp.float32),
                   b_ref[:].astype(jnp.float32), kind, eps)
    r_ref[:] = r32.astype(r_ref.dtype)
    h_ref[:] = h.astype(h_ref.dtype)


def fused_proj_norm(ctx, resid, wo, bo=None, scale=None, bias=None, *,
                    kind: str = "layernorm", eps: float = 1e-5,
                    parallel: bool = False, wscale=None,
                    impl: Optional[str] = None):
    """ctx: [B, M]; wo: [M, D]; resid: [B, D].  Returns (r, h): the updated
    residual stream and the normed MLP input (``parallel=True`` norms the
    layer input instead — gpt-neox parallel residual).  ``wscale`` marks
    ``wo`` as int8 (dequant in-kernel).

    Reference: ``(R) pt_binding.cpp`` residual+bias fusion after the
    attention out-GEMM plus the next block's norm."""
    impl = resolve_impl(impl)
    if bias is None:
        bias = jnp.zeros_like(scale)
    if impl == "xla":
        return _proj_norm_ref(ctx, resid, wo, bo, scale, bias,
                              kind=kind, eps=eps, parallel=parallel,
                              wscale=wscale)
    B, M = ctx.shape
    D = wo.shape[1]
    quant = wscale is not None
    has_bias = bo is not None
    bo2 = (bo if has_bias else jnp.zeros((D,), ctx.dtype)).reshape(1, D)
    ws = (wscale if quant else jnp.ones((D,), jnp.float32)).reshape(1, D)
    kernel = functools.partial(_proj_norm_kernel, kind=kind, eps=eps,
                               parallel=parallel, has_bias=has_bias,
                               quant=quant)
    r, h = pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec((B, M), lambda: (0, 0)),
                  pl.BlockSpec((B, D), lambda: (0, 0)),
                  pl.BlockSpec((M, D), lambda: (0, 0)),
                  pl.BlockSpec((1, D), lambda: (0, 0)),
                  pl.BlockSpec((1, D), lambda: (0, 0)),
                  pl.BlockSpec((1, D), lambda: (0, 0)),
                  pl.BlockSpec((1, D), lambda: (0, 0))],
        out_specs=[pl.BlockSpec((B, D), lambda: (0, 0)),
                   pl.BlockSpec((B, D), lambda: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((B, D), ctx.dtype),
                   jax.ShapeDtypeStruct((B, D), ctx.dtype)],
        interpret=interpret_flag(impl),
    )(ctx, resid, wo, ws, bo2, scale.reshape(1, D), bias.reshape(1, D))
    return r, h


# ---------------------------------------------------------------------------
# fused_mlp: h @ w_up (* act(h @ w_gate)) @ w_down + r, blocked over FFN dim
# ---------------------------------------------------------------------------

def _mlp_ref(h, r, w_up, w_gate, w_down, b_up, b_gate, b_down, *, act,
             wscales=None):
    if wscales is not None:
        su, sg, sd = wscales
        w_up = _deq(w_up, su.reshape(1, -1), h.dtype)
        w_down = _deq(w_down, sd.reshape(1, -1), h.dtype)
        if w_gate is not None:
            w_gate = _deq(w_gate, sg.reshape(1, -1), h.dtype)
    up = jax.lax.dot_general(h, w_up, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if b_up is not None:
        up = up + b_up.astype(jnp.float32)
    if w_gate is not None:
        g = jax.lax.dot_general(h, w_gate, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if b_gate is not None:
            g = g + b_gate.astype(jnp.float32)
        a = _act(act, g) * up
    else:
        a = _act(act, up)
    y = jax.lax.dot_general(a.astype(h.dtype), w_down,
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if b_down is not None:
        y = y + b_down.astype(jnp.float32)
    return (r.astype(jnp.float32) + y).astype(h.dtype)


def _mlp_kernel(h_ref, r_ref, wu_ref, wg_ref, wd_ref, su_ref, sg_ref,
                sd_ref, bu_ref, bg_ref, bd_ref, o_ref, acc_scr, *, act, glu,
                has_bias, nf, quant):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        acc_scr[:] = r_ref[:].astype(jnp.float32)
        if has_bias:
            acc_scr[:] += bd_ref[:].astype(jnp.float32)

    h = h_ref[:]
    wu = _deq(wu_ref[:], su_ref[:], h.dtype) if quant else wu_ref[:]
    up = jax.lax.dot_general(h, wu, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if has_bias:
        up = up + bu_ref[:].astype(jnp.float32)
    if glu:
        wg = _deq(wg_ref[:], sg_ref[:], h.dtype) if quant else wg_ref[:]
        g = jax.lax.dot_general(h, wg, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if has_bias:
            g = g + bg_ref[:].astype(jnp.float32)
        a = _act(act, g) * up
    else:
        a = _act(act, up)
    wd = _deq(wd_ref[:], sd_ref[:], h.dtype) if quant else wd_ref[:]
    acc_scr[:] += jax.lax.dot_general(a.astype(h.dtype), wd,
                                      (((1,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)

    @pl.when(j == nf - 1)
    def _finish():
        o_ref[:] = acc_scr[:].astype(o_ref.dtype)


def fused_mlp(h, r, w_up, w_down, w_gate=None, b_up=None, b_gate=None,
              b_down=None, *, act: str = "gelu", wscales=None,
              impl: Optional[str] = None):
    """h: [B, D] (normed); r: [B, D] (residual).  Returns r + mlp(h).
    ``wscales`` = (up, gate, down) per-out-channel fp32 scales marking the
    weights as int8 (dequant in-kernel; gate entry ignored when no GLU).

    Blocked over the FFN dim: grid step j computes the partial product of
    FFN slice j and accumulates the down-projection into a VMEM scratch, so
    the weight working set is one tile per matrix (reference: the inference
    MLP gemm chain with fused bias+activation epilogues)."""
    impl = resolve_impl(impl)
    if impl == "xla":
        return _mlp_ref(h, r, w_up, w_gate, w_down, b_up, b_gate, b_down,
                        act=act, wscales=wscales)
    B, D = h.shape
    F = w_up.shape[1]
    quant = wscales is not None
    per = 3 if w_gate is not None else 2
    # see fused_norm_qkv: quant blocks sized by the fp32 dequant intermediate
    bf = _col_block(D * per, F, 4 if quant else w_up.dtype.itemsize)
    glu = w_gate is not None
    has_bias = b_up is not None
    wdt = h.dtype if not quant else jnp.int8
    wg = w_gate if glu else jnp.zeros((D, bf), wdt)
    bu2 = (b_up if has_bias else jnp.zeros((F,), h.dtype)).reshape(1, F)
    bg2 = (b_gate if (glu and has_bias and b_gate is not None)
           else jnp.zeros((F,), h.dtype)).reshape(1, F)
    bd2 = (b_down if has_bias and b_down is not None
           else jnp.zeros((D,), h.dtype)).reshape(1, D)
    if quant:
        su, sg, sd = wscales
        su2 = su.reshape(1, F)
        sg2 = (sg.reshape(1, F) if glu else jnp.ones((1, bf), jnp.float32))
        sd2 = sd.reshape(1, D)
    else:
        su2 = jnp.ones((1, F), jnp.float32)
        sg2 = jnp.ones((1, F if glu else bf), jnp.float32)
        sd2 = jnp.ones((1, D), jnp.float32)
    kernel = functools.partial(_mlp_kernel, act=act, glu=glu,
                               has_bias=has_bias, nf=F // bf, quant=quant)
    gate_spec = (pl.BlockSpec((D, bf), lambda j: (0, j)) if glu
                 else pl.BlockSpec((D, bf), lambda j: (0, 0)))
    gate_s_spec = (pl.BlockSpec((1, bf), lambda j: (0, j)) if glu
                   else pl.BlockSpec((1, bf), lambda j: (0, 0)))
    return pl.pallas_call(
        kernel,
        grid=(F // bf,),
        in_specs=[pl.BlockSpec((B, D), lambda j: (0, 0)),
                  pl.BlockSpec((B, D), lambda j: (0, 0)),
                  pl.BlockSpec((D, bf), lambda j: (0, j)),
                  gate_spec,
                  pl.BlockSpec((bf, D), lambda j: (j, 0)),
                  pl.BlockSpec((1, bf), lambda j: (0, j)),
                  gate_s_spec,
                  pl.BlockSpec((1, D), lambda j: (0, 0)),
                  pl.BlockSpec((1, bf), lambda j: (0, j)),
                  pl.BlockSpec((1, bf), lambda j: (0, j)),
                  pl.BlockSpec((1, D), lambda j: (0, 0))],
        out_specs=pl.BlockSpec((B, D), lambda j: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, D), h.dtype),
        scratch_shapes=[pltpu.VMEM((B, D), jnp.float32)],
        interpret=interpret_flag(impl),
    )(h, r, w_up, wg, w_down, su2, sg2, sd2, bu2, bg2, bd2)
