"""Fused int8-state Adam update kernel (+ stochastic rounding).

The Adam8bit optimizer (ops/adam/adam8bit.py) stores m/v as int8 blocks
with per-block scales.  Composed as jnp ops, the dequant -> moment update
-> requant -> stochastic-round chain compiles to a slow many-pass program
(measured ~1000x below TPU capability at 1.3B params); this kernel does the
whole update in ONE VMEM pass per tile — the exact role the reference's
fused ``multi_tensor_adam.cu`` + quantization kernels play (SURVEY.md §2.2
rows "Fused Adam", "Quantizer kernels").

Per [rows, block] tile: dequant m/v (sqrt-space v), Adam moment update,
bias-corrected AdamW direction, per-row absmax requant, and — for bf16
params — stochastic rounding via the on-core PRNG (``pltpu.prng_seed`` /
``prng_random_bits``): add uniform bits below the truncated mantissa,
truncate, store bf16.  fp32 math throughout; int8/bf16 I/O only.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.common import interpret_flag, resolve_impl

ROW_MULT = 32  # int8 sublane tile; nb is padded to a multiple of this
XLA_CHUNK_ELEMS = 1 << 25  # fp32-temporary bound per chunk in the xla fallback


def _kernel(c1_ref, c2_ref, lr_ref, seed_ref, p_ref, g_ref, mq_ref, ms_ref,
            vq_ref, vs_ref, p_out, mq_out, ms_out, vq_out, vs_out, *,
            b1, b2, eps, wd, sr):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = mq_ref[:].astype(jnp.float32) * ms_ref[:]
    rv = vq_ref[:].astype(jnp.float32) * vs_ref[:]
    v = rv * rv                               # sqrt-space storage
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * g * g
    update = (m * c1_ref[0]) / (jnp.sqrt(v * c2_ref[0]) + eps) + wd * p
    new = p - lr_ref[0] * update

    def requant(x, q_out, s_out):
        absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
        q_out[:] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
        s_out[:] = scale

    requant(m, mq_out, ms_out)
    requant(jnp.sqrt(v), vq_out, vs_out)
    if sr:
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        bits = pltpu.prng_random_bits(new.shape).astype(jnp.int32)
        u = jax.lax.bitcast_convert_type(new, jnp.int32)
        u = (u + (bits & 0xFFFF)) & jnp.int32(~0xFFFF)
        new = jax.lax.bitcast_convert_type(u, jnp.float32)
    p_out[:] = new.astype(p_out.dtype)


def fused_adam8bit_update(p2d, g2d, mq, ms, vq, vs, c1, c2, lr, seed, *,
                          b1: float, b2: float, eps: float, wd: float,
                          sr: bool, impl: Optional[str] = None):
    """One fused step over a [nb, block] view of a leaf.

    ``p2d``/``g2d``: [nb, block] param/grad views; ``mq``/``vq``: int8
    [nb, block]; ``ms``/``vs``: fp32 [nb, 1]; ``c1``/``c2``: bias-correction
    factors 1/(1-beta^t); ``seed``: i32 scalar for the SR stream.  Returns
    (new_p [nb, block] in p2d.dtype, mq', ms', vq', vs').
    """
    nb, block = p2d.shape
    assert nb % ROW_MULT == 0, (nb, ROW_MULT)
    impl = resolve_impl(impl)
    if impl == "xla":
        def xla_step(p_c, g_c, mq_c, ms_c, vq_c, vs_c, seed_c):
            m = mq_c.astype(jnp.float32) * ms_c
            v = jnp.square(vq_c.astype(jnp.float32) * vs_c)
            g = g_c.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * g * g
            p = p_c.astype(jnp.float32)
            new = p - lr * ((m * c1) / (jnp.sqrt(v * c2) + eps) + wd * p)

            def requant(x):  # shared quantizer: same semantics as the kernel
                from deepspeed_tpu.ops.pallas.quantizer import quantize

                q, scale, _pad = quantize(x, bits=8, block=block, impl="xla")
                return q, scale[:, None]

            mq2, ms2 = requant(m)
            vq2, vs2 = requant(jnp.sqrt(v))
            if sr and p_c.dtype == jnp.bfloat16:
                from deepspeed_tpu.ops.adam.adam8bit import stochastic_round_bf16

                key = jax.random.fold_in(jax.random.PRNGKey(0), seed_c)
                new_p = stochastic_round_bf16(new, key)
            else:
                new_p = new.astype(p_c.dtype)
            return new_p, mq2, ms2, vq2, vs2

        # Bound fp32 temporaries to ~XLA_CHUNK_ELEMS per chunk: this debug
        # path must not reintroduce whole-leaf fp32 copies (a >1B model's
        # stacked-layers leaf is ~278M elements; ~6 fp32 temporaries of
        # that is ~7GB — an instant OOM on a 16GB chip).
        chunk_rows = max(ROW_MULT, XLA_CHUNK_ELEMS // block)
        if nb <= chunk_rows:
            return xla_step(p2d, g2d, mq, ms, vq, vs, seed)
        S = -(-nb // chunk_rows)
        pad_rows = S * chunk_rows - nb

        def padr(x):
            return jnp.pad(x, ((0, pad_rows), (0, 0))).reshape(
                S, chunk_rows, x.shape[1])

        xs = (padr(p2d), padr(g2d), padr(mq), padr(ms), padr(vq), padr(vs),
              seed + jnp.arange(S, dtype=jnp.int32) * jnp.int32(7919))
        outs = jax.lax.map(lambda t: xla_step(*t), xs)
        return tuple(o.reshape(S * chunk_rows, -1)[:nb] for o in outs)

    rows = min(256, nb)
    while nb % rows:
        rows //= 2
    grid = nb // rows
    tile = pl.BlockSpec((rows, block), lambda i, *_: (i, 0))
    stile = pl.BlockSpec((rows, 1), lambda i, *_: (i, 0))
    kernel = functools.partial(_kernel, b1=b1, b2=b2, eps=eps, wd=wd,
                               sr=bool(sr and p2d.dtype == jnp.bfloat16))
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=(grid,),
            in_specs=[tile, tile, tile, stile, tile, stile],
            out_specs=[tile, tile, stile, tile, stile],
        ),
        out_shape=[jax.ShapeDtypeStruct((nb, block), p2d.dtype),
                   jax.ShapeDtypeStruct((nb, block), jnp.int8),
                   jax.ShapeDtypeStruct((nb, 1), jnp.float32),
                   jax.ShapeDtypeStruct((nb, block), jnp.int8),
                   jax.ShapeDtypeStruct((nb, 1), jnp.float32)],
        interpret=interpret_flag(impl),
    )(jnp.asarray([c1], jnp.float32), jnp.asarray([c2], jnp.float32),
      jnp.asarray([lr], jnp.float32), jnp.asarray([seed], jnp.int32),
      p2d, g2d, mq, ms, vq, vs)
