"""Fused LAMB update kernel.

TPU-native replacement for ``csrc/lamb/fused_lamb_cuda_kernel.cu``
(SURVEY.md §2.2 "Fused LAMB"): LAMB = Adam moments + a per-TENSOR trust
ratio ||p|| / ||update|| scaling the learning rate.  The reference's
two-phase CUDA reduction maps to two Pallas passes:

1. moment update + squared-norm partial reduction per grid block (one read
   of p/g/m/v, writes m/v and the un-scaled update, accumulates norms in a
   scratch accumulator);
2. a tiny scalar combine (XLA) producing the trust ratio, then one fused
   scale-and-apply pass over the update.

The norm reductions ride in the same kernel pass as the moment update, so
p/g/m/v are read exactly once — the part XLA does not fuse on its own is
exactly this cross-pass reuse.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.common import interpret_flag, resolve_impl

_LANE = 128
_BLOCK = 64 * 1024


def _lamb_phase1_kernel(c1_ref, c2_ref, p_ref, g_ref, m_ref, v_ref,
                        u_out, m_out, v_out, norms_out, acc, *, beta1, beta2,
                        eps, weight_decay):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m_new = beta1 * m_ref[:] + (1.0 - beta1) * g
    v_new = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    u = (m_new * c1_ref[0]) / (jnp.sqrt(v_new * c2_ref[0]) + eps)
    if weight_decay != 0.0:
        u = u + weight_decay * p
    u_out[:] = u
    m_out[:] = m_new
    v_out[:] = v_new
    acc[0, 0] += jnp.sum(p * p)
    acc[0, 1] += jnp.sum(u * u)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finish():
        norms_out[:] = acc[:]


def _scale_kernel(s_ref, p_ref, u_ref, p_out):
    p_out[:] = (p_ref[:].astype(jnp.float32)
                - s_ref[0] * u_ref[:]).astype(p_out.dtype)


def fused_lamb_update(param, grad, m, v, step, *, lr, beta1: float = 0.9,
                      beta2: float = 0.999, eps: float = 1e-6,
                      weight_decay: float = 0.0, impl: Optional[str] = None):
    """Single-tensor fused LAMB step.  Returns (new_param, new_m, new_v)."""
    impl = resolve_impl(impl)
    stepf = step.astype(jnp.float32)
    c1 = 1.0 / (1.0 - beta1 ** stepf)
    c2 = 1.0 / (1.0 - beta2 ** stepf)
    if impl == "xla":
        p = param.astype(jnp.float32)
        g = grad.astype(jnp.float32)
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * g * g
        u = (m_new * c1) / (jnp.sqrt(v_new * c2) + eps)
        if weight_decay != 0.0:
            u = u + weight_decay * p
        w_norm = jnp.linalg.norm(p)
        u_norm = jnp.linalg.norm(u)
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        return (p - lr * trust * u).astype(param.dtype), m_new, v_new

    orig_shape = param.shape
    n = param.size
    pad = (-n) % _LANE

    def flat(x):
        xf = x.reshape(-1)
        if pad:
            xf = jnp.pad(xf, (0, pad))
        return xf.reshape(-1, _LANE)

    pf, gf, mf, vf = flat(param), flat(grad), flat(m), flat(v)
    rows = pf.shape[0]
    block_rows = min(rows, _BLOCK // _LANE)
    while rows % block_rows:
        block_rows //= 2
    block_rows = max(1, block_rows)
    grid = rows // block_rows
    bspec = pl.BlockSpec((block_rows, _LANE), lambda i, *_: (i, 0))
    nspec = pl.BlockSpec((1, _LANE), lambda i, *_: (0, 0))
    kernel = functools.partial(_lamb_phase1_kernel, beta1=beta1, beta2=beta2,
                               eps=eps, weight_decay=weight_decay)
    u, m_new, v_new, norms = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2, grid=(grid,),
            in_specs=[bspec, bspec, bspec, bspec],
            out_specs=[bspec, bspec, bspec, nspec],
            scratch_shapes=[pltpu.VMEM((1, _LANE), jnp.float32)]),
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((1, _LANE), jnp.float32)],
        interpret=interpret_flag(impl),
    )(jnp.asarray([c1], jnp.float32), jnp.asarray([c2], jnp.float32),
      pf, gf, mf, vf)
    w_norm = jnp.sqrt(norms[0, 0])
    u_norm = jnp.sqrt(norms[0, 1])
    trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
    scale = jnp.asarray([lr], jnp.float32) * trust
    p_new = pl.pallas_call(
        _scale_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(grid,),
            in_specs=[bspec, bspec], out_specs=bspec),
        out_shape=jax.ShapeDtypeStruct((rows, _LANE), param.dtype),
        interpret=interpret_flag(impl),
    )(scale.reshape(1), pf, u)
    unflat = lambda x: x.reshape(-1)[:n].reshape(orig_shape)
    return unflat(p_new), unflat(m_new), unflat(v_new)


class FusedLambState(NamedTuple):
    count: jnp.ndarray
    mu: any
    nu: any


def fused_lamb(learning_rate, *, beta1: float = 0.9, beta2: float = 0.999,
               eps: float = 1e-6, weight_decay: float = 0.0,
               impl: Optional[str] = None) -> optax.GradientTransformation:
    """optax-style transformation over the fused LAMB kernel (the engine's
    optimizer contract; reference: ``FusedLamb``)."""

    def init_fn(params):
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return FusedLambState(count=jnp.zeros((), jnp.int32),
                              mu=jax.tree.map(zeros, params),
                              nu=jax.tree.map(zeros, params))

    def update_fn(grads, state, params=None):
        assert params is not None, "fused_lamb needs params"
        count = state.count + 1
        lr = (learning_rate(count) if callable(learning_rate)
              else learning_rate)

        new_p, new_mu, new_nu = {}, {}, {}
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(state.mu)
        flat_v = jax.tree_util.tree_leaves(state.nu)
        outs = [fused_lamb_update(p, g, m, v, count, lr=lr, beta1=beta1,
                                  beta2=beta2, eps=eps,
                                  weight_decay=weight_decay, impl=impl)
                for p, g, m, v in zip(flat_p, flat_g, flat_m, flat_v)]
        unflat = lambda i: jax.tree_util.tree_unflatten(treedef,
                                                        [o[i] for o in outs])
        new_params = unflat(0)
        updates = jax.tree.map(lambda new, old: new - old.astype(new.dtype),
                               new_params, params)
        return updates, FusedLambState(count=count, mu=unflat(1), nu=unflat(2))

    return optax.GradientTransformation(init_fn, update_fn)
