"""Block quantization kernels.

Reference: ``csrc/quantization/{quantize,dequantize,quant_reduce}.cu``
(SURVEY.md §2.2 "Quantizer kernels"): symmetric/asymmetric block int8/int4
quant + dequant.  The Pallas kernel computes the per-block absmax and the
quantized payload in ONE pass over the data (the fused form the CUDA
kernels exist for); dequant is a single scaled cast.  int4 packs two codes
per int8 byte.  The quantized-collective layer
(``runtime/comm/quantized.py``) and the compression QAT path are the
consumers.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.common import interpret_flag, resolve_impl

_LANE = 128


def _quant_kernel(x_ref, q_ref, scale_ref, *, qmax):
    x = x_ref[:].astype(jnp.float32)                 # [1, block]
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
    q_ref[:] = jnp.clip(jnp.round(x / scale), -qmax, qmax).astype(jnp.int8)
    scale_ref[:] = jnp.broadcast_to(scale, scale_ref.shape)


def quantize(x, bits: int = 8, block: int = 2048,
             impl: Optional[str] = None) -> Tuple[jnp.ndarray, jnp.ndarray, int]:
    """Symmetric per-block quantization in one fused pass.

    Returns (q int8 [nblocks, block], scale fp32 [nblocks], pad).  For
    ``bits=4`` the codes span [-7, 7] (packing to nibbles is the caller's
    transport concern; see :func:`pack_int4`).
    """
    assert bits in (8, 4), bits
    qmax = 127.0 if bits == 8 else 7.0
    impl = resolve_impl(impl)
    n = x.size
    block = min(block, 1 << 16)
    if impl != "xla":
        # the Pallas kernel tiles on 128 lanes; the XLA path honors any
        # caller granularity (quantized collectives use small blocks)
        block = max(_LANE, block)
    pad = (-n) % block
    flat = x.reshape(-1).astype(jnp.float32)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    blocks = flat.reshape(-1, block)
    nb = blocks.shape[0]
    if impl == "xla":
        absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
        scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
        q = jnp.clip(jnp.round(blocks / scale), -qmax, qmax).astype(jnp.int8)
        return q, scale[:, 0], pad
    q, scale = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax),
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, block), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, block), lambda i: (i, 0)),
                   pl.BlockSpec((1, _LANE), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((nb, block), jnp.int8),
                   jax.ShapeDtypeStruct((nb, _LANE), jnp.float32)],
        interpret=interpret_flag(impl),
    )(blocks)
    return q, scale[:, 0], pad


def dequantize(q, scale, pad: int, shape, dtype=jnp.float32):
    """Inverse of :func:`quantize` (scaled cast — XLA fuses it into the
    consumer, matching the reference's fused dequant epilogues)."""
    out = (q.astype(jnp.float32) * scale[:, None]).reshape(-1)
    if pad:
        out = out[: out.size - pad]
    return out.reshape(shape).astype(dtype)


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """int8 codes in [-7, 7] -> packed uint8 (two nibbles/byte)."""
    flat = q.reshape(-1)
    if flat.size % 2:
        flat = jnp.concatenate([flat, jnp.zeros((1,), jnp.int8)])
    pairs = (flat.astype(jnp.int32) + 8).reshape(-1, 2)
    return (pairs[:, 0] | (pairs[:, 1] << 4)).astype(jnp.uint8)


def unpack_int4(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    lo = (packed.astype(jnp.int32) & 0xF) - 8
    hi = ((packed.astype(jnp.int32) >> 4) & 0xF) - 8
    return jnp.stack([lo, hi], axis=-1).reshape(-1)[:n].astype(jnp.int8)
