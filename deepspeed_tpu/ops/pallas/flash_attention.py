"""Blockwise (flash) attention Pallas kernels, forward + backward.

TPU-native replacement for the reference's fused attention path
(``csrc/transformer/softmax_kernels.cu`` + strided-batch GEMM attention in
``csrc/includes/strided_batch_gemm.h``, and the inference ``softmax.cu``;
SURVEY.md §2.2): instead of materializing the [S, S] score matrix between two
cuBLAS GEMMs, the kernel streams KV blocks through VMEM with an online
softmax, so memory is O(S·D) and the MXU sees back-to-back matmuls.

Layout: q, k, v are [B, H, S, D].  Causal masking supported; optional
additive bias (e.g. ALiBi) can be folded by the caller via the bias arg of the
jnp reference for now.  All softmax math in fp32 (matching the reference
kernels' accumulation).

The TPU grid executes sequentially with the last axis fastest, so the KV-block
axis is the innermost grid dimension and the running (m, l, acc) state lives
in VMEM scratch across those grid steps — the Pallas-idiomatic form of the
flash-attention inner loop.

Backward follows the standard recompute scheme: saved LSE from forward;
``delta = rowsum(dO ∘ O)``; one kernel accumulates dQ over KV blocks, another
accumulates dK/dV over Q blocks.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.common import interpret_flag, pick_block, resolve_impl

# 512-token tiles: 8× fewer grid steps than 128 and MXU-shaped [512, 512]
# score matmuls; VMEM per step stays < 4MB at D=128. Measured 3× faster than
# 128-tiles on v5e at S=1024 (see bench notes in git history).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


# ---------------------------------------------------------------------------
# jnp reference (parity target + CPU path)
# ---------------------------------------------------------------------------

def mha_reference(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
                  bias=None, probs_transform=None, pv_dtype=None):
    """jnp attention; ``probs_transform`` hooks the post-softmax
    probabilities (e.g. attention dropout in the fused transformer layer);
    ``pv_dtype`` sets the probs@V matmul precision (default fp32 — the
    parity-reference contract; pass the compute dtype for MXU-rate serving
    of the masked path)."""
    *_, S, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if bias is not None:
        logits = logits + bias
    if causal:
        Sk = k.shape[-2]
        mask = jnp.tril(jnp.ones((S, Sk), bool), k=Sk - S)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if probs_transform is not None:
        probs = probs_transform(probs)
    pv = pv_dtype if pv_dtype is not None else jnp.float32
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(pv),
                      v.astype(pv)).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, slope_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, alibi, block_q,
                block_k, nk):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    qb = pl.program_id(1)
    q_start = qb * block_q
    k_start = kb * block_k

    run = True
    if causal:
        # whole KV block strictly above the diagonal -> nothing to do
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        # MXU matmuls take the native (bf16) operands; only the accumulator
        # and softmax statistics are fp32 — fp32 MXU inputs would quarter
        # throughput for no accuracy gain over fp32 accumulation.
        q = q_ref[0]  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]  # [BK, D]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal or alibi:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if alibi:
            s = s + slope_ref[0, 0, 0] * (cols - rows).astype(jnp.float32)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_scr[:]                              # [BQ, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                         # [BQ, BK]
        alpha = jnp.exp(m_prev - m_new)                # [BQ, 1]
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(kb == nk - 1)
    def _finish():
        l = l_scr[:]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse layout (BH, S, 1): the in-kernel block is the (bq, 1) column
        # vector itself — no relayout needed (see module docstring).
        lse_ref[0] = m_scr[:] + jnp.log(safe_l)


def _head_slopes(B: int, H: int, alibi: bool):
    """[B*H, 1, 1] per-grid-row ALiBi slopes (zeros when off — the argument
    shape must be static for the shared kernel signature).  3-D so the
    block's LAST TWO dims are full-size: Mosaic requires partial block dims
    in the last two positions to be (8, 128)-tile aligned."""
    if not alibi:
        return jnp.zeros((B * H, 1, 1), jnp.float32)
    from deepspeed_tpu.models.layers import alibi_slopes

    return jnp.tile(alibi_slopes(H), B).reshape(B * H, 1, 1)


def _flash_fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret):
    B, H, S, D = q.shape
    Sk = k.shape[2]
    bq = pick_block(S, block_q, minimum=8)
    bk = pick_block(Sk, block_k, minimum=8)
    nq, nk = S // bq, Sk // bk
    BH = B * H
    q3 = q.reshape(BH, S, D)
    k3 = k.reshape(BH, Sk, D)
    v3 = v.reshape(BH, Sk, D)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               alibi=alibi, block_q=bq, block_k=bk, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
                  pl.BlockSpec((1, 1, 1), lambda b, i, j: (b, 0, 0))],
        out_specs=[pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, S, D), q.dtype),
                   jax.ShapeDtypeStruct((BH, S, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, _head_slopes(B, H, alibi))
    return o.reshape(B, H, S, D), lse.reshape(B, H, S)


def _col(x_ref):
    """Read a (1, bq, 1) stat block as a (bq, 1) column."""
    return x_ref[0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, slope_ref,
                   dq_ref, dq_scr, *, scale, causal, alibi, block_q, block_k,
                   nk):
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    q_start = pl.program_id(1) * block_q
    k_start = kb * block_k
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or alibi:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if alibi:
            s = s + slope_ref[0, 0, 0] * (cols - rows).astype(jnp.float32)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(kb == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    slope_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                    causal, alibi, block_q, block_k, nq):
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    q_start = qb * block_q
    k_start = pl.program_id(1) * block_k
    run = True
    if causal:
        # whole Q block strictly left of the diagonal -> no grad flows here
        run = q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or alibi:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if alibi:
            s = s + slope_ref[0, 0, 0] * (cols - rows).astype(jnp.float32)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                     # [BQ, BK]
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)          # [BQ, BK]
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qb == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, causal, alibi, scale, block_q, block_k, interpret):
    q, k, v, o, lse = res
    B, H, S, D = q.shape
    Sk = k.shape[2]
    bq = pick_block(S, block_q, minimum=8)
    bk = pick_block(Sk, block_k, minimum=8)
    nq, nk = S // bq, Sk // bk
    BH = B * H
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,S]
    q3, k3, v3 = (t.reshape(BH, -1, D) for t in (q, k, v))
    do3 = g.reshape(BH, S, D)
    lse3 = lse.reshape(BH, S, 1)
    delta3 = delta.reshape(BH, S, 1)
    slopes = _head_slopes(B, H, alibi)
    slope_spec = pl.BlockSpec((1, 1, 1), lambda b, i, j: (b, 0, 0))

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                  alibi=alibi, block_q=bq, block_k=bk, nk=nk)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(BH, nq, nk),
        in_specs=[pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, 0)),
                  pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
                  pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
                  pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0)),
                  slope_spec],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3, slopes)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                                   alibi=alibi, block_q=bq, block_k=bk, nq=nq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(BH, nk, nq),
        in_specs=[pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
                  pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
                  pl.BlockSpec((1, bq, D), lambda b, j, i: (b, i, 0)),
                  pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
                  pl.BlockSpec((1, bq, 1), lambda b, j, i: (b, i, 0)),
                  pl.BlockSpec((1, 1, 1), lambda b, j, i: (b, 0, 0))],
        out_specs=[pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0)),
                   pl.BlockSpec((1, bk, D), lambda b, j, i: (b, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((BH, Sk, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, Sk, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(q3, k3, v3, do3, lse3, delta3, slopes)
    return (dq.reshape(B, H, S, D), dk.reshape(B, H, Sk, D), dv.reshape(B, H, Sk, D))


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _alibi_ref_bias(q, k, alibi):
    if not alibi:
        return None
    from deepspeed_tpu.models.layers import alibi_bias

    H, S, Sk = q.shape[1], q.shape[2], k.shape[2]
    # cross-length calls: query i sits at absolute position i + (Sk - S),
    # matching mha_reference's offset causal mask convention
    return alibi_bias(H, jnp.arange(S) + (Sk - S), jnp.arange(Sk))[None]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = True, sm_scale: Optional[float] = None,
                    block_q: int = DEFAULT_BLOCK_Q, block_k: int = DEFAULT_BLOCK_K,
                    impl: Optional[str] = None, alibi: bool = False):
    """Memory-efficient attention.  q/k/v: [B, H, S, D] -> [B, H, S, D].

    ``alibi=True`` adds the per-head linear position bias in-kernel
    (slopes derived from H; reference ``(R) softmax.cu`` alibi mask path)."""
    out, _ = _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k, impl, alibi)
    return out


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k, impl, alibi=False):
    impl = resolve_impl(impl)
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if impl == "xla":
        out = mha_reference(q, k, v, causal=causal, sm_scale=scale,
                            bias=_alibi_ref_bias(q, k, alibi))
        return out, (q, k, v, out, None)
    o, lse = _flash_fwd(q, k, v, causal, alibi, scale, block_q, block_k,
                        interpret_flag(impl))
    return o, (q, k, v, o, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, impl, alibi, res, g):
    impl = resolve_impl(impl)
    q, k, v, o, lse = res
    scale = sm_scale if sm_scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    if impl == "xla" or lse is None:
        # jnp autodiff of the reference
        def f(q_, k_, v_):
            return mha_reference(q_, k_, v_, causal=causal, sm_scale=scale,
                                 bias=_alibi_ref_bias(q_, k_, alibi))

        _, vjp = jax.vjp(f, q, k, v)
        return vjp(g)
    return _flash_bwd((q, k, v, o, lse), g, causal, alibi, scale, block_q,
                      block_k, interpret_flag(impl))


flash_attention.defvjp(_fa_fwd, _fa_bwd)
