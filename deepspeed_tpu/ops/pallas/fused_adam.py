"""Fused Adam/AdamW update kernel.

TPU-native replacement for the reference's ``csrc/adam/multi_tensor_adam.cu``
(+ ``multi_tensor_apply.cuh``, SURVEY.md §2.2 "Fused Adam"): one Pallas kernel
applies the whole Adam update (moment updates + bias correction + weight decay
+ param update) in a single pass over each tensor, reading/writing VMEM tiles.
The multi-tensor-apply trick (batch many small tensors into few launches) is
unnecessary under XLA — the per-leaf kernels fuse into one program — but the
single-pass form still saves HBM round-trips versus naive composition of
elementwise ops, and pins fp32 math for the moments regardless of param dtype.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.pallas.common import interpret_flag, resolve_impl

_LANE = 128
_BLOCK = 64 * 1024  # elements per grid step


def _adam_kernel(c1_ref, c2_ref, lr_ref, p_ref, g_ref, m_ref, v_ref,
                 p_out, m_out, v_out, *, beta1, beta2, eps, weight_decay,
                 adam_w_mode):
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    m = m_ref[:]
    v = v_ref[:]
    c1 = c1_ref[0]  # 1/(1-beta1^t)
    c2 = c2_ref[0]  # 1/(1-beta2^t)
    lr = lr_ref[0]  # scalar-prefetch: may be schedule-driven (a traced value)
    if not adam_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p  # L2 mode folds decay into the gradient
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    m_hat = m_new * c1
    v_hat = v_new * c2
    update = m_hat / (jnp.sqrt(v_hat) + eps)
    if adam_w_mode and weight_decay != 0.0:
        update = update + weight_decay * p  # decoupled decay
    p_out[:] = (p - lr * update).astype(p_out.dtype)
    m_out[:] = m_new
    v_out[:] = v_new


def fused_adam_update(param, grad, m, v, step, *, lr: float, beta1: float = 0.9,
                      beta2: float = 0.999, eps: float = 1e-8,
                      weight_decay: float = 0.0, adam_w_mode: bool = True,
                      impl: Optional[str] = None):
    """Single-tensor fused Adam step.  ``m``/``v`` must be fp32; ``step`` is the
    1-based step count (scalar i32).  Returns (new_param, new_m, new_v)."""
    impl = resolve_impl(impl)
    stepf = step.astype(jnp.float32)
    c1 = 1.0 / (1.0 - beta1 ** stepf)
    c2 = 1.0 / (1.0 - beta2 ** stepf)
    if impl == "xla":
        p = param.astype(jnp.float32)
        g = grad.astype(jnp.float32)
        if not adam_w_mode and weight_decay != 0.0:
            g = g + weight_decay * p
        m_new = beta1 * m + (1 - beta1) * g
        v_new = beta2 * v + (1 - beta2) * g * g
        update = (m_new * c1) / (jnp.sqrt(v_new * c2) + eps)
        if adam_w_mode and weight_decay != 0.0:
            update = update + weight_decay * p
        return (p - lr * update).astype(param.dtype), m_new, v_new

    # Mosaic wants >=2-D tiles: view the flat tensor as [rows, 128] and block
    # over rows; the per-step scalars ride in as scalar-prefetch args.
    orig_shape = param.shape
    n = param.size
    pad = (-n) % _LANE
    def flat(x):
        xf = x.reshape(-1)
        if pad:
            xf = jnp.pad(xf, (0, pad))
        return xf.reshape(-1, _LANE)

    pf, gf, mf, vf = flat(param), flat(grad), flat(m), flat(v)
    rows = pf.shape[0]
    block_rows = min(rows, _BLOCK // _LANE)
    while rows % block_rows:
        block_rows //= 2
    block_rows = max(1, block_rows)
    grid = rows // block_rows
    kernel = functools.partial(_adam_kernel, beta1=beta1, beta2=beta2, eps=eps,
                               weight_decay=weight_decay, adam_w_mode=adam_w_mode)
    c1a = jnp.asarray([c1], jnp.float32)
    c2a = jnp.asarray([c2], jnp.float32)
    # lr rides in as a scalar-prefetch arg (not a closure constant) so a
    # schedule-driven lr — a traced value inside the jitted train step —
    # doesn't end up baked into the kernel body.
    lra = jnp.asarray([lr], jnp.float32).reshape(1)
    # index_map receives (grid_idx, *scalar_prefetch_refs)
    bspec = pl.BlockSpec((block_rows, _LANE), lambda i, *_: (i, 0))
    p_new, m_new, v_new = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(grid,),
            in_specs=[bspec, bspec, bspec, bspec],
            out_specs=[bspec, bspec, bspec],
        ),
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), param.dtype),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32)],
        interpret=interpret_flag(impl),
    )(c1a, c2a, lra, pf, gf, mf, vf)
    unflat = lambda x: x.reshape(-1)[:n].reshape(orig_shape)
    return unflat(p_new), unflat(m_new), unflat(v_new)
