"""Rotary position embedding (RoPE).

TPU-native replacement for the reference's
``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu`` (SURVEY.md §2.2,
named explicitly in the north star).  The rotation is pure VPU elementwise
work, so the Pallas kernel's value is fusing the sin/cos generation with the
rotation in VMEM; the jnp path is the parity reference and lets XLA fuse into
neighboring matmuls.

Convention: half-rotation (GPT-NeoX / Llama style) — the head dim is split in
halves [x1, x2] -> [x1*cos - x2*sin, x2*cos + x1*sin].
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.common import interpret_flag, resolve_impl


def rope_angles(positions, head_dim: int, theta: float = 10000.0):
    """[S] int positions -> ([S, D/2] cos, [S, D/2] sin), fp32."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def _rope_ref(x, cos, sin):
    # x: [..., S, D]; cos/sin: [S, D/2]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    shape = (1,) * (x.ndim - 2) + cos.shape
    c = cos.reshape(shape).astype(jnp.float32)
    s = sin.reshape(shape).astype(jnp.float32)
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate([x1f * c - x2f * s, x2f * c + x1f * s], axis=-1).astype(x.dtype)


def _rope_kernel(x_ref, cos_ref, sin_ref, y_ref):
    x = x_ref[0].astype(jnp.float32)  # [S, D]
    half = x.shape[-1] // 2
    c = cos_ref[:].astype(jnp.float32)
    s = sin_ref[:].astype(jnp.float32)
    x1, x2 = x[:, :half], x[:, half:]
    y = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    y_ref[0] = y.astype(y_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def apply_rotary_pos_emb(x, cos, sin, impl: Optional[str] = None):
    """Apply RoPE.  ``x``: [..., S, D] (any leading batch/head dims); ``cos``/
    ``sin``: [S, D/2] from :func:`rope_angles`."""
    return _rope_fwd(x, cos, sin, impl)


def _rope_fwd(x, cos, sin, impl):
    impl = resolve_impl(impl)
    if impl == "xla":
        return _rope_ref(x, cos, sin)
    orig = x.shape
    S, D = orig[-2], orig[-1]
    lead = 1
    for d in orig[:-2]:
        lead *= d
    x3 = x.reshape(lead, S, D)
    y = pl.pallas_call(
        _rope_kernel,
        grid=(lead,),
        in_specs=[pl.BlockSpec((1, S, D), lambda i: (i, 0, 0)),
                  pl.BlockSpec((S, D // 2), lambda i: (0, 0)),
                  pl.BlockSpec((S, D // 2), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, S, D), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((lead, S, D), x.dtype),
        interpret=interpret_flag(impl),
    )(x3, cos, sin)
    return y.reshape(orig)


def _rope_fwd_vjp(x, cos, sin, impl):
    return _rope_fwd(x, cos, sin, impl), (cos, sin)


def _rope_bwd_vjp(impl, res, dy):
    cos, sin = res
    # Rotation is orthogonal: the VJP is rotation by -angle.
    return _rope_fwd(dy, cos, -sin, impl), None, None


apply_rotary_pos_emb.defvjp(_rope_fwd_vjp, _rope_bwd_vjp)
