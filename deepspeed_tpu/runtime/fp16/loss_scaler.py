"""Dynamic and static loss scaling, functional.

TPU-native analog of the reference's ``deepspeed/runtime/fp16/loss_scaler.py``
(SURVEY.md §2.1 "FP16 optimizers"): same semantics — scale the loss before
backward, detect inf/nan in gradients, skip the step and halve the scale on
overflow, double the scale after ``loss_scale_window`` clean steps, honor
``hysteresis`` — but expressed as a pure state transition inside the jitted
train step (the reference mutates a Python object between eager calls; here
the skip is a ``jnp.where`` select on the update).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar, current loss scale
    growth_tracker: jnp.ndarray  # i32, consecutive overflow-free steps
    hysteresis_tracker: jnp.ndarray  # i32, remaining tolerated overflows before shrink
    skipped_steps: jnp.ndarray   # i32, total skipped steps (reporting)


def make_state(config) -> LossScaleState:
    """Build initial scaler state from an FP16Config (static scale if
    ``loss_scale`` nonzero, else dynamic starting at 2**initial_scale_power)."""
    if config is not None and config.enabled:
        init = config.loss_scale if config.loss_scale > 0 else float(2 ** config.initial_scale_power)
        hyst = config.hysteresis
    else:
        init, hyst = 1.0, 1
    return LossScaleState(scale=jnp.asarray(init, jnp.float32),
                          growth_tracker=jnp.zeros((), jnp.int32),
                          hysteresis_tracker=jnp.asarray(hyst, jnp.int32),
                          skipped_steps=jnp.zeros((), jnp.int32))


def update(state: LossScaleState, overflow: jnp.ndarray, *, dynamic: bool,
           loss_scale_window: int, min_loss_scale: float, hysteresis: int,
           consecutive_hysteresis: bool = False) -> LossScaleState:
    """One scaler transition given this step's overflow flag."""
    if not dynamic:
        return state._replace(skipped_steps=state.skipped_steps + overflow.astype(jnp.int32))
    ht = jnp.where(overflow, state.hysteresis_tracker - 1, state.hysteresis_tracker)
    shrink = jnp.logical_and(overflow, ht <= 0)
    new_scale = jnp.where(shrink, jnp.maximum(state.scale / 2.0, min_loss_scale), state.scale)
    ht = jnp.where(shrink, jnp.asarray(hysteresis, jnp.int32), ht)
    growth = jnp.where(overflow, 0, state.growth_tracker + 1)
    grow = growth >= loss_scale_window
    new_scale = jnp.where(grow, new_scale * 2.0, new_scale)
    growth = jnp.where(grow, 0, growth)
    if consecutive_hysteresis:
        ht = jnp.where(jnp.logical_not(overflow), jnp.asarray(hysteresis, jnp.int32), ht)
    return LossScaleState(scale=new_scale, growth_tracker=growth, hysteresis_tracker=ht,
                          skipped_steps=state.skipped_steps + overflow.astype(jnp.int32))


class DynamicLossScaler:
    """Imperative shim for reference API parity (``cur_scale`` attribute)."""

    def __init__(self, init_scale=2**16, scale_window=1000, min_scale=1.0, hysteresis=2):
        self.state = LossScaleState(jnp.asarray(float(init_scale), jnp.float32),
                                    jnp.zeros((), jnp.int32),
                                    jnp.asarray(hysteresis, jnp.int32),
                                    jnp.zeros((), jnp.int32))
        self.scale_window = scale_window
        self.min_scale = min_scale
        self.hysteresis = hysteresis

    @property
    def cur_scale(self) -> float:
        return float(self.state.scale)

    def update_scale(self, overflow: bool) -> None:
        self.state = update(self.state, jnp.asarray(overflow), dynamic=True,
                            loss_scale_window=self.scale_window,
                            min_loss_scale=self.min_scale, hysteresis=self.hysteresis)
