"""0/1 Adam: communication-skipping (0-bit) + sign-compressed (1-bit) Adam.

Reference: ``deepspeed/runtime/fp16/onebit/zoadam.py`` (SURVEY.md §2.1 row
14) implementing the 0/1 Adam paper (PAPERS.md): on top of 1-bit Adam's
frozen-variance compressed momentum exchange, workers additionally SKIP
communication for growing intervals ("local steps"), updating their own
param replicas from purely local momentum, and reconcile at sync points by
sign-compressing the accumulated parameter displacement since the last sync.

Schedule (knob names match the reference config):

- ``var_freeze_step``: last step at which the variance may update.
- ``var_update_scaler``: while unfrozen, ``v`` refreshes every this many
  steps (from a full-precision grad pmean — rare by construction).
- ``local_step_scaler`` / ``local_step_clipper``: the learning-rate policy
  for the local-step interval.  Until ``var_freeze_step`` the interval is
  1 (sync every step).  After freezing, at each executed sync: if the LR
  changed since the previous sync the interval RESETS to 1 (replicas must
  reconcile often while the schedule moves); otherwise a stable-sync
  counter advances and every ``local_step_scaler``-th stable sync the
  interval doubles, capped at ``local_step_clipper``.  ``scaler=1``
  degenerates to plain doubling-to-cap.

TPU-native contract: like OneBitAdam this is a *per-worker local* update
meant for a full-manual ``shard_map`` region, but params are [W]-stacked
(spec ``P(waxes, ...)``) because replicas legitimately diverge between
syncs — each device holds exactly its own replica, so total memory matches
the reference's per-rank torch tensors.  The engine stacks/unstacks
(``_compile_onebit_steps``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.runtime.comm.quantized import compressed_allreduce
from deepspeed_tpu.runtime.fp16.onebit.adam import _chunk_size


class ZeroOneState(NamedTuple):
    exp_avg: Any          # per-worker momentum, [W, ...] stacked
    exp_avg_sq: Any       # variance, replicated (updates only from synced grads)
    anchor: Any           # params at last sync, replicated (fp32)
    error_m: Any          # momentum-compression worker error, [W, ...]
    server_error_m: Any   # momentum-compression server error, [W, chunk]
    error_p: Any          # displacement-compression worker error, [W, ...]
    server_error_p: Any   # displacement-compression server error, [W, chunk]
    count: jnp.ndarray    # i32 step counter, replicated
    var_updates: jnp.ndarray    # i32 number of variance EMA updates so far
    syncs: jnp.ndarray          # i32 number of executed sync exchanges
    sync_interval: jnp.ndarray  # i32 current local-step interval, replicated
    next_sync: jnp.ndarray      # i32 step index of the next sync, replicated
    last_sync_lr: jnp.ndarray   # f32 LR observed at the last sync (-1 = none)
    stable_syncs: jnp.ndarray   # i32 consecutive same-LR syncs (LR policy)


class ZeroOneAdam:
    """0/1 Adam local update functions (see module docstring)."""

    stacked_params = True  # engine: params carry a leading [W] worker axis

    def __init__(self, world: int, axis_names: Sequence[str], lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, var_freeze_step: int = 100000,
                 var_update_scaler: int = 16, local_step_scaler: int = 32678,
                 local_step_clipper: int = 16):
        self.world = world
        self.axis_names = tuple(axis_names)
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.var_freeze_step = var_freeze_step
        self.var_update_scaler = max(1, var_update_scaler)
        self.local_step_scaler = max(1, local_step_scaler)
        self.local_step_clipper = max(1, local_step_clipper)

    # -- state ----------------------------------------------------------
    def init(self, params_stacked: Any) -> ZeroOneState:
        """``params_stacked`` leaves carry the [W] worker axis."""
        W = self.world

        def unstack(p):
            return p[0]

        base = jax.tree.map(unstack, params_stacked)
        zeros_w = lambda p: jnp.zeros((W,) + p.shape, jnp.float32)
        serr = lambda p: jnp.zeros((W, _chunk_size(p.size, W)), jnp.float32)
        return ZeroOneState(
            exp_avg=jax.tree.map(zeros_w, base),
            exp_avg_sq=jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), base),
            anchor=jax.tree.map(lambda p: p.astype(jnp.float32), base),
            error_m=jax.tree.map(zeros_w, base),
            server_error_m=jax.tree.map(serr, base),
            error_p=jax.tree.map(zeros_w, base),
            server_error_p=jax.tree.map(serr, base),
            count=jnp.zeros((), jnp.int32),
            var_updates=jnp.zeros((), jnp.int32),
            syncs=jnp.zeros((), jnp.int32),
            sync_interval=jnp.ones((), jnp.int32),
            next_sync=jnp.ones((), jnp.int32),
            last_sync_lr=jnp.full((), -1.0, jnp.float32),
            stable_syncs=jnp.zeros((), jnp.int32))

    def state_pspecs(self, params: Any, waxes) -> "ZeroOneState":
        """PartitionSpecs for the state (stacked leaves over the worker
        axes, scalars and variance/anchor replicated)."""
        wspec = lambda p: P(waxes, *([None] * getattr(p, "ndim", 0)))
        rspec = lambda p: P(*([None] * getattr(p, "ndim", 0)))
        return ZeroOneState(
            exp_avg=jax.tree.map(wspec, params),
            exp_avg_sq=jax.tree.map(rspec, params),
            anchor=jax.tree.map(rspec, params),
            error_m=jax.tree.map(wspec, params),
            server_error_m=jax.tree.map(lambda p: P(waxes, None), params),
            error_p=jax.tree.map(wspec, params),
            server_error_p=jax.tree.map(lambda p: P(waxes, None), params),
            count=P(), var_updates=P(), syncs=P(), sync_interval=P(),
            next_sync=P(), last_sync_lr=P(), stable_syncs=P())

    # -- local (in-shard_map) update ------------------------------------
    def update_local(self, grads_local: Any, state: ZeroOneState,
                     params_local: Any, lr=None):
        """One step from THIS worker's local grads.  ``params_local`` leaves
        are this worker's [1, ...] replica slices; stacked state leaves
        arrive as [1, ...] slices.  Returns (new_params [1, ...], state)."""
        lr = self.lr if lr is None else lr
        count = state.count + 1
        cf = count.astype(jnp.float32)
        unfrozen = count <= self.var_freeze_step
        # the variance updates EVERY step until var_update_scaler updates
        # have landed (v==0 early would divide the momentum by eps), then
        # thins out to every var_update_scaler-th step until the freeze
        var_due = unfrozen & ((count <= self.var_update_scaler)
                              | (count % self.var_update_scaler == 0))
        var_updates = state.var_updates + var_due.astype(jnp.int32)
        vu = var_updates.astype(jnp.float32)
        sync = count >= state.next_sync

        def leaf(g, m, v, anc, em, sm_, ep, sp_, p):
            g = g.astype(jnp.float32)
            p32 = p[0].astype(jnp.float32)

            def warm_branch(_):
                # variance-adaptation phase: dense Adam over the averaged
                # gradient (replicas stay bit-identical; anchor rides along)
                g_avg = lax.pmean(g, self.axis_names)
                m_new = self.b1 * m[0] + (1 - self.b1) * g_avg
                v_new = jnp.where(var_due,
                                  self.b2 * v + (1 - self.b2) * g_avg * g_avg,
                                  v)
                m_hat = m_new / (1 - self.b1 ** cf)
                # bias-correct by the number of EMA updates v actually
                # received, not the step count — with thinned updates the
                # step-count form undersizes v_hat by ~the scaler factor
                v_hat = v_new / (1 - self.b2 ** jnp.maximum(vu, 1.0))
                upd = m_hat / (jnp.sqrt(v_hat) + self.eps)
                if self.weight_decay:
                    upd = upd + self.weight_decay * p32
                p_new = p32 - lr * upd
                return p_new, m_new, v_new, p_new, em[0], sm_[0], ep[0], sp_[0]

            def frozen_branch(_):
                # frozen variance: purely local momentum + update; replicas
                # diverge until the sync step reconciles them
                m_w = self.b1 * m[0] + (1 - self.b1) * g
                upd = m_w / (jnp.sqrt(v) + self.eps)
                if self.weight_decay:
                    upd = upd + self.weight_decay * p32
                p_local = p32 - lr * upd

                def sync_branch(_):
                    # sign-compress the displacement since the last sync and
                    # the momentum; everyone lands on identical replicas
                    delta = p_local - anc
                    d_avg, ep2, sp2 = compressed_allreduce(
                        delta, ep[0], sp_[0], self.axis_names)
                    p_sync = anc + d_avg
                    m_avg, em2, sm2 = compressed_allreduce(
                        m_w, em[0], sm_[0], self.axis_names)
                    return p_sync, m_avg, v, p_sync, em2, sm2, ep2, sp2

                def local_branch(_):
                    return (p_local, m_w, v, anc, em[0], sm_[0], ep[0], sp_[0])

                return lax.cond(sync, sync_branch, local_branch, operand=None)

            p_new, m_out, v_out, anc_out, em_out, sm_out, ep_out, sp_out = \
                lax.cond(unfrozen, warm_branch, frozen_branch, operand=None)
            return (p_new.astype(p.dtype)[None], m_out[None], v_out, anc_out,
                    em_out[None], sm_out[None], ep_out[None], sp_out[None])

        flat_p, treedef = jax.tree_util.tree_flatten(params_local)
        z = zip(jax.tree_util.tree_leaves(grads_local),
                jax.tree_util.tree_leaves(state.exp_avg),
                jax.tree_util.tree_leaves(state.exp_avg_sq),
                jax.tree_util.tree_leaves(state.anchor),
                jax.tree_util.tree_leaves(state.error_m),
                jax.tree_util.tree_leaves(state.server_error_m),
                jax.tree_util.tree_leaves(state.error_p),
                jax.tree_util.tree_leaves(state.server_error_p),
                flat_p)
        outs = [leaf(g, m, v, anc, em, sm_, ep, sp_, p)
                for g, m, v, anc, em, sm_, ep, sp_, p in z]
        unflat = lambda i: jax.tree_util.tree_unflatten(treedef,
                                                        [o[i] for o in outs])
        # local-step interval under the LR policy: 1 while the variance
        # adapts; after freezing, each executed sync observes the LR —
        # changed → interval resets to 1, stable → every local_step_scaler-th
        # stable sync doubles the interval up to the clipper cap
        lr_f = jnp.asarray(lr, jnp.float32)
        synced = unfrozen | sync
        frozen_sync = sync & ~unfrozen
        lr_changed = frozen_sync & (state.last_sync_lr >= 0) & (
            lr_f != state.last_sync_lr)
        stable_syncs = jnp.where(
            frozen_sync, jnp.where(lr_changed, 0, state.stable_syncs + 1),
            state.stable_syncs)
        grow = frozen_sync & ~lr_changed & (
            stable_syncs % jnp.int32(self.local_step_scaler) == 0)
        grown = jnp.minimum(state.sync_interval * 2,
                            jnp.int32(self.local_step_clipper))
        interval_after_sync = jnp.where(
            lr_changed, jnp.int32(1),
            jnp.where(grow, grown, state.sync_interval))
        next_interval = jnp.where(
            synced, jnp.where(unfrozen, jnp.int32(1), interval_after_sync),
            state.sync_interval)
        next_sync = jnp.where(synced, count + next_interval, state.next_sync)
        new_state = ZeroOneState(
            exp_avg=unflat(1), exp_avg_sq=unflat(2), anchor=unflat(3),
            error_m=unflat(4), server_error_m=unflat(5),
            error_p=unflat(6), server_error_p=unflat(7),
            count=count, var_updates=var_updates,
            syncs=state.syncs + synced.astype(jnp.int32),
            sync_interval=next_interval, next_sync=next_sync,
            last_sync_lr=jnp.where(synced, lr_f, state.last_sync_lr),
            stable_syncs=stable_syncs)
        return unflat(0), new_state
