"""1-bit Adam / 1-bit LAMB: error-feedback compressed-communication optimizers.

Reference: ``deepspeed/runtime/fp16/onebit/{adam,lamb}.py`` + the cupy
``compressed_allreduce`` backend (SURVEY.md §2.1 rows 14, 27).  Algorithm:

- **Warmup stage** (``step < freeze_step``): standard dense Adam — gradients
  are averaged across data-parallel workers (pmean), both moments update.
- **Compression stage**: the variance ``v`` freezes; each worker folds its
  *local* gradient into its momentum copy, the momentum is exchanged with
  1-bit sign compression + two-level error feedback
  (``runtime/comm/quantized.compressed_allreduce``), and the averaged
  momentum drives the update.  Comm volume drops ~16-32x (1 bit/element
  over ICI instead of 16/32).

TPU-native shape: these are *per-rank local* update functions meant to run
inside a ``shard_map`` manual region over the data-parallel mesh axes — the
engine wires them in (``DeepSpeedEngine`` onebit path) because 1-bit
semantics need per-worker local gradients, which only exist under manual
partitioning.  Like the reference, ZeRO stages >= 2 and model parallelism
are not supported with 1-bit optimizers.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.runtime.comm.quantized import compressed_allreduce


class OneBitState(NamedTuple):
    """Optimizer state pytree.  ``error``/``server_error`` carry a leading
    [world] axis (each worker's slice is its local feedback buffer)."""

    exp_avg: Any          # momentum, replicated
    exp_avg_sq: Any       # variance (frozen after warmup), replicated
    error: Any            # worker error feedback, [W, ...] stacked
    server_error: Any     # server error feedback, [W, chunk] stacked
    count: jnp.ndarray    # i32 step counter, replicated


def _chunk_size(n: int, world: int) -> int:
    padded = -(-n // (world * 8)) * (world * 8)
    return padded // world


class OneBitAdam:
    """Config-driven 1-bit Adam/LAMB update (local functions; see module
    docstring for the shard_map contract)."""

    def __init__(self, world: int, axis_names: Sequence[str], lr: float = 1e-3,
                 betas: Tuple[float, float] = (0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, freeze_step: int = 100,
                 lamb: bool = False):
        self.world = world
        self.axis_names = tuple(axis_names)
        self.lr = lr
        self.b1, self.b2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.freeze_step = freeze_step
        self.lamb = lamb

    # -- state ----------------------------------------------------------
    def init(self, params: Any) -> OneBitState:
        W = self.world
        zeros = lambda p: jnp.zeros(p.shape, jnp.float32)
        return OneBitState(
            exp_avg=jax.tree.map(zeros, params),
            exp_avg_sq=jax.tree.map(zeros, params),
            error=jax.tree.map(lambda p: jnp.zeros((W,) + p.shape, jnp.float32),
                               params),
            server_error=jax.tree.map(
                lambda p: jnp.zeros((W, _chunk_size(p.size, W)), jnp.float32),
                params),
            count=jnp.zeros((), jnp.int32))

    # -- local (in-shard_map) update ------------------------------------
    def update_local(self, grads_local: Any, state: OneBitState, params: Any,
                     lr=None):
        """One optimizer step from THIS worker's local gradients.

        All leaves of ``error``/``server_error`` arrive as this worker's
        [1, ...] slices.  Returns (new_params, new_state).
        """
        lr = self.lr if lr is None else lr
        count = state.count + 1
        warm = count <= self.freeze_step

        def leaf_update(g_local, m, v, err, serr, p):
            g_local = g_local.astype(jnp.float32)
            g_avg = lax.pmean(g_local, self.axis_names)

            def warm_branch(_):
                m_new = self.b1 * m + (1 - self.b1) * g_avg
                v_new = self.b2 * v + (1 - self.b2) * g_avg * g_avg
                # bias correction only in warmup (matches dense Adam exactly)
                c = count.astype(jnp.float32)
                m_hat = m_new / (1 - self.b1 ** c)
                v_hat = v_new / (1 - self.b2 ** c)
                upd = m_hat / (jnp.sqrt(v_hat) + self.eps)
                return m_new, v_new, err[0], serr[0], upd

            def frozen_branch(_):
                m_w = self.b1 * m + (1 - self.b1) * g_local  # LOCAL fold
                m_new, e_new, s_new = compressed_allreduce(
                    m_w, err[0], serr[0], self.axis_names)
                upd = m_new / (jnp.sqrt(v) + self.eps)
                return m_new, v, e_new, s_new, upd

            m_new, v_new, e_new, s_new, upd = lax.cond(
                warm, warm_branch, frozen_branch, operand=None)
            if self.weight_decay:
                upd = upd + self.weight_decay * p.astype(jnp.float32)
            if self.lamb:
                w_norm = jnp.linalg.norm(p.astype(jnp.float32))
                u_norm = jnp.linalg.norm(upd)
                trust = jnp.where((w_norm > 0) & (u_norm > 0),
                                  w_norm / u_norm, 1.0)
                upd = trust * upd
            p_new = (p.astype(jnp.float32) - lr * upd).astype(p.dtype)
            return p_new, m_new, v_new, e_new[None], s_new[None]

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads_local)
        flat_m = jax.tree_util.tree_leaves(state.exp_avg)
        flat_v = jax.tree_util.tree_leaves(state.exp_avg_sq)
        flat_e = jax.tree_util.tree_leaves(state.error)
        flat_s = jax.tree_util.tree_leaves(state.server_error)
        outs = [leaf_update(g, m, v, e, s, p) for g, m, v, e, s, p in
                zip(flat_g, flat_m, flat_v, flat_e, flat_s, flat_p)]
        unflat = lambda i: jax.tree_util.tree_unflatten(treedef,
                                                        [o[i] for o in outs])
        new_state = OneBitState(exp_avg=unflat(1), exp_avg_sq=unflat(2),
                                error=unflat(3), server_error=unflat(4),
                                count=count)
        return unflat(0), new_state


def onebit_from_config(opt_type: str, params: Dict[str, Any], world: int,
                       axis_names: Sequence[str]):
    name = opt_type.lower().replace("_", "").replace("-", "")
    betas = tuple(params.get("betas", (0.9, 0.999)))
    common = dict(world=world, axis_names=axis_names,
                  lr=params.get("lr", 1e-3), betas=betas,
                  eps=params.get("eps", 1e-8),
                  weight_decay=params.get("weight_decay", 0.0))
    if name == "zerooneadam":
        from deepspeed_tpu.runtime.fp16.onebit.zoadam import ZeroOneAdam

        # defaults match the reference ZeroOneAdam signature (var_freeze_step
        # 100000 — freezing at 100 would begin divergent local stepping
        # orders of magnitude earlier than the reference schedule)
        return ZeroOneAdam(
            var_freeze_step=params.get("var_freeze_step", 100000),
            var_update_scaler=params.get("var_update_scaler", 16),
            local_step_scaler=params.get("local_step_scaler", 32678),
            local_step_clipper=params.get("local_step_clipper", 16),
            **common)
    return OneBitAdam(freeze_step=params.get("freeze_step", 100),
                      lamb=(name == "onebitlamb"), **common)
