"""Runtime utilities.

TPU-native analog of the reference's ``deepspeed/runtime/utils.py`` (SURVEY.md
§2.1 "Runtime utils"): memory reporting, global-norm computation, overflow
checking.  The cross-rank allreduce in the reference's ``clip_grad_norm_``
disappears here — under jit with sharded grads, ``jnp`` reductions are global
and GSPMD inserts the collective.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger


def see_memory_usage(message: str, force: bool = False) -> None:
    """Log device-memory stats (reference: ``see_memory_usage``)."""
    if not force:
        return
    from deepspeed_tpu.accelerator import get_accelerator

    acc = get_accelerator()
    alloc = acc.memory_allocated() / 2**30
    peak = acc.max_memory_allocated() / 2**30
    total = acc.total_memory() / 2**30
    logger.info("%s | device mem: alloc %.2fGB peak %.2fGB total %.2fGB", message, alloc, peak, total)


def global_norm(tree: Any) -> jnp.ndarray:
    """L2 norm over a pytree of arrays (global across shards under jit)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def clip_grad_norm(grads: Any, max_norm: float, norm: Optional[jnp.ndarray] = None):
    """Clip a gradient pytree to ``max_norm`` by global L2 norm.

    Returns (clipped_grads, pre_clip_norm).  Reference:
    ``clip_grad_norm_`` with cross-rank allreduce (SURVEY.md §3.3).
    """
    norm = global_norm(grads) if norm is None else norm
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads), norm


def has_overflow(grads: Any) -> jnp.ndarray:
    """True if any gradient entry is non-finite (reference: ``CheckOverflow``).

    Under jit the ``jnp.isfinite`` reduction is global across shards, which is
    the reference's inf/nan allreduce collapsed into the XLA program.
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), dtype=bool)
    finite = jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves])
    return jnp.logical_not(jnp.all(finite))


def cast_tree(tree: Any, dtype) -> Any:
    return jax.tree.map(lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
                        tree)


def tree_num_params(tree: Any) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


def tree_bytes(tree: Any) -> int:
    return sum(int(x.size) * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


class PartitionedTensor:
    """Flatten-and-shard helper for pipeline activation exchange
    (reference: ``PartitionedTensor`` in runtime/utils.py).  On TPU this is
    only needed for host-side staging; in-program sharding uses NamedSharding.
    """

    def __init__(self, tensor: jnp.ndarray, num_parts: int):
        self.orig_shape = tensor.shape
        flat = tensor.reshape(-1)
        pad = (-flat.size) % num_parts
        if pad:
            flat = jnp.pad(flat, (0, pad))
        self.parts = flat.reshape(num_parts, -1)
        self.num_parts = num_parts

    def full(self) -> jnp.ndarray:
        flat = self.parts.reshape(-1)
        n = 1
        for d in self.orig_shape:
            n *= d
        return flat[:n].reshape(self.orig_shape)
