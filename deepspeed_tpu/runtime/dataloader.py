"""Data loading onto the device mesh.

TPU-native analog of the reference's ``deepspeed/runtime/dataloader.py``
(SURVEY.md §2.1 "Dataloader"): ``DeepSpeedDataLoader`` yields *global*
micro-batches placed on the mesh with the batch sharding (data axes split the
leading dimension), plus ``RepeatingLoader``.  Where the reference wraps a
torch ``DistributedSampler`` (each rank loads its slice), the TPU version
builds one global batch per micro-step; under multi-process SPMD each process
contributes its local slice via ``make_array_from_process_local_data``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import batch_sharding, get_global_mesh


def shard_batch(batch: Any, mesh: Optional[Mesh] = None, stacked: bool = False) -> Any:
    """Place a (possibly nested) host batch onto the mesh, splitting the
    leading dim over the data axes (``stacked=True``: leaves carry a
    [gas, micro, ...] accumulation axis first; the micro dim is split)."""
    mesh = mesh or get_global_mesh()
    sharding = batch_sharding(mesh, stacked=stacked)

    def put(x):
        if isinstance(x, jax.Array) and jax.process_count() == 1:
            # already on device: resharding device-to-device, no host hop
            return jax.device_put(x, sharding)
        x = np.asarray(x)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return multihost_utils.host_local_array_to_global_array(x, mesh, sharding.spec)
        return jax.device_put(x, sharding)

    return jax.tree.map(put, batch)


class DeepSpeedDataLoader:
    """Batched iteration over an in-memory dataset or torch-style dataset.

    ``dataset`` may be: a tuple/list of equal-length arrays (xs, ys, ...), a
    sequence of per-sample pytrees, or an object with ``__len__``/``__getitem__``.
    Yields micro-batches of ``batch_size`` samples (the GLOBAL micro-batch =
    micro_batch_per_chip * data-parallel world), sharded onto the mesh.
    """

    def __init__(self, dataset: Any, batch_size: int, mesh: Optional[Mesh] = None,
                 shuffle: bool = False, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None, local_rank: int = 0,
                 data_sampler: Any = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self._epoch = 0
        self.data_sampler = data_sampler
        # deterministic stream state (docs/RESILIENCE.md "Elastic
        # training"): sample offset within the current epoch, tracked in
        # SAMPLES (not batches) so a resume at a different batch size —
        # an elastic world-size change resizes the global micro-batch —
        # replays exactly the remaining sample stream.  The shuffle
        # permutation is a pure function of (seed, epoch), so offsets
        # survive a process restart.  ``_samples_consumed`` mirrors the
        # live iterator's position (what ``state_dict`` reports);
        # ``_resume_offset`` is consumed by exactly ONE subsequent
        # ``__iter__`` after ``load_state_dict`` — a fresh iterator
        # without a pending resume starts the epoch at sample 0, so
        # peek-then-iterate callers never silently lose a batch
        self._samples_consumed = 0
        self._resume_offset = 0

        if isinstance(dataset, (tuple, list)) and len(dataset) > 0 and hasattr(dataset[0], "shape"):
            self._arrays = tuple(np.asarray(a) for a in dataset)
            self._n = len(self._arrays[0])
        else:
            self._arrays = None
            self._n = len(dataset)

    def __len__(self) -> int:
        if self.drop_last:
            return self._n // self.batch_size
        return (self._n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch
        self._samples_consumed = 0
        self._resume_offset = 0

    # -- saveable stream state (rides checkpoints as client_state) -------
    def state_dict(self) -> dict:
        """Everything needed to resume the exact sample stream: epoch,
        sample offset within it, and the shuffle identity (seed + flag +
        dataset length, validated on restore)."""
        return {"epoch": int(self._epoch),
                "samples_consumed": int(self._samples_consumed),
                "seed": int(self.seed), "shuffle": bool(self.shuffle),
                "n": int(self._n)}

    def load_state_dict(self, sd: dict) -> None:
        """Restore a :meth:`state_dict`.  The permutation identity must
        match — a different dataset length or shuffle seed cannot replay
        the recorded stream, and silently resuming a DIFFERENT stream is
        worse than failing."""
        if int(sd.get("n", self._n)) != self._n:
            raise ValueError(
                f"dataloader resume: dataset length changed "
                f"({sd.get('n')} -> {self._n}); the saved sample offset "
                "indexes a different permutation")
        if bool(sd.get("shuffle", self.shuffle)) != self.shuffle:
            raise ValueError("dataloader resume: shuffle flag changed")
        if self.shuffle and int(sd.get("seed", self.seed)) != self.seed:
            raise ValueError(
                f"dataloader resume: shuffle seed changed "
                f"({sd.get('seed')} -> {self.seed})")
        self._epoch = int(sd.get("epoch", 0))
        self._samples_consumed = int(sd.get("samples_consumed", 0))
        self._resume_offset = self._samples_consumed

    def _perm(self) -> np.ndarray:
        idx = np.arange(self._n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        return idx

    def __iter__(self) -> Iterator[Any]:
        idx = self._perm()
        # resume mid-epoch at the restored SAMPLE offset (consumed by
        # this one iterator): a new batch size (elastic restart) slices
        # the same permutation differently but yields the identical
        # remaining sample stream.  The offset is iterator-LOCAL from
        # here — a second/abandoned iterator restarts its epoch at 0
        # instead of silently eating the stream.
        start, self._resume_offset = self._resume_offset, 0
        self._samples_consumed = start
        avail = self._n - start
        nb = (avail // self.batch_size if self.drop_last
              else (avail + self.batch_size - 1) // self.batch_size)
        for b in range(nb):
            lo = start + b * self.batch_size
            sel = idx[lo:lo + self.batch_size]
            if self._arrays is not None:
                batch = tuple(a[sel] for a in self._arrays)
            else:
                samples = [self.dataset[int(i)] for i in sel]
                if self.collate_fn is not None:
                    batch = self.collate_fn(samples)
                else:
                    batch = jax.tree.map(lambda *xs: np.stack(xs), *samples)
            # mirrored for state_dict (checkpoints taken mid-epoch)
            self._samples_consumed = lo + len(sel)
            yield shard_batch(batch, self.mesh)
        self._epoch += 1
        self._samples_consumed = 0


class RepeatingLoader:
    """Endless wrapper (reference: ``RepeatingLoader``)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self._it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            self._it = iter(self.loader)
            return next(self._it)

    # stream-state passthrough: a repeating wrapper checkpoints/restores
    # its inner loader's position (restore re-enters at the saved offset)
    def state_dict(self) -> dict:
        return self.loader.state_dict()

    def load_state_dict(self, sd: dict) -> None:
        self.loader.load_state_dict(sd)
        self._it = iter(self.loader)
