"""Data loading onto the device mesh.

TPU-native analog of the reference's ``deepspeed/runtime/dataloader.py``
(SURVEY.md §2.1 "Dataloader"): ``DeepSpeedDataLoader`` yields *global*
micro-batches placed on the mesh with the batch sharding (data axes split the
leading dimension), plus ``RepeatingLoader``.  Where the reference wraps a
torch ``DistributedSampler`` (each rank loads its slice), the TPU version
builds one global batch per micro-step; under multi-process SPMD each process
contributes its local slice via ``make_array_from_process_local_data``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import batch_sharding, get_global_mesh


def shard_batch(batch: Any, mesh: Optional[Mesh] = None, stacked: bool = False) -> Any:
    """Place a (possibly nested) host batch onto the mesh, splitting the
    leading dim over the data axes (``stacked=True``: leaves carry a
    [gas, micro, ...] accumulation axis first; the micro dim is split)."""
    mesh = mesh or get_global_mesh()
    sharding = batch_sharding(mesh, stacked=stacked)

    def put(x):
        if isinstance(x, jax.Array) and jax.process_count() == 1:
            # already on device: resharding device-to-device, no host hop
            return jax.device_put(x, sharding)
        x = np.asarray(x)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return multihost_utils.host_local_array_to_global_array(x, mesh, sharding.spec)
        return jax.device_put(x, sharding)

    return jax.tree.map(put, batch)


class DeepSpeedDataLoader:
    """Batched iteration over an in-memory dataset or torch-style dataset.

    ``dataset`` may be: a tuple/list of equal-length arrays (xs, ys, ...), a
    sequence of per-sample pytrees, or an object with ``__len__``/``__getitem__``.
    Yields micro-batches of ``batch_size`` samples (the GLOBAL micro-batch =
    micro_batch_per_chip * data-parallel world), sharded onto the mesh.
    """

    def __init__(self, dataset: Any, batch_size: int, mesh: Optional[Mesh] = None,
                 shuffle: bool = False, seed: int = 0, drop_last: bool = True,
                 collate_fn: Optional[Callable] = None, local_rank: int = 0,
                 data_sampler: Any = None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.mesh = mesh
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self._epoch = 0
        self.data_sampler = data_sampler

        if isinstance(dataset, (tuple, list)) and len(dataset) > 0 and hasattr(dataset[0], "shape"):
            self._arrays = tuple(np.asarray(a) for a in dataset)
            self._n = len(self._arrays[0])
        else:
            self._arrays = None
            self._n = len(dataset)

    def __len__(self) -> int:
        if self.drop_last:
            return self._n // self.batch_size
        return (self._n + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __iter__(self) -> Iterator[Any]:
        idx = np.arange(self._n)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self._epoch)
            rng.shuffle(idx)
        nb = len(self)
        for b in range(nb):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            if self._arrays is not None:
                batch = tuple(a[sel] for a in self._arrays)
            else:
                samples = [self.dataset[int(i)] for i in sel]
                if self.collate_fn is not None:
                    batch = self.collate_fn(samples)
                else:
                    batch = jax.tree.map(lambda *xs: np.stack(xs), *samples)
            yield shard_batch(batch, self.mesh)
        self._epoch += 1


class RepeatingLoader:
    """Endless wrapper (reference: ``RepeatingLoader``)."""

    def __init__(self, loader: Iterable):
        self.loader = loader
        self._it = iter(loader)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._it)
        except StopIteration:
            self._it = iter(self.loader)
            return next(self._it)
