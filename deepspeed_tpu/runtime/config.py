"""ds_config.json-compatible configuration system.

TPU-native analog of the reference's ``deepspeed/runtime/config.py``
(SURVEY.md §2.1 "Config system", §5.6): parses the single JSON config (path,
dict, or base64-encoded JSON) into typed sub-configs, resolves the batch-size
triad ``train_batch_size = micro_batch_per_gpu * gradient_accumulation_steps *
world_size`` (any one of the three may be omitted), validates the result, and
exposes every section the reference supports plus a TPU-only ``mesh``
extension section describing the ICI/DCN device-mesh axes.

"gpu" in key names (``train_micro_batch_size_per_gpu``) is kept verbatim for
config compatibility; on TPU it means "per chip".
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, ClassVar, Dict, List, Optional, Union

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import AUTO, DeepSpeedConfigModel, get_scalar_param
from deepspeed_tpu.utils.logging import logger

# ---------------------------------------------------------------------------
# Section models
# ---------------------------------------------------------------------------


class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    loss_scale: float = 0.0  # 0 => dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    auto_cast: bool = False

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0


class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    # TPU extension: master_weights=False drops the fp32 master copy — the
    # training state itself is bf16 and the optimizer applies updates with
    # stochastic rounding (Adam8bit does this natively).  This is the memory
    # recipe for >1B params on one 16GB chip: no fp32 master (4N bytes) and
    # no fp32 grad tree ever materializes.
    master_weights: bool = True


class AMPConfig(DeepSpeedConfigModel):
    enabled: bool = False
    opt_level: str = "O1"


class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "Adam"
    params: Dict[str, Any] = Field(default_factory=dict)


class DataTypesConfig(DeepSpeedConfigModel):
    """``data_types`` section (reference key): gradient-accumulation dtype.

    ``grad_accum_dtype: "bf16"`` halves the persistent accumulator (and the
    reduce-scatter bytes from stage 2 up); fp32 (default) is exact.  fp16
    loss scaling requires fp32 accumulation (overflow/unscale semantics)."""

    grad_accum_dtype: Optional[str] = None  # None -> fp32


_DTYPE_NAMES = {"fp32": "float32", "float32": "float32", "float": "float32",
                "bf16": "bfloat16", "bfloat16": "bfloat16",
                "fp16": "float16", "float16": "float16"}


class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = Field(default_factory=dict)


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False
    # TPU extension: stream the backward per layer so gradients never exist
    # as a [model]-sized device buffer (the reference's swap pipeline moves
    # grads off-device per parameter as autograd produces them; the
    # whole-program jax path can't — see runtime/zero/stream_grad.py).
    stream_grads: bool = True
    # Streaming-relay knobs (runtime/zero/streaming.py — ROADMAP item 3):
    # prefetch double-buffers layer i+1's H2D while layer i computes
    # (loss-identical on/off — the transport order never changes the math);
    # int8_stream ships each layer as blockwise int8 + scales with a fused
    # on-device dequant stage (~2x fewer relay bytes than bf16; bounded
    # quantization noise — pair with offload_optimizer.int8_masters);
    # staging_slots pre-allocates that many persistent device staging
    # buffers reused by donation instead of fresh per-layer allocations.
    prefetch: bool = True
    int8_stream: bool = False
    staging_slots: int = 2


class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    device: str = OffloadDeviceEnum.none
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    # TPU-native default: the NVMe swapper pipelines read-ahead and async
    # write-back unless explicitly disabled (the reference defaults these
    # off because its plain swapper predates the pipelined one).
    pipeline_read: bool = True
    pipeline_write: bool = True
    fast_init: bool = False
    ratio: float = 1.0
    # TPU extension (ROADMAP item 3, ZeRO-Offload/Infinity bandwidth wall):
    # keep fp32 masters + moments as blockwise int8 on host (cpu backend;
    # ~4x less host RAM) and ship int8+scales across the host->device relay
    # with a fused on-device dequant (~2x fewer relay bytes than bf16).
    # quant_block is the blockwise code granularity (comm/quant.py).
    int8_masters: bool = False
    quant_block: int = 256


class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """``zero_optimization`` section (SURVEY.md §2.1 "ZeRO config").

    On TPU the stages are sharding policies over the ``fsdp`` mesh axis
    (SURVEY.md §7): stage 1 shards optimizer state, stage 2 additionally
    reduce-scatters gradients, stage 3 shards parameters.  Bucket-size knobs
    are accepted for compatibility and used as scheduling hints only — XLA/GSPMD
    does the actual bucketing/overlap.
    """

    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    # TPU extension riding the reference's overlap_comm flag: when true,
    # ZeRO collectives are chunked per layer bucket and explicitly
    # interleaved with compute (runtime/zero/overlap.py) instead of leaving
    # placement to GSPMD; overlap_bucket_layers sets the chunk granularity
    # (layers per bucket — the layer-granular analog of the reference's
    # allgather_bucket_size, which is byte-granular).
    overlap_bucket_layers: int = 1
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    sub_group_size: int = 1_000_000_000
    cpu_offload: Optional[bool] = None  # deprecated spelling
    cpu_offload_params: Optional[bool] = None
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    DEPRECATED_FIELDS: ClassVar[Dict[str, str]] = {
        "stage3_gather_fp16_weights_on_model_save": "stage3_gather_16bit_weights_on_model_save"}

    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    round_robin_gradients: bool = False
    zero_hpz_partition_size: int = 1
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    memory_efficient_linear: bool = True

    def model_post_init(self, ctx: Any) -> None:
        super().model_post_init(ctx)
        # cpu_offload is a structural migration (bool -> offload_optimizer
        # section), not a rename, so it can't use DEPRECATED_FIELDS.
        if self.cpu_offload and self.offload_optimizer is None:
            object.__setattr__(self, "offload_optimizer",
                               DeepSpeedZeroOffloadOptimizerConfig(device=OffloadDeviceEnum.cpu))


class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    # TPU extension: master switch + remat policy. enabled=None leaves the
    # model's own default; True/False forces per-layer jax.checkpoint on/off.
    # The reference section has no master switch because torch checkpointing
    # is invoked by model code; here the engine owns the transform.
    enabled: Optional[bool] = None
    policy: str = "full"                   # "full" | "dots" (save matmul outs)
    # reference keys (SURVEY.md §2.1 "Activation checkpointing"):
    partition_activations: bool = False    # activations are sharded by GSPMD
    cpu_checkpointing: bool = False        # saved residuals page to pinned host
    contiguous_memory_optimization: bool = False  # XLA owns layout; accepted
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


class AIOConfig(DeepSpeedConfigModel):
    block_size: int = 1_048_576
    queue_depth: int = 8
    thread_count: int = 1
    single_submit: bool = False
    overlap_events: bool = True


class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


class ProfileTraceConfig(DeepSpeedConfigModel):
    """``profile_trace`` section (TPU extension; SURVEY.md §5.1): capture a
    ``jax.profiler`` trace (xplane/Perfetto) for a window of train steps —
    the NVTX/nsys analog, attributing collective and kernel latency that the
    wall-clock timers cannot.  ``enabled: null`` follows
    ``wall_clock_breakdown``."""

    enabled: Optional[bool] = None
    start_step: int = 2
    num_steps: int = 2
    output_path: Optional[str] = None


class TensorBoardConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class WandbConfig(DeepSpeedConfigModel):
    enabled: bool = False
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None


class CSVConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"


class CommQuantizationConfig(DeepSpeedConfigModel):
    """``comm_quantization`` section (TPU extension; ROADMAP item 2 /
    ZeRO++ arXiv:2306.10209, EQuARX arXiv:2506.17615): blockwise-int8
    transport for the collectives themselves, a property of the comm
    layer every caller opts into (``comm/collectives_q.py``).

    Per-site switches are tri-state: ``null`` follows ``enabled``; an
    explicit ``true``/``false`` wins.  Sites:

    - ``grad_all_reduce`` — the ZeRO stage 0/1/2 boundary gradient sync
      (engine manual path; ``error_feedback`` carries the quantization
      residual across steps so the compressed all-reduce converges —
      turning it off is measurably worse, tested).
    - ``all_gather`` / ``reduce_scatter`` — the overlap schedule's
      per-bucket forward gathers and AD-transpose reduce-scatters
      (``overlap_comm``), and — on the ZeRO++ stage-3 path — the qwAG /
      qgRS switches (see the precedence rule below).
    - ``all_to_all`` — MoE dispatch/combine (``moe/sharded_moe.py``) and
      ``comm.all_to_all_single(quantized=True)``.
    - ``sequence_ring`` — the sequence-parallel ring attention KV
      rotation (codes rotate; one quantization error total).
    - ``pipeline`` — the pipeline stage-boundary rings
      (``runtime/pipe/spmd.py``): every fill/drain ``ppermute`` hop —
      the forward activation ring AND the backward cotangent reverse
      ring — moves int8 codes + block scales instead of the dense
      boundary tensor.  Unlike the sequence ring, each hop carries a
      FRESH activation, so the error budget is one quantization per
      hop, not per rotation.  Refuses to arm under fp16 loss scaling:
      the reverse ring would quantize loss-scaled cotangents, and int8
      saturation maps inf/nan onto finite codes — silently blinding
      the fp16 overflow detector that decides skip-vs-apply.

    Precedence vs the legacy ZeRO++ flags
    (``zero_optimization.zero_quantized_weights`` / ``_gradients``): the
    legacy flags are the stage-3 ZeRO++ spellings of ``all_gather`` /
    ``reduce_scatter``.  Setting both to AGREEING values is fine, and
    either alone activates its seam (a comm_quantization site turns the
    ZeRO++ quantized transport on even with the legacy flags unset).
    The one DETECTABLE contradiction — a legacy flag true while the
    comm_quantization site is explicitly false — raises at config
    parse, because silently picking one would make the other a lying
    knob.  (The reverse cannot be detected: a default-false legacy flag
    is indistinguishable from an explicit false, so legacy-false +
    site-true simply activates the seam — silence is not an "off"
    vote.)
    """

    enabled: bool = False
    block: int = 256                 # blockwise code granularity (comm/quant.py)
    error_feedback: bool = True      # residual carry for grad_all_reduce
    grad_all_reduce: Optional[bool] = None
    all_gather: Optional[bool] = None
    reduce_scatter: Optional[bool] = None
    all_to_all: Optional[bool] = None
    sequence_ring: Optional[bool] = None
    pipeline: Optional[bool] = None

    def _site(self, value: Optional[bool]) -> bool:
        return bool(self.enabled) if value is None else bool(value)

    @property
    def q_grad_all_reduce(self) -> bool:
        return self._site(self.grad_all_reduce)

    @property
    def q_all_gather(self) -> bool:
        return self._site(self.all_gather)

    @property
    def q_reduce_scatter(self) -> bool:
        return self._site(self.reduce_scatter)

    @property
    def q_all_to_all(self) -> bool:
        return self._site(self.all_to_all)

    @property
    def q_sequence_ring(self) -> bool:
        return self._site(self.sequence_ring)

    @property
    def q_pipeline(self) -> bool:
        return self._site(self.pipeline)


class CommsLoggerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: List[str] = Field(default_factory=list)


class FlightRecorderConfig(DeepSpeedConfigModel):
    """``flight_recorder`` section (TPU extension; docs/OBSERVABILITY.md):
    a fixed-size ring of structured runtime events (step/collective/
    checkpoint/compile), dumped as JSON + all-thread stacks on an unhandled
    engine exception, and on SIGUSR2 when ``on_signal`` is set — the
    post-mortem for long-run crashes and hangs."""

    enabled: bool = False
    capacity: int = 512
    dump_dir: Optional[str] = None   # default: current directory
    on_signal: bool = False          # install the SIGUSR2 dump handler


class WatchdogConfig(DeepSpeedConfigModel):
    """``watchdog`` section (TPU extension; docs/OBSERVABILITY.md "Device
    truth"): rolling-median step-time anomaly detector.  A step slower
    than ``factor`` x the rolling median (over the last ``window`` steps,
    armed after ``warmup`` samples) fires ONCE: flight-recorder dump +
    (when this jax supports the perfetto export) a one-shot device-trace
    capture of the next ``capture_steps`` steps, post-processed into the
    ``ds_profile_*`` phase breakdown.  Steady-state cost: one deque append
    + one comparison per step (plus a once-per-``window`` bound re-anchor
    so a falling median — compile-inflated warmup — can't park the trip
    bar out of reach).  Enabling the watchdog arms the flight recorder (a
    dump needs a populated ring)."""

    enabled: bool = False
    factor: float = 10.0
    window: int = 64
    warmup: int = 5
    capture_steps: int = 2
    trace: bool = True               # arm the one-shot trace capture on trip
    output_path: Optional[str] = None  # default: <flight dump_dir or cwd>
    rearm: bool = False              # reset after a trip (watch for repeats)


class GoodputConfig(DeepSpeedConfigModel):
    """``goodput`` section (TPU extension; docs/OBSERVABILITY.md "Goodput
    ledger"): run-level wall-clock attribution to the closed category set
    (compute / exposed_comm / host_stall / checkpoint_* / recompile /
    anomaly_skip / rollback / restart_downtime / drain / idle), persisted
    as an append-only ``runledger.jsonl`` and exported as
    ``ds_run_goodput_ratio`` + ``ds_run_time_seconds{category=}``.
    ``DSTPU_RUNLEDGER=<path>`` in the environment enables the ledger even
    when this section is absent (the supervisors' channel).
    ``assumed_comm_gbps`` prices the analytic comm plan into
    ``exposed_comm`` seconds on hosts with no device capture (the
    ZeRO-Infinity bandwidth-model style; stamped into bench output as
    ``source=analytic`` for honesty)."""

    enabled: bool = False
    path: Optional[str] = None            # default: ./runledger.jsonl
    min_tick_interval_s: float = 0.0      # 0 = persist every boundary tick
    assumed_comm_gbps: float = 100.0      # analytic comm pricing (per host)


class SloConfig(DeepSpeedConfigModel):
    """``slo`` section (TPU extension; docs/OBSERVABILITY.md "Goodput
    ledger"): declarative burn-rate rules evaluated at the ledger's
    boundary ticks.  A breached rule emits one flight-recorder
    ``slo_burn`` event, increments ``ds_slo_burn_total{rule=}``, and
    appends an ``slo_burn`` ledger row per evaluation.  ``goodput_ratio``
    is a MIN threshold; ``ttft_p99_s`` and ``shed_ratio`` are MAX
    thresholds read from the serving registry series."""

    goodput_ratio: Optional[float] = None
    ttft_p99_s: Optional[float] = None
    shed_ratio: Optional[float] = None

    def rules(self) -> Dict[str, float]:
        return {k: float(v) for k, v in
                (("goodput_ratio", self.goodput_ratio),
                 ("ttft_p99_s", self.ttft_p99_s),
                 ("shed_ratio", self.shed_ratio)) if v is not None}


class ContinuousProfilerConfig(DeepSpeedConfigModel):
    """``continuous_profiler`` section (TPU extension; docs/OBSERVABILITY.md
    "Continuous profiling"): always-on, low-duty-cycle device-trace
    captures.  Every ``every_steps`` steps or ``every_seconds`` seconds —
    whichever comes first — the engine opens a short
    ``capture_steps``-step trace window, decomposes it into per-scope
    device-seconds (``ds_prof_scope_device_seconds{scope=}`` plus the
    ``ds_comm_<op>_device_seconds`` feed ``/profilez`` would have
    produced), persists the summary to the bounded ``history_dir`` ring,
    and diffs it against the previous window (flight event
    ``prof_regression`` + ``ds_prof_regressions_total{scope=}`` when a
    scope drifts past ``regression_tolerance``).  ``max_duty_cycle``
    caps cumulative capture+decompose wall time as a fraction of run
    wall clock (default ≤1%); a window that would bust the budget is
    deferred, counted in ``ds_prof_window_overhead_ratio``'s headroom.
    Default OFF: disabled costs one ``is not None`` branch per step
    boundary and never changes compiled programs (the named scopes are
    unconditional)."""

    enabled: bool = False
    every_steps: int = 200
    every_seconds: float = 120.0
    capture_steps: int = 2
    max_duty_cycle: float = 0.01
    history_dir: str = "profile_history"
    max_windows: int = 64
    max_bytes: int = 4 << 20
    regression_tolerance: float = 0.25
    min_scope_seconds: float = 5e-5


class AnomalyConfig(DeepSpeedConfigModel):
    """``anomaly_detection`` section (TPU extension; docs/RESILIENCE.md
    "Elastic training"): bf16/fp32 step-anomaly containment — the fp16
    overflow-skip ladder for runs with no loss scaler.  A step whose
    global grad norm is non-finite or exceeds ``factor`` x the rolling
    median (over the last ``window`` ACCEPTED steps, armed after
    ``warmup``) is SKIPPED in-program (branchless select, mirroring the
    fp16 ``has_overflow`` path); after ``patience`` consecutive skips the
    engine dumps the flight recorder and ROLLS BACK to the newest valid
    checkpoint in ``save_dir`` (default: ``checkpoint.save_dir``).
    ``max_rollbacks`` consecutive-ladder rollbacks without an accepted
    step in between raise instead of looping forever.  Metrics:
    ``ds_train_anomaly_skipped_total`` / ``ds_train_anomaly_rollback_total``.
    """

    enabled: bool = False
    factor: float = 10.0
    window: int = 64
    warmup: int = 8
    patience: int = 3
    rollback: bool = True
    save_dir: Optional[str] = None   # default: checkpoint.save_dir
    max_rollbacks: int = 3


class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = Field(default_factory=dict)
    async_save: bool = False
    # TPU extensions (docs/RESILIENCE.md): crash-atomic saves are always
    # on; these knobs govern the verified-load / retention / preemption
    # layers around them.
    # verify the MANIFEST.json (existence + size + sha256) before a load
    # trusts a tag's bytes; on failure the loader walks back to the
    # newest valid tag instead of crashing
    verify_on_load: bool = True
    # additionally verify the sharded payload's per-CHUNK sha256 index
    # records (tools/ckpt_verify.py --deep): pinpoints the offending
    # shard/leaf instead of just the file; costs a second hash pass
    deep_verify_on_load: bool = False
    # on a world-size-changed resume, rescale gradient_accumulation_steps
    # so the recorded global batch is preserved (docs/RESILIENCE.md
    # "Elastic training"); off = warn and keep the current triad
    elastic_resume: bool = True
    # retention GC: after a successful commit, delete the oldest VALID
    # tags beyond this count (never the tag `latest` points to); 0 = keep
    # everything
    keep_last_n: int = 0
    # SIGTERM -> emergency save at the next optimizer boundary, then exit
    # with PREEMPTED_EXIT_CODE (runtime/preemption.py); requires save_dir
    preemption_save: bool = False
    # where preemption saves (and supervisor resumes) live
    save_dir: Optional[str] = None


class ElasticityConfig(DeepSpeedConfigModel):
    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = Field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.1
    ignore_non_elastic_batch_info: bool = False
    prefer_larger_batch: bool = True


class TensorParallelConfig(DeepSpeedConfigModel):
    autotp_size: int = 1
    tp_size: int = 1

    def model_post_init(self, ctx: Any) -> None:
        super().model_post_init(ctx)
        if self.autotp_size > 1 and self.tp_size == 1:
            object.__setattr__(self, "tp_size", self.autotp_size)


class MeshConfig(DeepSpeedConfigModel):
    """TPU extension section (SURVEY.md §5.6 "add a mesh/tpu section").

    Axis sizes for the device mesh.  Any axis left at 0 is inferred: ``fsdp``
    absorbs whatever is left of the device count after the explicit axes.
    Axis order is (dp, fsdp, tp, sp, ep-folded-into-dp/fsdp, pp outermost for
    DCN) — see deepspeed_tpu/comm/mesh.py for the layout rationale.
    """

    dp: int = 0
    fsdp: int = 0
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    axis_order: List[str] = Field(default_factory=lambda: ["pp", "dp", "fsdp", "ep", "sp", "tp"])


class DataEfficiencyConfig(DeepSpeedConfigModel):
    enabled: bool = False
    seed: int = 1234
    data_sampling: Dict[str, Any] = Field(default_factory=dict)
    data_routing: Dict[str, Any] = Field(default_factory=dict)


class CompressionConfig(DeepSpeedConfigModel):
    weight_quantization: Dict[str, Any] = Field(default_factory=dict)
    activation_quantization: Dict[str, Any] = Field(default_factory=dict)
    sparse_pruning: Dict[str, Any] = Field(default_factory=dict)
    row_pruning: Dict[str, Any] = Field(default_factory=dict)
    head_pruning: Dict[str, Any] = Field(default_factory=dict)
    channel_pruning: Dict[str, Any] = Field(default_factory=dict)
    layer_reduction: Dict[str, Any] = Field(default_factory=dict)


class AutotuningConfig(DeepSpeedConfigModel):
    enabled: bool = False
    fast: bool = True
    results_dir: str = "autotuning_results"
    exps_dir: str = "autotuning_exps"
    overwrite: bool = False
    metric: str = "throughput"
    start_profile_step: int = 3
    end_profile_step: int = 5
    num_tuning_micro_batch_sizes: int = 3
    tuner_type: str = "gridsearch"
    tuner_early_stopping: int = 5
    tuner_num_trials: int = 50
    max_train_batch_size: Optional[int] = None
    min_train_batch_size: int = 1
    arg_mappings: Dict[str, str] = Field(default_factory=dict)


# ---------------------------------------------------------------------------
# Top-level config
# ---------------------------------------------------------------------------


def _load_config_dict(config: Union[str, Dict, None]) -> Dict:
    if config is None:
        return {}
    if isinstance(config, dict):
        return dict(config)
    if isinstance(config, (str, os.PathLike)):
        path = str(config)
        if os.path.exists(path):
            with open(path, "r") as fh:
                return json.load(fh)
        # The reference also accepts base64-encoded JSON (SURVEY.md §5.6,
        # verified via accelerate's deepspeed plugin).
        try:
            decoded = base64.urlsafe_b64decode(path).decode("utf-8")
            return json.loads(decoded)
        except Exception:
            pass
        try:
            return json.loads(path)
        except Exception as exc:
            raise ValueError(
                f"Expected a path to a ds_config JSON file, a JSON string, or a dict; got {path!r}") from exc
    raise TypeError(f"Unsupported config type: {type(config)}")


class DeepSpeedConfig:
    """Parsed, validated view of a ds_config.

    Mirrors the reference's public attribute surface (``train_batch_size``,
    ``train_micro_batch_size_per_gpu``, ``gradient_accumulation_steps``,
    ``zero_config``, ``fp16_enabled``, ...) so code written against the
    reference config object keeps working.
    """

    def __init__(self, config: Union[str, Dict, None], mpu=None, mesh_device=None,
                 world_size: Optional[int] = None):
        self._param_dict = _load_config_dict(config)
        d = self._param_dict

        # Mesh section is parsed first: the batch triad's "world size" is the
        # *data-parallel* world (reference precedence: mpu's DP group,
        # SURVEY.md §3.2) = devices / (tp*sp*pp); dp, fsdp and ep all carry
        # batch shards (comm/mesh.py data_axes).
        self.mesh = MeshConfig(**d.get("mesh", d.get("tpu", {}).get("mesh", {})
                                       if isinstance(d.get("tpu"), dict) else {}))
        if world_size is not None:
            self.world_size = int(world_size)
        elif mpu is not None and hasattr(mpu, "get_data_parallel_world_size"):
            self.world_size = int(mpu.get_data_parallel_world_size())
        else:
            denom = max(1, self.mesh.tp * self.mesh.sp * self.mesh.pp)
            self.world_size = max(1, _default_world_size() // denom)

        # -- batch triad ----------------------------------------------------
        tbs = d.get("train_batch_size")
        mbs = d.get("train_micro_batch_size_per_gpu")
        gas = d.get("gradient_accumulation_steps")
        tbs = None if tbs == AUTO else tbs
        mbs = None if mbs == AUTO else mbs
        gas = None if gas == AUTO else gas
        (self.train_batch_size,
         self.train_micro_batch_size_per_gpu,
         self.gradient_accumulation_steps) = resolve_batch_triad(tbs, mbs, gas, self.world_size)

        # -- scalar knobs ---------------------------------------------------
        self.steps_per_print = _scalar(d, "steps_per_print", 10)
        self.wall_clock_breakdown = _scalar(d, "wall_clock_breakdown", False)
        self.dump_state = _scalar(d, "dump_state", False)
        self.gradient_clipping = _scalar(d, "gradient_clipping", 0.0)
        self.prescale_gradients = _scalar(d, "prescale_gradients", False)
        self.gradient_predivide_factor = _scalar(d, "gradient_predivide_factor", 1.0)
        self.sparse_gradients_enabled = _scalar(d, "sparse_gradients", False)
        self.communication_data_type = _scalar(d, "communication_data_type", None)
        self.zero_allow_untested_optimizer = _scalar(d, "zero_allow_untested_optimizer", False)
        self.zero_force_ds_cpu_optimizer = _scalar(d, "zero_force_ds_cpu_optimizer", True)
        self.memory_breakdown = _scalar(d, "memory_breakdown", False)
        self.seed = _scalar(d, "seed", 42)
        self.disable_allgather = _scalar(d, "disable_allgather", False)
        self.train_steps = _scalar(d, "train_steps", None)

        # -- sections -------------------------------------------------------
        self.fp16 = FP16Config(**d.get("fp16", {}))
        self.bf16 = BF16Config(**d.get("bf16", d.get("bfloat16", {})))
        self.data_types = DataTypesConfig(**d.get("data_types", {}))
        self.amp = AMPConfig(**d.get("amp", {}))
        self.optimizer = OptimizerConfig(**d["optimizer"]) if "optimizer" in d else None
        self.scheduler = SchedulerConfig(**d["scheduler"]) if "scheduler" in d else None
        self.zero_config = DeepSpeedZeroConfig(**d.get("zero_optimization", {}))
        self.activation_checkpointing = ActivationCheckpointingConfig(
            **d.get("activation_checkpointing", {}))
        self.aio = AIOConfig(**d.get("aio", {}))
        self.flops_profiler = FlopsProfilerConfig(**d.get("flops_profiler", {}))
        self.profile_trace = ProfileTraceConfig(**d.get("profile_trace", {}))
        self.tensorboard = TensorBoardConfig(**d.get("tensorboard", {}))
        self.wandb = WandbConfig(**d.get("wandb", {}))
        self.csv_monitor = CSVConfig(**d.get("csv_monitor", {}))
        self.comms_logger = CommsLoggerConfig(**d.get("comms_logger", {}))
        self.comm_quantization = CommQuantizationConfig(
            **d.get("comm_quantization", {}))
        self.flight_recorder = FlightRecorderConfig(**d.get("flight_recorder", {}))
        self.goodput = GoodputConfig(**d.get("goodput", {}))
        self.slo = SloConfig(**d.get("slo", {}))
        self.watchdog = WatchdogConfig(**d.get("watchdog", {}))
        self.continuous_profiler = ContinuousProfilerConfig(
            **d.get("continuous_profiler", {}))
        self.anomaly_detection = AnomalyConfig(**d.get("anomaly_detection", {}))
        self.checkpoint_config = CheckpointConfig(**d.get("checkpoint", {}))
        self.elasticity = ElasticityConfig(**d.get("elasticity", {}))
        self.tensor_parallel = TensorParallelConfig(**d.get("tensor_parallel", {}))
        self.data_efficiency = DataEfficiencyConfig(**d.get("data_efficiency", {}))
        # legacy top-level curriculum section (reference accepts both forms)
        self.curriculum_learning = d.get("curriculum_learning", {})
        self.compression_training = CompressionConfig(**d.get("compression_training", {}))
        self.autotuning = AutotuningConfig(**d.get("autotuning", {}))
        self.pipeline = d.get("pipeline", {})

        self._validate()

    # -- convenience predicates (reference API parity) ----------------------
    @property
    def fp16_enabled(self) -> bool:
        return bool(self.fp16.enabled)

    @property
    def bfloat16_enabled(self) -> bool:
        return bool(self.bf16.enabled)

    @property
    def loss_scale(self) -> float:
        return self.fp16.loss_scale

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.fp16.dynamic_loss_scale

    @property
    def zero_enabled(self) -> bool:
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self) -> int:
        return self.zero_config.stage

    def dtype(self):
        import jax.numpy as jnp

        if self.bf16.enabled:
            return jnp.bfloat16
        if self.fp16.enabled:
            return jnp.float16
        return jnp.float32

    def get(self, dotted_key: str, default: Any = None) -> Any:
        return get_scalar_param(self._param_dict, dotted_key, default)

    def grad_accum_dtype(self):
        """jnp dtype for the gradient accumulator (None config -> fp32)."""
        import jax.numpy as jnp

        name = self.data_types.grad_accum_dtype
        if name is None:
            return jnp.float32
        return getattr(jnp, _DTYPE_NAMES[name.lower()])

    def _validate(self) -> None:
        if self.fp16.enabled and self.bf16.enabled:
            raise ValueError("fp16 and bf16 cannot both be enabled")
        ga = self.data_types.grad_accum_dtype
        if ga is not None and ga.lower() not in _DTYPE_NAMES:
            raise ValueError(f"data_types.grad_accum_dtype: unknown dtype {ga!r}")
        if self.fp16.enabled and ga is not None and _DTYPE_NAMES[ga.lower()] != "float32":
            raise ValueError("fp16 loss scaling requires fp32 gradient "
                             "accumulation (data_types.grad_accum_dtype)")
        if self.zero_config.stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_optimization.stage must be 0-3, got {self.zero_config.stage}")
        # comm_quantization vs the legacy ZeRO++ flags: agreeing settings
        # compose (the legacy flags are the stage-3 spellings of
        # all_gather / reduce_scatter); an explicit contradiction raises —
        # silently preferring one would make the other a lying knob.
        cq = self.comm_quantization
        zc = self.zero_config
        for legacy_key, legacy_val, site_key, site_val in (
                ("zero_optimization.zero_quantized_weights",
                 zc.zero_quantized_weights, "all_gather", cq.all_gather),
                ("zero_optimization.zero_quantized_gradients",
                 zc.zero_quantized_gradients, "reduce_scatter",
                 cq.reduce_scatter)):
            # a contradiction needs BOTH sides explicit: the legacy flag
            # set true while the comm_quantization site says false (a
            # default-False legacy flag is silence, not an "off" vote)
            if legacy_val and site_val is False:
                raise ValueError(
                    f"conflicting quantized-comm config: {legacy_key}="
                    f"{legacy_val} but comm_quantization.{site_key}="
                    f"{site_val}.  The legacy flag is the ZeRO++ spelling "
                    f"of the comm_quantization site — set them to agree "
                    f"or drop one (precedence rule: contradictions raise, "
                    f"they are never silently resolved)")
        if cq.block <= 0:
            raise ValueError("comm_quantization.block must be positive")
        if self.fp16.enabled and cq.q_pipeline:
            raise ValueError(
                "comm_quantization.pipeline cannot arm under fp16: the "
                "backward boundary ring carries loss-SCALED cotangents, and "
                "int8 saturation maps inf/nan onto finite codes — the fp16 "
                "overflow detector (skip-vs-apply) would read clean "
                "gradients through an overflowed boundary.  Use bf16 (no "
                "loss scaling, overflow-free boundary codes), or keep the "
                "pipeline boundary dense (comm_quantization.pipeline: "
                "false) under fp16")
        if self.train_batch_size <= 0:
            raise ValueError("train_batch_size must be positive")
        if self.gradient_clipping < 0:
            raise ValueError("gradient_clipping must be >= 0")
        cp = self.continuous_profiler
        if cp.enabled:
            if not 0.0 < cp.max_duty_cycle <= 1.0:
                raise ValueError("continuous_profiler.max_duty_cycle must "
                                 "be in (0, 1]")
            if cp.every_steps < 1 or cp.every_seconds <= 0.0:
                raise ValueError("continuous_profiler cadence must be "
                                 "positive (every_steps >= 1, "
                                 "every_seconds > 0)")
            if cp.capture_steps < 1:
                raise ValueError("continuous_profiler.capture_steps must "
                                 "be >= 1")

    def print_config(self) -> None:
        logger.info("DeepSpeedConfig:")
        logger.info(json.dumps(self._param_dict, indent=2, sort_keys=True, default=str))


def _scalar(d: Dict, key: str, default: Any) -> Any:
    v = d.get(key, default)
    return default if v == AUTO else v


def _default_world_size() -> int:
    try:
        import jax

        return jax.device_count()
    except Exception:  # pragma: no cover
        return 1


def resolve_batch_triad(train_batch_size: Optional[int],
                        micro_batch_per_gpu: Optional[int],
                        grad_accum_steps: Optional[int],
                        world_size: int):
    """Fill in any missing member of the batch triad.

    Formula (reference contract, SURVEY.md §2.1 "Config system", restated in
    the HF integration): ``train_batch_size = train_micro_batch_size_per_gpu *
    gradient_accumulation_steps * world_size``.
    """
    tbs, mbs, gas = train_batch_size, micro_batch_per_gpu, grad_accum_steps
    if tbs is not None and mbs is not None and gas is not None:
        if tbs != mbs * gas * world_size:
            raise ValueError(
                f"Inconsistent batch config: train_batch_size={tbs} != "
                f"micro_batch({mbs}) * grad_accum({gas}) * world_size({world_size})")
        return tbs, mbs, gas
    if tbs is None and mbs is not None and gas is not None:
        return mbs * gas * world_size, mbs, gas
    if mbs is None and tbs is not None and gas is not None:
        if tbs % (gas * world_size) != 0:
            raise ValueError(f"train_batch_size {tbs} not divisible by grad_accum*world {gas * world_size}")
        return tbs, tbs // (gas * world_size), gas
    if gas is None and tbs is not None and mbs is not None:
        if tbs % (mbs * world_size) != 0:
            raise ValueError(f"train_batch_size {tbs} not divisible by micro_batch*world {mbs * world_size}")
        return tbs, mbs, tbs // (mbs * world_size)
    if tbs is not None:
        if tbs % world_size != 0:
            raise ValueError(f"train_batch_size {tbs} not divisible by world_size {world_size}")
        return tbs, tbs // world_size, 1
    if mbs is not None:
        return mbs * world_size, mbs, 1
    if gas is not None:
        return gas * world_size, 1, gas
    # Nothing specified: micro-batch 1, no accumulation.
    return world_size, 1, 1
