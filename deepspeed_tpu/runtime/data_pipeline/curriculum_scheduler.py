"""Curriculum learning scheduler.

Reference: ``deepspeed/runtime/data_pipeline/curriculum_scheduler.py``
(SURVEY.md §2.1 "Data efficiency"): difficulty (typically sequence length)
ramps from ``min_difficulty`` to ``max_difficulty`` on a fixed schedule.
Schedules and config keys match the reference (``fixed_linear``,
``fixed_root``, ``fixed_discrete``).

TPU note: difficulty changes alter tensor shapes, so each distinct
difficulty compiles one program.  ``difficulty_step`` (reference knob)
quantizes the ramp — keep it coarse (e.g. 64) so the compile count stays
small; ``CurriculumDataLoader``/``truncate_batch`` apply the current
difficulty by slicing the sequence dim.
"""

from __future__ import annotations

import math
from typing import Any, Dict

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.state: Dict[str, Any] = {}
        assert "curriculum_type" in config, "curriculum_type required"
        self.curriculum_type = config["curriculum_type"]
        self.min_difficulty = config.get("min_difficulty", 8)
        self.max_difficulty = config.get("max_difficulty", 1 << 30)
        self.current_difficulty = self.min_difficulty
        sched = config.get("schedule_config", config)
        if self.curriculum_type in (FIXED_LINEAR, FIXED_ROOT):
            self.total_step = sched.get("total_curriculum_step",
                                        sched.get("total_step", 1000))
            self.difficulty_step = sched.get("difficulty_step", 8)
            self.root_degree = sched.get("root_degree", 2)
        elif self.curriculum_type == FIXED_DISCRETE:
            self.difficulties = list(sched["difficulty"])
            self.max_steps = list(sched["max_step"])
            assert len(self.difficulties) == len(self.max_steps) + 1, \
                "need one more difficulty than boundaries"
        else:
            raise ValueError(f"unknown curriculum_type {self.curriculum_type}")

    def update_difficulty(self, global_steps: int) -> int:
        t = self.curriculum_type
        if t == FIXED_DISCRETE:
            d = self.difficulties[-1]
            for diff, boundary in zip(self.difficulties, self.max_steps):
                if global_steps <= boundary:
                    d = diff
                    break
            self.current_difficulty = d
            return d
        frac = min(1.0, global_steps / max(1, self.total_step))
        if t == FIXED_ROOT:
            frac = frac ** (1.0 / self.root_degree)
        raw = self.min_difficulty + frac * (self.max_difficulty - self.min_difficulty)
        quant = self.difficulty_step
        d = int(raw // quant * quant)
        d = max(self.min_difficulty, min(self.max_difficulty, d))
        self.current_difficulty = d
        return d

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def get_state(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def set_state(self, state: Dict[str, Any]) -> None:
        self.current_difficulty = state["current_difficulty"]


def truncate_batch(batch, difficulty: int, seq_axis: int = 1):
    """Apply the current difficulty by truncating the sequence dim — the
    reference's seqlen-based curriculum semantics."""
    import jax

    def trunc(x):
        if hasattr(x, "ndim") and x.ndim > seq_axis and x.shape[seq_axis] > difficulty:
            sl = [slice(None)] * x.ndim
            sl[seq_axis] = slice(0, difficulty)
            return x[tuple(sl)]
        return x

    return jax.tree.map(trunc, batch)
