"""Data analysis + curriculum-aware sampling.

Reference: ``deepspeed/runtime/data_pipeline/data_sampling/`` (SURVEY.md
§2.1 "Data efficiency") — two halves:

- **DataAnalyzer** (``data_analyzer.py`` role): a map/reduce pass over a
  dataset computing per-sample difficulty metrics (seqlen, custom fns).
  Map workers each write their shard's values; reduce merges them into the
  on-disk index the sampler consumes: ``sample_to_metric.npy`` (value per
  sample) and ``metric_to_sample.npy`` (sample ids sorted by value).
- **DeepSpeedDataSampler** (``data_sampler.py`` role): a deterministic,
  resumable sampler that composes each global batch from the samples whose
  metric values the current curriculum difficulty admits, then hands THIS
  data-parallel rank its shard.  Difficulty follows the same schedules as
  ``CurriculumScheduler``; ``difficulty_type`` is ``"value"`` (admit
  metric <= difficulty) or ``"percentile"`` (admit the easiest d% of the
  sorted index).

TPU note: the sampler emits *index arrays* (host-side numpy); batch
assembly stays on the host and only the assembled batch is transferred —
sampling never touches the device.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.utils.logging import logger

SAMPLE_TO_METRIC = "sample_to_metric.npy"
METRIC_TO_SAMPLE = "metric_to_sample.npy"


def seqlen_metric(sample) -> int:
    """Default metric: token count (reference's seqlen analyzer)."""
    if isinstance(sample, dict):
        sample = sample.get("input_ids", next(iter(sample.values())))
    if isinstance(sample, (tuple, list)):
        sample = sample[0]
    arr = np.asarray(sample)
    return int(arr.shape[-1] if arr.ndim else 1)


class DataAnalyzer:
    """Map/reduce per-sample metric analysis (see module docstring).

    ``metric_functions`` maps metric name -> fn(sample) -> scalar.  Workers
    call ``run_map`` over disjoint shards (``worker_id``/``num_workers``),
    then one process calls ``run_reduce`` to merge and index.
    """

    def __init__(self, dataset: Sequence, save_path: str,
                 metric_functions: Optional[Dict[str, Callable]] = None,
                 num_workers: int = 1, worker_id: int = 0):
        self.dataset = dataset
        self.save_path = save_path
        self.metric_functions = metric_functions or {"seqlen": seqlen_metric}
        self.num_workers = max(1, num_workers)
        self.worker_id = worker_id

    def _metric_dir(self, name: str) -> str:
        return os.path.join(self.save_path, name)

    def run_map(self) -> None:
        n = len(self.dataset)
        idx = np.arange(self.worker_id, n, self.num_workers)
        for name, fn in self.metric_functions.items():
            vals = np.asarray([fn(self.dataset[int(i)]) for i in idx],
                              dtype=np.float64)
            d = self._metric_dir(name)
            os.makedirs(d, exist_ok=True)
            np.save(os.path.join(d, f"worker{self.worker_id}_idx.npy"), idx)
            np.save(os.path.join(d, f"worker{self.worker_id}_val.npy"), vals)
        logger.info("data analyzer: worker %d/%d mapped %d samples (%s)",
                    self.worker_id, self.num_workers, len(idx),
                    list(self.metric_functions))

    def run_reduce(self) -> None:
        n = len(self.dataset)
        for name in self.metric_functions:
            d = self._metric_dir(name)
            sample_to_metric = np.zeros((n,))
            written = np.zeros((n,), bool)  # NaN is a legal metric value
            for w in range(self.num_workers):
                ipath = os.path.join(d, f"worker{w}_idx.npy")
                if not os.path.exists(ipath):
                    raise RuntimeError(
                        f"data analyzer: worker {w} wrote no {name} values — "
                        f"did every worker run_map?")
                idx = np.load(ipath)
                val = np.load(os.path.join(d, f"worker{w}_val.npy"))
                sample_to_metric[idx] = val
                written[idx] = True
            if not written.all():
                missing = int((~written).sum())
                raise RuntimeError(f"data analyzer: {missing} samples have no "
                                   f"{name} value — did every worker run_map?")
            order = np.argsort(sample_to_metric, kind="stable")
            np.save(os.path.join(d, SAMPLE_TO_METRIC), sample_to_metric)
            np.save(os.path.join(d, METRIC_TO_SAMPLE), order)
            with open(os.path.join(d, "meta.json"), "w") as fh:
                json.dump({"num_samples": int(n),
                           "min": float(sample_to_metric.min()),
                           "max": float(sample_to_metric.max())}, fh)
            logger.info("data analyzer: %s indexed (%d samples, min=%g "
                        "max=%g)", name, n, sample_to_metric.min(),
                        sample_to_metric.max())

    def run(self) -> None:
        """Single-process convenience: map (all shards) then reduce."""
        for w in range(self.num_workers):
            DataAnalyzer(self.dataset, self.save_path, self.metric_functions,
                         self.num_workers, w).run_map()
        self.run_reduce()


class DeepSpeedDataSampler:
    """Curriculum-aware deterministic index sampler (see module docstring).

    ``curriculum_metrics``: {name: {"index_path": <analyzer dir>,
    "difficulty_type": "value"|"percentile", + CurriculumScheduler keys
    (curriculum_type, min/max_difficulty, total_curriculum_step, ...)}}.
    Yields, per global step, the sample indices for THIS dp rank.
    """

    def __init__(self, num_samples: int, global_batch_size: int,
                 data_parallel_rank: int = 0, data_parallel_size: int = 1,
                 curriculum_metrics: Optional[Dict[str, Dict]] = None,
                 seed: int = 1234, shuffle: bool = True):
        assert global_batch_size % data_parallel_size == 0, \
            (global_batch_size, data_parallel_size)
        self.num_samples = num_samples
        self.global_batch_size = global_batch_size
        self.rank = data_parallel_rank
        self.dp_size = data_parallel_size
        self.seed = seed
        self.shuffle = shuffle
        self._perm_cache: Dict[Any, np.ndarray] = {}
        self._warned_empty_intersection = False
        self.global_step = 0
        self.consumed_samples = 0
        self.metrics: Dict[str, Dict[str, Any]] = {}
        for name, mcfg in (curriculum_metrics or {}).items():
            mdir = mcfg["index_path"]
            s2m = np.load(os.path.join(mdir, SAMPLE_TO_METRIC))
            m2s = np.load(os.path.join(mdir, METRIC_TO_SAMPLE))
            if len(s2m) != num_samples:
                raise ValueError(f"metric {name}: index covers {len(s2m)} "
                                 f"samples, dataset has {num_samples}")
            sched_cfg = {k: v for k, v in mcfg.items()
                         if k not in ("index_path", "difficulty_type")}
            self.metrics[name] = {
                "sample_to_metric": s2m,
                "metric_to_sample": m2s,
                # values in index order: O(log n) threshold lookup per step
                "sorted_values": s2m[m2s],
                "difficulty_type": mcfg.get("difficulty_type", "value"),
                "scheduler": CurriculumScheduler(sched_cfg),
            }

    # -- difficulty gating ----------------------------------------------
    def _admitted(self, step: int) -> np.ndarray:
        """Sample ids the current difficulties admit (intersection over
        metrics); everything when no curriculum metric is configured."""
        admitted: Optional[np.ndarray] = None
        pools: List[np.ndarray] = []
        for name, m in self.metrics.items():
            diff = m["scheduler"].update_difficulty(step)
            if m["difficulty_type"] == "percentile":
                k = int(np.ceil(len(m["metric_to_sample"]) * diff / 100.0))
                ids = m["metric_to_sample"][:max(1, k)]
            else:  # value threshold: prefix of the sorted index
                k = int(np.searchsorted(m["sorted_values"], diff,
                                        side="right"))
                ids = m["metric_to_sample"][:max(1, k)]
            pools.append(ids)
            admitted = ids if admitted is None else \
                np.intersect1d(admitted, ids, assume_unique=False)
        if admitted is None:
            return np.arange(self.num_samples)
        if not len(admitted):
            # disjoint per-metric pools (can happen early in multi-metric
            # ramps): fall back to the union rather than starving the batch
            # down to one repeated sample
            if not self._warned_empty_intersection:
                logger.warning(
                    "data sampler: curriculum metrics admit disjoint sample "
                    "sets at step %d; falling back to their union until the "
                    "ramps overlap", step)
                self._warned_empty_intersection = True
            admitted = np.unique(np.concatenate(pools))
        return admitted

    # -- sampling --------------------------------------------------------
    def sample_step(self, step: Optional[int] = None) -> np.ndarray:
        """Indices for this rank at ``step`` (default: the next step)."""
        if step is None:
            step = self.global_step
        pool = self._admitted(step)
        if self.shuffle:
            # Epoch-style traversal (reference data_sampler semantics): one
            # permutation of the admitted pool per epoch, so while the pool
            # is stable every admitted sample is visited before any repeats.
            # Stateless in ``step`` (resume/replay-safe); a pool change
            # (curriculum ramp) reseeds the permutation via the pool
            # fingerprint — a mid-epoch change therefore restarts traversal
            # at the cumulative position, which can skip part of the fresh
            # permutation until the next epoch boundary (inherent to the
            # stateless design; ramps change the pool every few steps
            # anyway, so per-era traversal is approximate by nature).
            n = len(pool)
            if n * 4 <= self.global_batch_size:
                # every batch repeats the pool several times over — epoch
                # traversal is vacuous; sample with replacement instead of
                # building ceil(gbs/n) permutations per step
                rng = np.random.RandomState(
                    (self.seed * 1000003 + step) % (2 ** 31))
                picks = rng.choice(pool, size=self.global_batch_size,
                                   replace=True)
            else:
                # multi-metric pools (intersect1d/union) are NOT prefixes of
                # a fixed index, so the fingerprint must cover the content;
                # the crc is O(n) like _admitted itself — not a new cost class
                fp = zlib.crc32(np.ascontiguousarray(pool).tobytes())
                start = step * self.global_batch_size
                epoch, pos = divmod(start, n)

                def perm(e):
                    ck = (e, fp)
                    cached = self._perm_cache.get(ck)
                    if cached is None:
                        prng = np.random.RandomState(
                            (self.seed * 1000003 + e * 9176 + fp) % (2 ** 31))
                        cached = prng.permutation(pool)
                        if len(self._perm_cache) > 16:
                            self._perm_cache.clear()
                        self._perm_cache[ck] = cached
                    return cached

                need = pos + self.global_batch_size
                chunks = [perm(epoch + i) for i in range(-(-need // n))]
                picks = np.concatenate(chunks)[pos:pos + self.global_batch_size]
        else:
            off = (step * self.global_batch_size) % len(pool)
            picks = np.take(pool, np.arange(off, off + self.global_batch_size),
                            mode="wrap")
        per_rank = self.global_batch_size // self.dp_size
        mine = picks[self.rank * per_rank:(self.rank + 1) * per_rank]
        if step == self.global_step:
            self.global_step += 1
            self.consumed_samples += self.global_batch_size
        return mine

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            yield self.sample_step()

    # -- resume ----------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        return {"global_step": self.global_step,
                "consumed_samples": self.consumed_samples,
                "seed": self.seed}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.global_step = int(sd["global_step"])
        self.consumed_samples = int(sd["consumed_samples"])
        if int(sd.get("seed", self.seed)) != self.seed:
            logger.warning("data sampler: resuming with a different seed "
                           "(%s -> %s); sample order will diverge",
                           sd.get("seed"), self.seed)
