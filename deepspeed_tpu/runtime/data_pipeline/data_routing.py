"""Random layerwise token dropping (random-LTD).

Reference: ``deepspeed/runtime/data_pipeline/data_routing/`` + the
``csrc/random_ltd`` token-sort/gather kernels (SURVEY.md §2.1 "Data
efficiency", §2.2 "Random-LTD"): during training, middle layers process a
random subset of tokens; the skipped tokens bypass the layer and rejoin
afterwards.  On TPU the gather/scatter is plain ``jnp.take_along_axis``
over a random permutation — XLA fuses it (the CUDA sort/gather kernels
exist because of eager-launch overheads; SURVEY §2.2 prescribes exactly
this jnp mapping).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def random_token_select(x, rng, keep: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pick ``keep`` random token positions per sequence.

    x: [B, S, D] -> (kept [B, keep, D], perm [B, S]) where perm's first
    ``keep`` entries index the kept tokens (the rest are the dropped ones,
    used to restore order in :func:`scatter_back`).
    """
    B, S, _ = x.shape
    noise = jax.random.uniform(rng, (B, S))
    perm = jnp.argsort(noise, axis=-1)                 # random permutation
    kept = jnp.take_along_axis(x, perm[:, :keep, None], axis=1)
    return kept, perm


def scatter_back(x_full, y_kept, perm, keep: int):
    """Write processed kept tokens back into their original positions;
    dropped tokens keep their (layer-input) values — the random-LTD bypass."""
    idx = perm[:, :keep, None]
    return jnp.take_along_axis(  # inverse permutation scatter via argsort
        jnp.concatenate([y_kept,
                         jnp.take_along_axis(x_full, perm[:, keep:, None], axis=1)],
                        axis=1),
        jnp.argsort(perm, axis=-1)[..., None], axis=1), idx


class RandomLTDScheduler:
    """Ramp the kept-token count from ``seq_start`` to the full sequence over
    ``total_steps`` (reference: random_ltd schedule config)."""

    def __init__(self, seq_start: int, seq_full: int, total_steps: int,
                 step_size: int = 16):
        self.seq_start = seq_start
        self.seq_full = seq_full
        self.total_steps = total_steps
        self.step_size = step_size
        self.current = seq_start

    def update(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(1, self.total_steps))
        raw = self.seq_start + frac * (self.seq_full - self.seq_start)
        cur = int(raw // self.step_size * self.step_size)
        self.current = max(self.seq_start, min(self.seq_full, cur))
        return self.current


def random_ltd_layer(layer_fn, x, rng, keep: int):
    """Apply ``layer_fn`` to a random ``keep``-token subset; dropped tokens
    bypass (identity).  ``layer_fn``: [B, keep, D] -> [B, keep, D]."""
    if keep >= x.shape[1]:
        return layer_fn(x)
    kept, perm = random_token_select(x, rng, keep)
    y_kept = layer_fn(kept)
    out, _ = scatter_back(x, y_kept, perm, keep)
    return out
