"""Data-efficiency pipeline (reference: ``deepspeed/runtime/data_pipeline/``,
SURVEY.md §2.1): curriculum learning, random-LTD token dropping, and the
data analysis/sampling half (``data_sampling/``)."""

from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (  # noqa: F401
    CurriculumScheduler, truncate_batch)
from deepspeed_tpu.runtime.data_pipeline.data_routing import (  # noqa: F401
    RandomLTDScheduler, random_ltd_layer, random_token_select, scatter_back)
from deepspeed_tpu.runtime.data_pipeline.data_sampling import (  # noqa: F401
    DataAnalyzer, DeepSpeedDataSampler, seqlen_metric)
