"""Pipeline engine (reference: ``deepspeed/runtime/pipe/engine.py``).

The reference subclass replaces forward/backward with an instruction scheduler
(SURVEY.md §3.4).  Here pipelining happens *inside* the jitted train step
(runtime/pipe/spmd.py), so the engine surface is unchanged — this subclass
only adds the pipeline-specific introspection the reference exposes and makes
``train_batch``/``eval_batch`` the primary entry points.
"""

from __future__ import annotations

from deepspeed_tpu.comm.mesh import axis_size
from deepspeed_tpu.runtime.engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.is_pipe_parallel = axis_size(self.mesh, "pp") > 1

    @property
    def num_stages(self) -> int:
        return axis_size(self.mesh, "pp")

    def stage_id(self) -> int:
        # SPMD: every process drives all stages; stage placement is a mesh
        # sharding, not a per-process role (reference: grid.get_stage_id()).
        return 0

    def is_first_stage(self) -> bool:
        return True

    def is_last_stage(self) -> bool:
        return True
