"""Pipeline engine (reference: ``deepspeed/runtime/pipe/engine.py``).

The reference subclass replaces forward/backward with an instruction
scheduler (SURVEY.md §3.4).  Here pipelining happens *inside* the jitted
train step (runtime/pipe/spmd.py), so this subclass adds the
pipeline-specific surface around it: schedule/bubble introspection,
microbatch accounting, and ``train_batch``/``eval_batch`` as the primary
entry points (with the reference's data-iterator management —
``set_dataiterator``/``reset_activation_shape`` parity).
"""

from __future__ import annotations

from typing import Any, Optional

from deepspeed_tpu.comm.mesh import axis_size
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class PipelineEngine(DeepSpeedEngine):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.is_pipe_parallel = axis_size(self.mesh, "pp") > 1
        self._data_iter = None
        mcfg = getattr(self.module, "config", None)
        self.micro_batches = (getattr(mcfg, "pp_microbatches", 0)
                              or self.num_stages)
        if self.is_pipe_parallel:
            log_dist(f"pipeline engine: {self.num_stages} stages, "
                     f"{self.micro_batches} microbatches, "
                     f"{self.schedule} schedule, bubble "
                     f"{self.bubble_fraction:.1%}", ranks=[0])

    # -- schedule introspection -----------------------------------------
    @property
    def num_stages(self) -> int:
        return axis_size(self.mesh, "pp")

    @property
    def schedule(self) -> str:
        """Active schedule name: "gpipe" (fill-drain + autodiff) or "1f1b"
        (fused forward+backward scan)."""
        mcfg = getattr(self.module, "config", None)
        return getattr(mcfg, "pp_schedule", "gpipe") or "gpipe"

    @property
    def schedule_steps(self) -> int:
        """Schedule length in pipeline ticks per batch: M + pp - 1 for the
        GPipe fill-drain, M + 2(pp-1) for the fused 1F1B scan (each tick
        there carries one forward AND one backward microbatch slot)."""
        M, pp = self.micro_batches, self.num_stages
        if self.schedule == "1f1b":
            return M + 2 * (pp - 1)
        return M + pp - 1

    @property
    def bubble_fraction(self) -> float:
        """Idle fraction of the schedule — (pp-1)/T with T the schedule
        length: (pp-1)/(M+pp-1) for GPipe (the reference TrainSchedule's
        cost model) and (pp-1)/(M+2(pp-1)) for 1F1B, where each stage
        idles pp-1 of its 2T fwd+bwd slots on each wavefront."""
        return (self.num_stages - 1) / max(1, self.schedule_steps)

    def stage_id(self) -> int:
        # SPMD: every process drives all stages; stage placement is a mesh
        # sharding, not a per-process role (reference: grid.get_stage_id()).
        return 0

    def is_first_stage(self) -> bool:
        return True

    def is_last_stage(self) -> bool:
        return True

    def is_gradient_accumulation_boundary(self) -> bool:
        # the whole schedule (all microbatches) runs inside one jitted step,
        # so every train_batch IS an accumulation boundary
        return True

    # -- reference data-iterator management -------------------------------
    def set_dataiterator(self, iterator) -> None:
        self._data_iter = iterator

    def set_batch_fn(self, fn) -> None:
        """Reference API: transform applied to every batch pulled from the
        data iterator (``train_batch`` wraps the iterator with it)."""
        self._batch_fn = fn

    def reset_activation_shape(self) -> None:
        """Reference API: invalidate cached P2P buffer shapes.  Shapes are
        compiled into the XLA program here; a new shape simply triggers a
        new compile, so there is nothing to reset."""

    def train_batch(self, data_iter=None):
        it = data_iter or self._data_iter
        if it is None and self.training_dataloader is not None:
            from deepspeed_tpu.runtime.dataloader import RepeatingLoader

            self._data_iter = it = iter(RepeatingLoader(self.training_dataloader))
        fn = getattr(self, "_batch_fn", None)
        if fn is not None and it is not None:
            it = (fn(b) for b in it)
        loss = super().train_batch(it)
        return loss

    def eval_batch(self, data_iter=None, **kw):
        it = data_iter or self._data_iter
        if it is None:
            raise ValueError("eval_batch needs data_iter or a prior "
                             "set_dataiterator()")
        fn = getattr(self, "_batch_fn", None)
        if fn is not None:
            it = (fn(b) for b in it)
        return super().eval_batch(it)
