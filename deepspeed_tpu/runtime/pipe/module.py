"""Reference-parity pipeline module API.

Reference: ``deepspeed/runtime/pipe/module.py`` — ``PipelineModule(layers=
[LayerSpec(...), ...], num_stages, partition_method)`` (SURVEY.md §2.1).  The
functional TPU version keeps the LayerSpec construction surface but executes
via the SPMD pipeline (runtime/pipe/spmd.py): layer params are stacked along a
leading [L] dim and sharded over the ``pp`` mesh axis, so the reference's
layer-to-stage partitioner becomes a sharding decision.

Constraint inherited from the stacked representation: specs must build layers
with identical param structure and activation shape (the transformer case).
Heterogeneous stacks (embedding → blocks → head) follow the built-in models'
pattern instead: keep the non-uniform ends outside the pipelined stack
(models/transformer.py does exactly this).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

from deepspeed_tpu.comm.mesh import axis_size, get_global_mesh
from deepspeed_tpu.runtime.pipe.spmd import spmd_pipeline


class LayerSpec:
    """Deferred layer constructor (reference parity: holds class + args,
    builds lazily so stages only materialize their own layers — here,
    building is cheap and sharding handles placement)."""

    def __init__(self, typename: Callable, *module_args, **module_kwargs):
        self.typename = typename
        self.module_args = module_args
        self.module_kwargs = module_kwargs

    def build(self):
        return self.typename(*self.module_args, **self.module_kwargs)


class TiedLayerSpec(LayerSpec):
    """Reference parity: layers sharing params across stages (e.g. embedding
    reused as the LM head).  In the functional model, tied params are stored
    once outside the stacked layer tree and passed to both call sites —
    the tie is a pytree-sharing decision, not a gradient-allreduce protocol."""

    def __init__(self, key: str, typename: Callable, *args, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key


class PipelineModule:
    """Uniform-layer pipeline container.

    Each built layer must expose ``init(rng, x) -> params`` and
    ``apply(params, x) -> y`` with identical param structure and activation
    shapes.  Params are stacked per-leaf along a new leading [L] dim.
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: Optional[int] = None,
                 mesh=None, loss_fn: Optional[Callable] = None,
                 partition_method: str = "uniform", num_microbatches: int = 0):
        self.specs = list(layers)
        self.mesh = mesh or get_global_mesh(create_default=False)
        self.loss_fn = loss_fn
        self.num_microbatches = num_microbatches
        self._layers = [s.build() for s in self.specs]
        pp = axis_size(self.mesh, "pp") if self.mesh is not None else 1
        self.num_stages = num_stages or pp
        if pp > 1 and len(self._layers) % pp != 0:
            raise ValueError(f"{len(self._layers)} layers not divisible by pp={pp}")
        if partition_method not in ("uniform", "parameters"):
            raise ValueError(f"unknown partition_method {partition_method!r}")

    def init(self, rng, x) -> Any:
        rngs = jax.random.split(rng, len(self._layers))
        per_layer = []
        for layer, r in zip(self._layers, rngs):
            p = layer.init(r, x)
            x = jax.eval_shape(layer.apply, p, x)
            x = jnp.zeros(x.shape, x.dtype)
            per_layer.append(p)
        first = jax.tree.structure(per_layer[0])
        for i, p in enumerate(per_layer[1:], 1):
            if jax.tree.structure(p) != first:
                raise ValueError(
                    f"layer {i} param structure differs from layer 0; the SPMD "
                    "pipeline needs uniform layers (see module docstring)")
        return jax.tree.map(lambda *ls: jnp.stack(ls), *per_layer)

    def apply(self, params, x):
        apply0 = self._layers[0].apply

        def stage_fn(wl, xmb, _scan, *bcast):
            def body(c, lp):
                return apply0(lp, c), None
            y, _ = jax.lax.scan(body, xmb, wl)
            return y, jnp.zeros((), jnp.float32)

        y, _aux = spmd_pipeline(stage_fn, params, x, self.mesh,
                                num_microbatches=self.num_microbatches)
        if self.loss_fn is not None:
            return self.loss_fn(y)
        return y

    def __call__(self, params, x):
        return self.apply(params, x)
