"""Pipeline parallelism (reference: ``deepspeed/runtime/pipe/``)."""

from deepspeed_tpu.runtime.pipe.engine import PipelineEngine
from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                               TiedLayerSpec)
from deepspeed_tpu.runtime.pipe.spmd import pp_layer_pspecs, spmd_pipeline

__all__ = ["PipelineEngine", "LayerSpec", "PipelineModule", "TiedLayerSpec",
           "pp_layer_pspecs", "spmd_pipeline"]
