"""SPMD pipeline parallelism: microbatch pipelining inside one XLA program.

TPU-native replacement for the reference's instruction-VM pipeline
(``deepspeed/runtime/pipe/engine.py`` + ``schedule.py`` + ``p2p.py``,
SURVEY.md §2.1, §3.4): instead of a Python scheduler issuing
``SendActivation``/``RecvActivation`` P2P ops per rank, the whole schedule is
one ``lax.scan`` under a FULL-manual ``shard_map`` — stage-to-stage transfers
are explicit ``ppermute`` rings (nearest-neighbor on the ICI torus), the
backward boundary exchange is the reverse ring, and the 1F1B schedule fuses
both wavefronts into one scan whose carries ARE the boundary buffers.

**Full-manual, stage id as data.**  Earlier revisions were manual only over
``pp`` (``axis_names={'pp'}``) and read the stage with ``lax.axis_index`` —
which lowers to the PartitionId HLO the SPMD partitioner rejects on the CPU
backend (the 9 tier-1 ``test_pipe`` failures pinned since PR 9, ROADMAP item
2).  Now the region is manual over EVERY mesh axis and the stage identity is
*data*: a [pp] iota enters with ``in_specs=P(pp)`` so each stage reads its own
id from its slice, and all per-stage behavior is branchless selects over that
id.  No PartitionId, no partial-manual partitioning — the failure class is
gone, not suppressed.  The trade: in-stage GSPMD sharding (tp/fsdp inside the
stage body) degrades to replicated compute inside the region
(``models/layers.py:constrain`` detects manual axes and backs off), which is
exact but redundant — re-sharding the stage interior is the remaining
multi-host slice noted in ROADMAP.

Schedule shape = GPipe fill-drain over ``T = M + pp - 1`` steps with M
microbatches; the bubble fraction is ``(pp-1)/T``, identical to the
reference's default ``TrainSchedule`` cost.  Stage ``s`` processes microbatch
``m`` at step ``t = m + s``; invalid (bubble) steps compute on zeros and are
masked out of outputs and aux losses, contributing zero gradient.

**Boundary transport.**  Every ring hop goes through :func:`_boundary_send`:
dense hops are ``lax.ppermute`` under the unconditional ``ds_comm_ppermute``
named_scope, quantized hops (``quantize_boundary=True`` — the
``comm_quantization.pipeline`` site) re-use the PR 14 carry codec via
``q_boundary_ppermute`` (int8 codes + fp32 block scales on the wire, under
``ds_comm_q_ppermute``; one quantization error per hop since each hop carries
a fresh activation).  ``comm_record`` gates the trace-time byte ledger only —
standalone callers (tests, PipelineModule) default to trace-time recording,
while the engine records through its analytic per-execution comm plan and
passes ``comm_record=False`` so the two feeds stay disjoint (the repo-wide
double-count rule).  The fill/drain RING hops are the recorded boundary
traffic; the final output-replication / scalar-reduce psums are scoped but
not byte-recorded (the engine's plan carries them analytically where it
matters).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.collectives_q import q_boundary_ppermute
from deepspeed_tpu.comm.mesh import axis_size
from deepspeed_tpu.comm.quant import DEFAULT_BLOCK
from deepspeed_tpu.monitor.comms import comm_metrics
from deepspeed_tpu.profiling.trace import scope as _scope


def _stage_ids(pp: int) -> jnp.ndarray:
    """Stage identity as DATA: a [pp] iota that enters the manual region
    with ``in_specs=P(pp)`` so each stage reads its own id from its slice
    (``sid[0]``).  Replaces ``lax.axis_index``, whose PartitionId lowering
    the CPU SPMD partitioner rejects (ROADMAP item 2)."""
    return jnp.arange(pp, dtype=jnp.int32)


def _boundary_send(x, axis: str, perm, *, quantized: bool, block: int,
                   record: bool):
    """One stage-boundary ring hop (dense or int8), always under its
    unconditional ``ds_comm_*`` scope (DSL005)."""
    if quantized:
        return q_boundary_ppermute(x, axis, perm, block=block, record=record)
    if record:
        comm_metrics.record("ppermute", axis, x)
    with _scope("ds_comm_ppermute"):
        return jax.lax.ppermute(x, axis, perm)


def _uneven_msg(B: int, M: int, path: str) -> str:
    return (
        f"batch {B} not divisible by num_microbatches={M}: the {path} path "
        "folds microbatches into scalars and cannot tell padding from data "
        "— pad the batch to a multiple of M with rows your loss masks out "
        "(models/transformer.py pads with label=-1 / mask=0 rows), or pick "
        "a microbatch count that divides the batch")


def spmd_pipeline(stage_fn: Callable, layer_params: Any, x: jnp.ndarray,
                  mesh: Mesh, num_microbatches: int = 0,
                  broadcast_args: Tuple = (), scan_args: Any = None,
                  axis: str = "pp", reduce_fn: Optional[Callable] = None,
                  reduce_xs: Any = None, reduce_consts: Any = (),
                  remat_stage: bool = True,
                  boundary_fp32: Optional[bool] = None,
                  quantize_boundary: bool = False,
                  quant_block: int = DEFAULT_BLOCK,
                  comm_record: bool = True):
    """Run a stacked-layer function pipelined over the ``pp`` mesh axis.

    - ``stage_fn(local_layer_params, x_mb, local_scan_args, *broadcast_args)
      -> (y_mb, aux)``: consumes the local [L/pp, ...] slice of the stacked
      layer params (scanning over it internally) and one microbatch.
    - ``layer_params``: pytree with leading stacked layer dim [L, ...] on
      every leaf; sliced into [L/pp, ...] per stage.
    - ``x``: [B, ...] global batch; split into M microbatches along dim 0.
      When B is not divisible by M, the **output path** zero-pads the batch
      to the next multiple internally and slices the result back to [B]
      (pad rows carry zero cotangent); the scalar-reduce paths cannot do
      this blindly and raise with padding guidance instead.
    - ``scan_args``: optional pytree with leading [L] dim sliced like params
      (e.g. per-layer dropout keys).
    - ``broadcast_args``: replicated extras (e.g. RoPE cos/sin tables).

    Returns (y [B, ...], aux_sum) with y replicated over ``pp``.

    **Loss-in-pipeline** (``reduce_fn``): when given, the last stage folds
    each finished microbatch through ``reduce_fn(y_mb, reduce_xs_mb,
    reduce_consts) -> pytree of scalars`` (e.g. CE loss sums) and only the
    summed scalars are returned — the O(global-batch) replicated output
    buffer disappears (VERDICT r2 weak #5).  The reduce runs branchless on
    every stage and non-last contributions are masked to zero.
    ``reduce_consts`` carries replicated weights the reduce needs (final
    norm, lm head) — traced values must enter the manual region as
    arguments, never as closures.
    Returns (reduced_scalars, aux_sum) in this mode.

    **Memory** (``remat_stage``, default on): the scan over ``T = M + pp - 1``
    steps would otherwise save every step's stage-body internals for backward
    — O(T · layers/stage · activations), the first OOM at real pp/M (VERDICT
    r3 weak #3; the reference's 1F1B schedule exists for the same reason,
    ``(R) runtime/pipe/schedule.py``).  ``jax.checkpoint`` around the stage
    body (and the reduce) bounds per-step residuals to the boundary tensors;
    the stage recomputes in backward, which XLA overlaps with the pipelined
    cotangent flow.  Callers whose ``stage_fn`` already remats internally
    (e.g. the transformer model's tuned per-layer policies) must pass
    ``remat_stage=False`` — an outer save-nothing wrap would override the
    tuned policy and recompute the full stage anyway.

    **Boundary dtype** (``boundary_fp32``, default auto): tensors crossing
    the shard_map entry/exit in bf16 trip an XLA **CPU** backend check
    ("invalid binary instruction opcode copy", jax 0.9 / 2026-07), so the
    CPU backend crosses in fp32.  On TPU the boundary stays in the compute
    dtype — fp32 would double stage-to-stage ICI bytes for a bf16 model
    (VERDICT r3 weak #2).  The in-region ring hops always run the compute
    dtype.

    **Quantized boundary** (``quantize_boundary`` — the
    ``comm_quantization.pipeline`` site): ring hops ship int8 codes + fp32
    block scales (``quant_block``-element blocks) instead of the dense
    activation, forward AND backward (the codec's custom VJP sends the
    cotangent through the reverse ring the same way).  Each hop carries a
    fresh activation so each hop pays one quantization error — loss parity
    holds to quantization tolerance, not bit-exactly.
    """
    if boundary_fp32 is None:
        # Key off the MESH's devices, not jax.default_backend(): the crash
        # is a property of the backend that executes this mesh (a CPU mesh
        # built on a TPU host still compiles with the CPU backend).
        boundary_fp32 = mesh.devices.flat[0].platform == "cpu"
    pp = axis_size(mesh, axis)
    if pp == 1:
        y, aux = stage_fn(layer_params, x, scan_args, *broadcast_args)
        if reduce_fn is not None:
            B = x.shape[0]
            M = num_microbatches or 1
            if B % M:
                raise ValueError(_uneven_msg(B, M, "scalar-reduce"))
            mb = B // M
            red = None
            for m in range(M):
                r = reduce_fn(y[m * mb:(m + 1) * mb],
                              jax.tree.map(lambda a: a[m * mb:(m + 1) * mb],
                                           reduce_xs), reduce_consts)
                red = r if red is None else jax.tree.map(
                    lambda a, b: a + b, red, r)
            return red, aux
        return y, aux
    B = x.shape[0]
    M = num_microbatches or pp
    pad = (-B) % M
    if pad and reduce_fn is not None:
        raise ValueError(_uneven_msg(B, M, "scalar-reduce"))
    if pad:
        # uneven last microbatch: zero-pad to a full grid, slice back below
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    Bp = B + pad
    mb = Bp // M
    T = M + pp - 1
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    # Bound backward residuals to the boundary tensors (see docstring).
    stage_call = (jax.checkpoint(stage_fn, prevent_cse=False) if remat_stage
                  else stage_fn)
    reduce_call = (jax.checkpoint(reduce_fn, prevent_cse=False)
                   if (reduce_fn is not None and remat_stage) else reduce_fn)

    # Replicated (P()) boundary tensors cross in fp32 on the CPU backend
    # only (see docstring); TPU keeps the compute dtype.
    x_dtype = x.dtype
    b_dtypes = tuple(jnp.asarray(a).dtype for a in broadcast_args)
    n_b = len(broadcast_args)

    with_reduce = reduce_fn is not None
    if with_reduce:
        red_shapes = jax.eval_shape(
            lambda y, r, c: reduce_fn(y, r, c),
            jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype),
            jax.tree.map(lambda a: jax.ShapeDtypeStruct((mb,) + a.shape[1:],
                                                        a.dtype), reduce_xs),
            jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(jnp.asarray(a).shape,
                                               jnp.asarray(a).dtype),
                reduce_consts))
    rc_dtypes = (jax.tree.map(lambda a: jnp.asarray(a).dtype, reduce_consts)
                 if with_reduce else jnp.float32)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(axis), P(), P(axis), P(axis))
                       + (P(),) * n_b + (P(), P()),
                       out_specs=(P(), P()),
                       check_vma=False)
    def _pipelined(wl, xg32, sl, sid, *bc32_and_red):
        bc32 = bc32_and_red[:n_b]
        red_xs = bc32_and_red[n_b]
        # replicated consts cross in fp32 (their cotangent psum in bf16
        # trips the same XLA CPU check as the other boundary tensors);
        # restore the original dtypes inside the manual region
        red_consts = jax.tree.map(
            lambda a, dt: a.astype(dt), bc32_and_red[n_b + 1], rc_dtypes)
        xg = xg32.astype(x_dtype)
        broadcast_args = tuple(a.astype(dt) for a, dt in zip(bc32, b_dtypes))
        stage = sid[0]
        is_first = stage == 0
        is_last = stage == pp - 1
        xmb = xg.reshape((M, mb) + xg.shape[1:])
        if with_reduce:
            red_mb = jax.tree.map(
                lambda a: a.reshape((M, mb) + a.shape[1:]), red_xs)

        def step(carry, t):
            buf, outs, red_acc, aux_acc = carry
            m_idx = t - stage
            valid = (m_idx >= 0) & (m_idx < M)
            inp = jnp.where(is_first, xmb[jnp.clip(t, 0, M - 1)], buf)
            out, aux = stage_call(wl, inp, sl, *broadcast_args)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
            o_idx = t - (pp - 1)
            is_out = is_last & (o_idx >= 0)
            if with_reduce:
                # last stage folds the finished microbatch into scalars; the
                # reduce runs branchless on every stage and non-last
                # contributions are masked to zero
                r_xs = jax.tree.map(lambda a: a[jnp.clip(o_idx, 0, M - 1)],
                                    red_mb)
                r = reduce_call(out, r_xs, red_consts)
                red_acc = jax.tree.map(
                    lambda a, v: a + jnp.where(is_out,
                                               v.astype(jnp.float32),
                                               0.0).reshape(a.shape),
                    red_acc, r)
            else:
                # branchless slot write: read the current row, select, write
                # back (a lax.cond here would copy the whole buffer per
                # branch)
                o_clip = jnp.clip(o_idx, 0, M - 1)
                cur = jax.lax.dynamic_slice(
                    outs, (o_clip,) + (0,) * out.ndim, (1,) + out.shape)[0]
                outs = jax.lax.dynamic_update_slice(
                    outs, jnp.where(is_out, out, cur)[None],
                    (o_clip,) + (0,) * out.ndim)
            buf = _boundary_send(out, axis, perm,
                                 quantized=quantize_boundary,
                                 block=quant_block, record=comm_record)
            return (buf, outs, red_acc, aux_acc), None

        buf0 = jnp.zeros((mb,) + xg.shape[1:], xg.dtype)
        outs0 = (jnp.zeros((0,), xg.dtype) if with_reduce
                 else jnp.zeros((M, mb) + xg.shape[1:], xg.dtype))
        # Scalar scan carries become rank-0 residuals that this jax's
        # shard_map TRANSPOSE rule mishandles (_SpecError: names={0: ...} on a
        # rank-0 aval) — carry every scalar as shape (1,) and squeeze outside
        # the manual region.
        red0 = (jax.tree.map(
            lambda s: jnp.zeros(s.shape if s.ndim else (1,), jnp.float32),
            red_shapes) if with_reduce else jnp.zeros((0,)))
        (b, outs, red, aux), _ = jax.lax.scan(
            step, (buf0, outs0, red0, jnp.zeros((1,), jnp.float32)),
            jnp.arange(T))
        # Mean over microbatches so aux losses match the unpipelined full-batch
        # value (each stage contributes only its own layers; the psum over pp
        # is the sum over layers, not a duplication).
        with _scope("ds_comm_psum"):
            aux = jax.lax.psum(aux, axis) / M
        if with_reduce:
            # only scalars cross stages — O(1) instead of O(global batch)
            with _scope("ds_comm_psum"):
                red = jax.tree.map(lambda v: jax.lax.psum(v, axis), red)
            return red, aux
        # Replicate the last stage's outputs across pp.  Exact in any dtype
        # (one nonzero contribution per position); fp32 only where the
        # CPU-backend bug demands it (see docstring).
        if boundary_fp32:
            with _scope("ds_comm_psum"):
                outs = jax.lax.psum(
                    jnp.where(is_last, outs.astype(jnp.float32), 0.0), axis)
            return outs.astype(xg.dtype).reshape((Bp,) + xg.shape[1:]), aux
        with _scope("ds_comm_psum"):
            outs = jax.lax.psum(
                jnp.where(is_last, outs, jnp.zeros_like(outs)), axis)
        return outs.reshape((Bp,) + xg.shape[1:]), aux

    if scan_args is None:
        # shard_map needs a concrete argument; a [L]-length dummy slices fine
        leaves = jax.tree.leaves(layer_params)
        scan_args = jnp.zeros((leaves[0].shape[0],), jnp.uint32)

    def boundary_cast(a):
        a = jnp.asarray(a)
        if not boundary_fp32:
            return a
        return a.astype(jnp.float32) if jnp.issubdtype(a.dtype, jnp.floating) else a

    red_arg = (jax.tree.map(jnp.asarray, reduce_xs) if with_reduce
               else jnp.zeros((0,)))
    const_arg = (jax.tree.map(lambda a: boundary_cast(a), reduce_consts)
                 if with_reduce else jnp.zeros((0,)))
    y, aux = _pipelined(layer_params, boundary_cast(x), scan_args,
                        _stage_ids(pp),
                        *(boundary_cast(a) for a in broadcast_args),
                        red_arg, const_arg)
    aux = aux[0]  # undo the (1,) scalar-carry promotion (see _pipelined)
    if with_reduce:
        y = jax.tree.map(lambda v, s: v.reshape(s.shape), y, red_shapes)
    if not with_reduce and pad:
        y = y[:B]
    return y, aux


# ---------------------------------------------------------------------------
# 1F1B fused schedule
# ---------------------------------------------------------------------------

def spmd_pipeline_1f1b(stage_fn: Callable, loss_mb_fn: Callable,
                       layer_params: Any, x: jnp.ndarray, mesh: Mesh,
                       num_microbatches: int = 0, broadcast_args: Tuple = (),
                       scan_args: Any = None, axis: str = "pp",
                       loss_xs: Any = None, loss_consts: Any = (),
                       aux_coef: float = 0.0,
                       boundary_fp32: Optional[bool] = None,
                       quantize_boundary: bool = False,
                       quant_block: int = DEFAULT_BLOCK,
                       comm_record: bool = True):
    """1F1B pipeline: ONE scan interleaves each step's forward microbatch
    with the backward of the microbatch whose cotangent just arrived,
    exactly the reference ``TrainSchedule``'s steady state
    (``(R) runtime/pipe/schedule.py``), expressed SPMD.  The scan's carries
    ARE the two boundary buffers: the forward activation hop rides the
    forward ring (``(i, i+1)`` ppermute) and the backward cotangent hop
    rides the reverse ring (``(i, i-1)``), both through
    :func:`_boundary_send` (dense scoped ppermute, or the int8 carry codec
    when ``quantize_boundary`` — the ``comm_quantization.pipeline`` site).

    Contract differences from :func:`spmd_pipeline`:

    - ``loss_mb_fn(y_mb, loss_xs_mb, loss_consts) -> scalar``: each finished
      microbatch's *additive* loss contribution (the caller divides by the
      data-only token count BEFORE the pipeline, so contributions sum to the
      final loss).  ``aux_coef`` folds the stage aux losses (MoE) into the
      same scalar.
    - Returns the summed scalar loss.  Differentiable via ``jax.custom_vjp``:
      the fused scan computes the gradients alongside the loss (seeded with
      1.0 — valid because the pipeline output enters the final loss
      linearly), stores them as the VJP residual, and the backward pass just
      scales them by the incoming cotangent.

    Why it exists (VERDICT r4 item 2): autodiff over the GPipe scan stashes
    one stage-boundary tensor per scan step — ``M + pp - 1`` live
    microbatch boundaries between forward and backward.  Here backward of
    microbatch ``m`` at stage ``s`` runs ``2*(pp-1-s)`` steps after its
    forward, so a circular buffer of ``2*pp - 1`` slots suffices no matter
    how large M grows; each backward step recomputes its stage forward from
    the saved boundary (same recompute the GPipe path's ``remat_stage``
    already pays).  Total steps ``M + 2*(pp-1)`` — the reference 1F1B
    fill+drain length.

    Cotangents are returned for ``layer_params``, ``x``, and
    ``loss_consts``; ``scan_args`` (rng keys), ``broadcast_args`` (RoPE
    tables), and ``loss_xs`` (labels/masks) get symbolic zeros — they carry
    no trainable upstream in this framework's models.
    """
    if boundary_fp32 is None:
        boundary_fp32 = mesh.devices.flat[0].platform == "cpu"
    pp = axis_size(mesh, axis)
    B = x.shape[0]
    M = num_microbatches or pp
    if B % M:
        raise ValueError(_uneven_msg(B, M, "fused 1F1B loss"))
    if scan_args is None:
        leaves = jax.tree.leaves(layer_params)
        scan_args = jnp.zeros((leaves[0].shape[0],), jnp.uint32)
    static = _P1F1BStatic(stage_fn, loss_mb_fn, mesh, M, axis, float(aux_coef),
                          bool(boundary_fp32), bool(quantize_boundary),
                          int(quant_block), bool(comm_record))
    return _p1f1b(static, layer_params, jnp.asarray(x),
                  jax.tree.map(jnp.asarray, scan_args),
                  tuple(jnp.asarray(a) for a in broadcast_args),
                  jax.tree.map(jnp.asarray, loss_xs),
                  jax.tree.map(jnp.asarray, loss_consts))


class _P1F1BStatic:
    """Hashable static bundle for the custom_vjp nondiff arg."""

    def __init__(self, stage_fn, loss_mb_fn, mesh, M, axis, aux_coef,
                 boundary_fp32, quantize_boundary=False,
                 quant_block=DEFAULT_BLOCK, comm_record=True):
        self.stage_fn = stage_fn
        self.loss_mb_fn = loss_mb_fn
        self.mesh = mesh
        self.M = M
        self.axis = axis
        self.aux_coef = aux_coef
        self.boundary_fp32 = boundary_fp32
        self.quantize_boundary = quantize_boundary
        self.quant_block = quant_block
        self.comm_record = comm_record
        self._key = (stage_fn, loss_mb_fn, mesh, M, axis, aux_coef,
                     boundary_fp32, quantize_boundary, quant_block,
                     comm_record)

    def __hash__(self):
        return hash(self._key)

    def __eq__(self, other):
        return isinstance(other, _P1F1BStatic) and self._key == other._key


def _zero_cot(a):
    """Symbolic-zero cotangent (float0 for integer leaves)."""
    import numpy as np

    a = jnp.asarray(a)
    if jnp.issubdtype(a.dtype, jnp.floating):
        return jnp.zeros_like(a)
    return np.zeros(a.shape, jax.dtypes.float0)


def _p1f1b_run(static, layer_params, x, scan_args, broadcast_args, loss_xs,
               loss_consts):
    """The fused 1F1B scan: returns (loss, (d_layers, d_x, d_consts))."""
    mesh, axis, M = static.mesh, static.axis, static.M
    stage_fn, loss_mb_fn = static.stage_fn, static.loss_mb_fn
    aux_coef = static.aux_coef
    pp = axis_size(mesh, axis)
    B = x.shape[0]
    mb = B // M
    T2 = M + 2 * (pp - 1)
    C = 2 * pp - 1
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
    x_dtype = x.dtype
    b_dtypes = tuple(a.dtype for a in broadcast_args)
    n_b = len(broadcast_args)
    lc_dtypes = jax.tree.map(lambda a: a.dtype, loss_consts)
    bf32 = static.boundary_fp32
    send = functools.partial(_boundary_send,
                             quantized=static.quantize_boundary,
                             block=static.quant_block,
                             record=static.comm_record)

    def boundary_cast(a):
        if not bf32:
            return a
        return (a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a)

    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=(P(axis), P(), P(axis), P(axis))
                       + (P(),) * n_b + (P(), P()),
                       out_specs=(P(), P(axis), P(), P()),
                       check_vma=False)
    def _fused(wl, xg32, sl, sid, *bc_and_loss):
        bc = tuple(a.astype(dt) for a, dt
                   in zip(bc_and_loss[:n_b], b_dtypes))
        l_xs = bc_and_loss[n_b]
        l_consts = jax.tree.map(lambda a, dt: a.astype(dt),
                                bc_and_loss[n_b + 1], lc_dtypes)
        xg = xg32.astype(x_dtype)
        stage = sid[0]
        is_last = stage == pp - 1
        is_first = stage == 0
        xmb = xg.reshape((M, mb) + xg.shape[1:])
        l_mb = jax.tree.map(lambda a: a.reshape((M, mb) + a.shape[1:]), l_xs)

        def fwd_f(w, i, keys):
            return stage_fn(w, i, keys, *bc)

        def step(carry, t):
            fbuf, bbuf, circ, gw, gx, gc, loss_acc = carry
            # ---- forward wavefront: stage s runs microbatch t - s --------
            m_f = t - stage
            valid_f = (m_f >= 0) & (m_f < M)
            inp = jnp.where(is_first, xmb[jnp.clip(m_f, 0, M - 1)], fbuf)
            circ = jax.lax.dynamic_update_slice(
                circ, inp[None], (t % C,) + (0,) * inp.ndim)
            out, aux = fwd_f(wl, inp, sl)
            # last stage: loss contribution + the cotangent seed for its own
            # backward (which runs THIS step: t_b(last, m) == t_f(last, m))
            lx = jax.tree.map(lambda a: a[jnp.clip(m_f, 0, M - 1)], l_mb)
            lval, vjp_loss = jax.vjp(loss_mb_fn, out, lx, l_consts)
            mask_l = (is_last & valid_f).astype(jnp.float32)
            loss_acc = loss_acc + mask_l * lval.astype(jnp.float32)
            loss_acc = loss_acc + jnp.where(
                valid_f, aux_coef / M * aux.astype(jnp.float32), 0.0)
            dout_l, _dlx, dlc = vjp_loss(mask_l.astype(lval.dtype))
            gc = jax.tree.map(lambda a, d: a + d.astype(jnp.float32), gc, dlc)
            # ---- backward wavefront: stage s runs m = t - (2pp-2-s) ------
            m_b = t - (2 * pp - 2 - stage)
            valid_b = (m_b >= 0) & (m_b < M)
            saved = jax.lax.dynamic_slice(
                circ, (jnp.clip(m_b + stage, 0, T2) % C,) + (0,) * inp.ndim,
                (1,) + inp.shape)[0]
            dout = jnp.where(is_last, dout_l, bbuf)
            dout = jnp.where(valid_b, dout, jnp.zeros_like(dout))
            (_out_r, aux_r), vjp_stage = jax.vjp(
                lambda w, i: fwd_f(w, i, sl), wl, saved)
            daux = jnp.where(valid_b, aux_coef / M, 0.0).astype(aux_r.dtype)
            dw, dinp = vjp_stage((dout.astype(x_dtype), daux))
            gw = jax.tree.map(lambda a, d: a + d.astype(jnp.float32), gw, dw)
            dinp = jnp.where(valid_b, dinp, jnp.zeros_like(dinp))
            # unconditional write of the already-masked dinp (a lax.cond
            # here would copy the whole gx buffer per branch).  Only stage
            # 0's gx survives the psum mask below, and for stage 0 the
            # clipped zero-writes all land in slot 0 before its real write
            # (m_b there never exceeds M-1).
            gx = jax.lax.dynamic_update_slice(
                gx, dinp[None].astype(jnp.float32),
                (jnp.clip(m_b, 0, M - 1),) + (0,) * dinp.ndim)
            # ---- boundary rings: forward ring for the activation, the
            # reverse ring for the cotangent ------------------------------
            fbuf = send(out, axis, fwd_perm)
            bbuf = send(dinp, axis, bwd_perm)
            return (fbuf, bbuf, circ, gw, gx, gc, loss_acc), None

        carry0 = (
            jnp.zeros((mb,) + xg.shape[1:], xg.dtype),
            jnp.zeros((mb,) + xg.shape[1:], x_dtype),
            jnp.zeros((C, mb) + xg.shape[1:], xg.dtype),
            jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), wl),
            jnp.zeros((M, mb) + xg.shape[1:], jnp.float32),
            jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32), l_consts),
            jnp.zeros((), jnp.float32))
        (fb, bb, circ, gw, gx, gc, loss), _ = jax.lax.scan(
            step, carry0, jnp.arange(T2))
        with _scope("ds_comm_psum"):
            loss = jax.lax.psum(loss, axis)
            gx = jax.lax.psum(jnp.where(is_first, gx, jnp.zeros_like(gx)),
                              axis)
            gc = jax.tree.map(
                lambda a: jax.lax.psum(
                    jnp.where(is_last, a, jnp.zeros_like(a)), axis), gc)
        return loss, gw, gx.reshape((B,) + xg.shape[1:]), gc

    loss, gw, gx, gc = _fused(
        layer_params, boundary_cast(x), scan_args, _stage_ids(pp),
        *(boundary_cast(a) for a in broadcast_args),
        jax.tree.map(jnp.asarray, loss_xs),
        jax.tree.map(boundary_cast, loss_consts))
    gw = jax.tree.map(lambda g, p: g.astype(p.dtype), gw, layer_params)
    gx = gx.astype(x.dtype)
    gc = jax.tree.map(lambda g, c: g.astype(c.dtype), gc, loss_consts)
    return loss, (gw, gx, gc)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _p1f1b(static, layer_params, x, scan_args, broadcast_args, loss_xs,
           loss_consts):
    loss, _ = _p1f1b_run(static, layer_params, x, scan_args, broadcast_args,
                         loss_xs, loss_consts)
    return loss


def _p1f1b_fwd(static, layer_params, x, scan_args, broadcast_args, loss_xs,
               loss_consts):
    loss, grads = _p1f1b_run(static, layer_params, x, scan_args,
                             broadcast_args, loss_xs, loss_consts)
    return loss, (grads, scan_args, broadcast_args, loss_xs)


def _p1f1b_bwd(static, res, d):
    (gw, gx, gc), scan_args, broadcast_args, loss_xs = res
    scale = d.astype(jnp.float32)
    return (jax.tree.map(lambda g: (scale * g.astype(jnp.float32)
                                    ).astype(g.dtype), gw),
            (scale * gx.astype(jnp.float32)).astype(gx.dtype),
            jax.tree.map(_zero_cot, scan_args),
            jax.tree.map(_zero_cot, broadcast_args),
            jax.tree.map(_zero_cot, loss_xs),
            jax.tree.map(lambda g: (scale * g.astype(jnp.float32)
                                    ).astype(g.dtype), gc))


_p1f1b.defvjp(_p1f1b_fwd, _p1f1b_bwd)


def pp_layer_pspecs(pspecs: Any, mesh: Mesh, axis: str = "pp") -> Any:
    """Mark the stacked layer dim of every leaf spec with the ``pp`` axis
    (storage placement matches pipeline stage ownership)."""
    if axis_size(mesh, axis) == 1:
        return pspecs

    def mark(spec: P) -> P:
        entries = list(spec) + [None] * max(0, 1 - len(spec))
        if entries[0] is None:
            entries[0] = axis
        return P(*entries)

    return jax.tree.map(mark, pspecs, is_leaf=lambda s: isinstance(s, P))
