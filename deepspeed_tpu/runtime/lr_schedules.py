"""Learning-rate schedules.

TPU-native analog of the reference's ``deepspeed/runtime/lr_schedules.py``
(SURVEY.md §2.1 "LR schedules"): the same schedule types and config keys
(``WarmupLR``, ``WarmupDecayLR``, ``WarmupCosineLR``, ``OneCycle``,
``LRRangeTest``) but expressed as pure ``step -> lr`` functions compatible
with optax's ``Schedule``, so they live inside the jitted train step instead
of mutating optimizer param groups between steps.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp

Schedule = Callable[[Any], Any]

WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
ONE_CYCLE = "OneCycle"
LR_RANGE_TEST = "LRRangeTest"

VALID_LR_SCHEDULES = [WARMUP_LR, WARMUP_DECAY_LR, WARMUP_COSINE_LR, ONE_CYCLE, LR_RANGE_TEST]


def warmup_lr(warmup_min_lr: float = 0.0, warmup_max_lr: float = 0.001,
              warmup_num_steps: int = 1000, warmup_type: str = "log", **_: Any) -> Schedule:
    """Warm up from min to max, then hold (reference ``WarmupLR``)."""
    warmup_num_steps = max(2, warmup_num_steps)

    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        frac = jnp.clip(step / warmup_num_steps, 0.0, 1.0)
        if warmup_type == "log":
            # log-spaced warmup, matching the reference's default
            gamma = jnp.where(frac > 0, jnp.log(1.0 + frac * (math.e - 1.0)), 0.0)
        else:
            gamma = frac
        return warmup_min_lr + (warmup_max_lr - warmup_min_lr) * gamma

    return schedule


def warmup_decay_lr(total_num_steps: int, warmup_min_lr: float = 0.0,
                    warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                    warmup_type: str = "log", **_: Any) -> Schedule:
    """Warmup then linear decay to 0 (reference ``WarmupDecayLR``)."""
    base = warmup_lr(warmup_min_lr, warmup_max_lr, warmup_num_steps, warmup_type)
    total = max(total_num_steps, warmup_num_steps + 1)

    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warm = base(step)
        decay = jnp.clip((total - step) / max(1.0, total - warmup_num_steps), 0.0, 1.0)
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr * decay)

    return schedule


def warmup_cosine_lr(total_num_steps: int, warmup_min_ratio: float = 0.0,
                     warmup_num_steps: int = 1000, cos_min_ratio: float = 0.0001,
                     warmup_max_lr: float = 0.001, **_: Any) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        warm_frac = jnp.clip(step / max(1, warmup_num_steps), 0.0, 1.0)
        warm = (warmup_min_ratio + (1 - warmup_min_ratio) * warm_frac) * warmup_max_lr
        progress = jnp.clip((step - warmup_num_steps) / max(1, total_num_steps - warmup_num_steps),
                            0.0, 1.0)
        cosine = cos_min_ratio + (1 - cos_min_ratio) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_num_steps, warm, warmup_max_lr * cosine)

    return schedule


def one_cycle(cycle_min_lr: float, cycle_max_lr: float, cycle_first_step_size: int = 2000,
              cycle_second_step_size: Optional[int] = None, decay_step_size: int = 0,
              decay_lr_rate: float = 0.0, cycle_momentum: bool = False, **_: Any) -> Schedule:
    """Triangular one-cycle policy (reference ``OneCycle``)."""
    second = cycle_second_step_size if cycle_second_step_size is not None else cycle_first_step_size
    cycle_len = cycle_first_step_size + second

    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        in_cycle = jnp.minimum(step, cycle_len)
        up = jnp.clip(in_cycle / cycle_first_step_size, 0.0, 1.0)
        down = jnp.clip((in_cycle - cycle_first_step_size) / second, 0.0, 1.0)
        tri = jnp.where(in_cycle < cycle_first_step_size,
                        cycle_min_lr + (cycle_max_lr - cycle_min_lr) * up,
                        cycle_max_lr - (cycle_max_lr - cycle_min_lr) * down)
        if decay_step_size > 0:
            decay_steps = jnp.maximum(step - cycle_len, 0.0) / decay_step_size
            tri = tri * (1.0 / (1.0 + decay_lr_rate * decay_steps))
        return tri

    return schedule


def lr_range_test(lr_range_test_min_lr: float = 1e-3, lr_range_test_step_size: int = 2000,
                  lr_range_test_step_rate: float = 1.0, lr_range_test_staircase: bool = False,
                  **_: Any) -> Schedule:
    def schedule(step):
        step = jnp.asarray(step, dtype=jnp.float32)
        interval = step / lr_range_test_step_size
        if lr_range_test_staircase:
            interval = jnp.floor(interval)
        return lr_range_test_min_lr * (1.0 + interval * lr_range_test_step_rate)

    return schedule


_FACTORIES: Dict[str, Callable[..., Schedule]] = {
    WARMUP_LR: warmup_lr,
    WARMUP_DECAY_LR: warmup_decay_lr,
    WARMUP_COSINE_LR: warmup_cosine_lr,
    ONE_CYCLE: one_cycle,
    LR_RANGE_TEST: lr_range_test,
}


def get_lr_schedule(name: str, params: Dict[str, Any]) -> Schedule:
    if name not in _FACTORIES:
        raise ValueError(f"Unknown scheduler type {name!r}; valid: {VALID_LR_SCHEDULES}")
    return _FACTORIES[name](**params)


class LRSchedulerShim:
    """Imperative facade over a functional schedule, for reference API parity
    (``lr_scheduler.step()``, ``get_last_lr()``)."""

    def __init__(self, schedule: Schedule, engine=None):
        self.schedule = schedule
        self._step = 0

    def step(self, increment: int = 1) -> None:
        self._step += increment

    def get_last_lr(self):
        return [float(self.schedule(self._step))]

    def state_dict(self):
        return {"step": self._step}

    def load_state_dict(self, sd):
        self._step = sd["step"]
