"""NVMe tensor swapping (ZeRO-Infinity).

Reference: ``deepspeed/runtime/swap_tensor/`` — ``partitioned_optimizer_swapper``
+ ``pipelined_optimizer_swapper`` over the aio op (SURVEY.md §2.1 "NVMe swap").
"""

from deepspeed_tpu.runtime.swap_tensor.optimizer_swapper import OptimizerStateSwapper

__all__ = ["OptimizerStateSwapper"]
