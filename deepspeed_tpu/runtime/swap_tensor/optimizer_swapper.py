"""Pipelined NVMe swapper for optimizer states.

Reference roles covered (SURVEY.md §2.1 "NVMe swap (ZeRO-Infinity)"):
- ``partitioned_optimizer_swapper.py``: one state file per parameter,
  [master, m, v] fp32 concatenated, O_DIRECT-capable via the aio library.
- ``pipelined_optimizer_swapper.py``: read-ahead of parameter ``i+1`` while
  ``i`` is being stepped, and asynchronous write-back, overlapping NVMe I/O
  with the host optimizer compute.

A small rotating pool of host buffers bounds memory: with ``n_buffers=3``
one buffer is being stepped, one holds the in-flight read-ahead, and one may
still be draining a write.  Reads and writes run on separate aio handles so
waiting for the pending read does not also drain write-backs.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.ops.aio import aio_handle


class OptimizerStateSwapper:
    def __init__(self, swap_dir: str, sizes: List[int], aio_config=None,
                 n_buffers: int = 3, n_slots: int = 3):
        os.makedirs(swap_dir, exist_ok=True)
        self.dir = swap_dir
        self.sizes = sizes
        self.STATES = n_slots  # master + aux slots (adam: m, v)
        kw = {}
        if aio_config is not None:
            kw = dict(block_size=aio_config.block_size,
                      queue_depth=aio_config.queue_depth,
                      num_threads=aio_config.thread_count,
                      single_submit=aio_config.single_submit,
                      overlap_events=aio_config.overlap_events)
        self._read_h = aio_handle(**kw)
        self._write_h = aio_handle(**kw)
        max_elems = max(sizes) * self.STATES if sizes else 0
        self._buffers = [np.empty(max_elems, np.float32) for _ in range(n_buffers)]
        self._buf_of: Dict[int, int] = {}   # leaf index -> buffer slot
        self._pending_read: Optional[int] = None
        self._writes_since_drain = 0

    def _path(self, i: int) -> str:
        return os.path.join(self.dir, f"state_{i}.bin")

    def _nbytes(self, i: int) -> int:
        return self.sizes[i] * self.STATES * 4

    def _claim_slot(self, i: int) -> int:
        slot = i % len(self._buffers)
        # The slot may still back an in-flight write from a previous leaf;
        # drain writes before reuse (cheap: at most every n_buffers leaves).
        if self._writes_since_drain:
            self._write_h.wait()
            self._writes_since_drain = 0
        self._buf_of[i] = slot
        return slot

    # -- init / sync paths --------------------------------------------------
    def initialize(self, i: int, master_flat: np.ndarray) -> None:
        """Create the state file: master = given, moments = 0."""
        buf = np.concatenate([master_flat.astype(np.float32),
                              np.zeros((self.STATES - 1) * self.sizes[i],
                                       np.float32)])
        rc = self._write_h.sync_pwrite(buf, self._path(i))
        assert rc == 0, f"nvme write failed for leaf {i}"

    def read_sync(self, i: int) -> np.ndarray:
        buf = np.empty(self.sizes[i] * self.STATES, np.float32)
        rc = self._read_h.sync_pread(buf, self._path(i))
        assert rc == 0, f"nvme read failed for leaf {i}"
        return buf

    def write_sync(self, i: int, buf: np.ndarray) -> None:
        rc = self._write_h.sync_pwrite(
            np.ascontiguousarray(buf[:self.sizes[i] * self.STATES]), self._path(i))
        assert rc == 0, f"nvme write failed for leaf {i}"

    # -- pipelined path ------------------------------------------------------
    def prefetch(self, i: int) -> None:
        """Submit the async read for leaf i (at most one in flight)."""
        assert self._pending_read is None, "one read-ahead at a time"
        slot = self._claim_slot(i)
        view = self._buffers[slot][:self.sizes[i] * self.STATES]
        self._read_h.async_pread(view, self._path(i))
        self._pending_read = i

    def wait_fetch(self, i: int) -> np.ndarray:
        assert self._pending_read == i, f"leaf {i} was not prefetched"
        rc = self._read_h.wait()
        assert rc == 0, f"nvme read failed for leaf {i}"
        self._pending_read = None
        slot = self._buf_of[i]
        return self._buffers[slot][:self.sizes[i] * self.STATES]

    def writeback(self, i: int, buf: np.ndarray) -> None:
        """Async write-back of a stepped buffer (drained lazily)."""
        self._write_h.async_pwrite(buf[:self.sizes[i] * self.STATES], self._path(i))
        self._writes_since_drain += 1

    def drain(self) -> None:
        rc = self._write_h.wait()
        self._writes_since_drain = 0
        assert rc == 0, "nvme write-back failed"
