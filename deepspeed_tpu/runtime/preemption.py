"""Preemption handling: SIGTERM -> emergency save at the next optimizer
boundary.

TPU preemption is a routine scheduling event, delivered as SIGTERM with a
grace window.  A signal handler cannot checkpoint (saves run collectives
and touch jax state mid-dispatch), so the handler only RAISES A FLAG; the
engine polls it at every optimizer boundary — the same boundary-hook slot
the watchdog and ``/profilez`` captures use — performs one emergency
``save_checkpoint``, and (by default) exits with
:data:`PREEMPTED_EXIT_CODE` so a supervisor (``tools/train_supervisor.py``
or the elastic agent) can distinguish "preempted after a clean save"
from a crash and restart without shrinking the world.

Stdlib-only on purpose: the supervisor runs on boxes without jax and
mirrors the exit-code contract (``DS_PREEMPT_EXIT_CODE`` overrides both
sides).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional

__all__ = ["PREEMPTED_EXIT_CODE", "PreemptionHandler"]

# Exit status of a process that took its emergency save and left on
# purpose.  243 sits above the shell/signal ranges (126-128+N) and below
# 255; tools/train_supervisor.py carries the same default.
PREEMPTED_EXIT_CODE = int(os.environ.get("DS_PREEMPT_EXIT_CODE", "243"))


class PreemptionHandler:
    """Latched SIGTERM flag, polled at optimizer boundaries.

    The handler chains to any previously-installed handler (a host
    framework's own SIGTERM bookkeeping keeps running) and is restored by
    :meth:`uninstall`.  ``install`` is explicit — a library must not take
    over process signals unasked (the flight-recorder rule).
    """

    def __init__(self) -> None:
        self._requested = False
        self.signal_time: Optional[float] = None
        self._installed_signal: Optional[int] = None
        self._prev_handler = None

    # -- signal side ----------------------------------------------------
    def install(self, signum: int = signal.SIGTERM) -> "PreemptionHandler":
        if self._installed_signal == signum:
            return self

        def _handler(sig, frame):
            self._requested = True
            self.signal_time = time.time()
            prev = self._prev_handler
            if callable(prev):
                prev(sig, frame)

        self._prev_handler = signal.signal(signum, _handler)
        self._installed_signal = signum
        return self

    def uninstall(self) -> None:
        if self._installed_signal is None:
            return
        try:
            signal.signal(self._installed_signal,
                          self._prev_handler or signal.SIG_DFL)
        except (ValueError, OSError):  # pragma: no cover - non-main thread
            pass
        self._installed_signal = None
        self._prev_handler = None

    # -- boundary side --------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._requested

    def request(self) -> None:
        """Programmatic preemption (tests, chaos harness): same latch the
        signal sets."""
        self._requested = True
        self.signal_time = time.time()

    def clear(self) -> None:
        self._requested = False
