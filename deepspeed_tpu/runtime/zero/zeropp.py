"""ZeRO++ — quantized collectives wired to ZeRO-3 sharding.

Reference role: DeepSpeed ZeRO++ (``zero_quantized_weights`` /
``zero_quantized_gradients`` / ``zero_hpz_partition_size``; ``(R)
csrc/quantization/quant_reduce.cu``, PAPERS.md EQuARX):

- **qwAG**: forward/backward parameter all-gathers carry int8 blocks +
  fp32 scales instead of bf16 — ~2x fewer bytes on the wire than bf16
  (4x vs fp32).
- **qgRS**: gradient reduce-scatter quantizes once, exchanges int8, and
  reduces in fp32 after dequant (one quantization error per element) —
  the qgZ shape, via ``runtime/comm/quantized.quantized_reduce_scatter``.
- **hpZ**: a secondary copy of the weights lives sharded over a *small*
  partition (``zero_hpz_partition_size`` ranks — intra-host on a pod), so
  the per-microbatch gathers ride the fast local links; only the one
  refresh gather per optimizer step crosses the full ``fsdp`` axis.

TPU-native shape: ZeRO-3 params are *flat per-leaf shards* over the
``fsdp`` mesh axis inside a full-manual ``shard_map`` region (the engine's
``_compile_zeropp_steps``).  Quantized transport is jnp bit math on int8
payloads; the collectives are XLA ``all_gather``/``all_to_all`` over the
named axis — with ``axis_index_groups`` expressing the hpZ subgroups.
All volumes are recorded through the CommsLogger so tests can assert the
reduction.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deepspeed_tpu.comm import collectives_q as cq
from deepspeed_tpu.comm import comm as comm_api
from deepspeed_tpu.profiling.trace import scope as _scope
from deepspeed_tpu.runtime.comm.quantized import (block_dequantize,
                                                  block_quantize)

QUANT_BLOCK = 256


class ZeroPPParams(NamedTuple):
    """The ``params`` field of the engine TrainState under ZeRO++.

    ``primary``: tree of flat fp32 [n_pad] leaves sharded over ``fsdp``
    (each rank materializes [n_pad / P]).  ``secondary_q``/``secondary_s``:
    hpZ secondary copy, present only when ``hpz > 1`` — flat per-rank
    slices stacked over ``fsdp`` (int8 payload + fp32 block scales when
    quantized weights are on, otherwise the payload holds bf16 and the
    scales leaf is a placeholder)."""

    primary: Any
    secondary_q: Any
    secondary_s: Any


def pad_to(n: int, mult: int) -> int:
    return -(-n // mult) * mult


def hpz_groups(P: int, z: int) -> Optional[Tuple[Tuple[int, ...], ...]]:
    """Contiguous subgroups of size ``z`` along the fsdp axis (rank r is in
    group r // z at position r % z)."""
    if z <= 1 or z == P:
        return None
    return tuple(tuple(range(g * z, (g + 1) * z)) for g in range(P // z))


def q_all_gather_flat(local: jnp.ndarray, axis: str,
                      groups=None, block: int = QUANT_BLOCK) -> jnp.ndarray:
    """int8 all-gather of a flat local shard -> flat fp32 concatenation
    (over the whole axis, or each subgroup when ``groups`` is given).
    Thin caller of the comm-layer transport — the qwAG exchange itself is
    ``collectives_q.q_all_gather_flat``; this wrapper only pins the
    ZeRO++ record label so the zpp byte series stay distinct."""
    return cq.q_all_gather_flat(local, axis, groups=groups, block=block,
                                op="zpp_q_all_gather")


def dense_all_gather_flat(local: jnp.ndarray, axis: str, groups=None) -> jnp.ndarray:
    comm_api.comms_logger.record("zpp_all_gather", axis, local)
    with _scope("ds_comm_zpp_all_gather"):
        return lax.all_gather(local, axis, axis=0, tiled=True,
                              axis_index_groups=groups)


def reduce_scatter_flat(full: jnp.ndarray, axis: str, quantized: bool,
                        block: int = QUANT_BLOCK) -> jnp.ndarray:
    """[n_pad] local gradient -> this rank's reduced [n_pad / P] shard.
    The quantized branch is the comm-layer qgRS (quantize once, exchange
    int8, fp32 reduce after dequant — ``collectives_q``)."""
    if quantized:
        return cq.q_reduce_scatter_flat(full, axis, block=block)
    comm_api.comms_logger.record("zpp_reduce_scatter", axis, full)
    with _scope("ds_comm_zpp_reduce_scatter"):
        return lax.psum_scatter(full, axis, scatter_dimension=0, tiled=True)


class ZeroPPConfig(NamedTuple):
    axis: str                 # the sharding axis ("fsdp")
    world: int                # fsdp size P
    hpz: int                  # secondary partition size z (1 = off)
    q_weights: bool
    q_grads: bool
    compute_dtype: Any
    block: int = QUANT_BLOCK


def flatten_spec(shapes_tree: Any, P: int) -> Any:
    """Padded flat length per leaf (static, host-side).  ``shapes_tree``
    holds shape *tuples* as leaves (is_leaf guards them from being treated
    as pytree nodes)."""
    return jax.tree.map(
        lambda shp: pad_to(int(np.prod(shp or (1,))), P * 8), shapes_tree,
        is_leaf=lambda x: isinstance(x, tuple))


def gather_param_tree(zp: ZeroPPParams, cfg: ZeroPPConfig, shapes: Any):
    """In-manual-region: reconstruct the full compute-dtype param tree from
    the per-rank shards (secondary subgroup gather under hpZ, else primary
    full-axis gather)."""
    groups = hpz_groups(cfg.world, cfg.hpz)

    def one(flat_local, sec_q, sec_s, shape):
        n = int(np.prod(shape or (1,)))
        if cfg.hpz > 1:
            # secondary slice length (pre-quant): n_pad / z
            s2 = flat_local.shape[0] * cfg.world // cfg.hpz
            if cfg.q_weights:
                # dense twin: the bf16/compute-dtype slice this subgroup
                # gather replaced (never materialized — shape/dtype only)
                comm_api.comms_logger.record_q(
                    "zpp_q_all_gather(hpz)", cfg.axis, (sec_q, sec_s),
                    jax.ShapeDtypeStruct((s2,), cfg.compute_dtype))
                with _scope("ds_comm_zpp_q_all_gather_hpz"):
                    qg = lax.all_gather(sec_q, cfg.axis, axis=0, tiled=False,
                                        axis_index_groups=groups)
                    sg = lax.all_gather(sec_s, cfg.axis, axis=0, tiled=False,
                                        axis_index_groups=groups)
                parts = (qg.astype(jnp.float32) * sg[..., None]
                         ).reshape(cfg.hpz, -1)
                # strip each rank's quant-block padding before concatenating
                # (inline zero-blocks would otherwise shift every later
                # rank's data — the [:n] slice alone is NOT enough)
                full = parts[:, :s2].reshape(-1)
            else:
                comm_api.comms_logger.record("zpp_all_gather(hpz)",
                                             cfg.axis, sec_q)
                with _scope("ds_comm_zpp_all_gather_hpz"):
                    full = lax.all_gather(sec_q, cfg.axis, axis=0, tiled=True,
                                          axis_index_groups=groups
                                          ).astype(jnp.float32)
        elif cfg.q_weights:
            full = q_all_gather_flat(flat_local.astype(cfg.compute_dtype),
                                     cfg.axis, block=cfg.block)
        else:
            full = dense_all_gather_flat(
                flat_local.astype(cfg.compute_dtype), cfg.axis)
        return full[:n].reshape(shape).astype(cfg.compute_dtype)

    shapes_leaf = lambda x: isinstance(x, tuple)
    if cfg.hpz > 1:
        return jax.tree.map(one, zp.primary, zp.secondary_q, zp.secondary_s,
                            shapes, is_leaf=shapes_leaf)
    return jax.tree.map(lambda fl, shp: one(fl, None, None, shp),
                        zp.primary, shapes)


def refresh_secondary(new_primary: Any, cfg: ZeroPPConfig):
    """Step-boundary hpZ refresh: one full-axis gather of the updated
    weights, then re-slice + (re-)quantize this rank's secondary shard."""
    z = cfg.hpz
    if z <= 1:
        return (), ()

    def one(flat_local):
        n_pad = flat_local.shape[0] * cfg.world
        s2 = n_pad // z
        if cfg.q_weights:
            full = q_all_gather_flat(flat_local.astype(cfg.compute_dtype),
                                     cfg.axis, block=cfg.block)
        else:
            full = dense_all_gather_flat(
                flat_local.astype(cfg.compute_dtype), cfg.axis)
        pos = lax.axis_index(cfg.axis) % z
        mine = lax.dynamic_slice_in_dim(full.reshape(-1), pos * s2, s2)
        if cfg.q_weights:
            q, s, _pad = block_quantize(mine, cfg.block)
            return q, s.reshape(-1)  # normalize to [nb] (block_quantize
            #                          returns [nb, 1] for collective use)
        return mine.astype(jnp.bfloat16), jnp.zeros((), jnp.float32)

    leaves, treedef = jax.tree_util.tree_flatten(new_primary)
    pairs = [one(l) for l in leaves]
    return (jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs]),
            jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs]))


def flat_grads(grad_tree: Any, flat_lens: Any) -> Any:
    """Full-size per-rank grads -> padded flat leaves (ready for RS)."""
    return jax.tree.map(
        lambda g, L: jnp.pad(g.reshape(-1).astype(jnp.float32),
                             (0, L - g.size)),
        grad_tree, flat_lens)
