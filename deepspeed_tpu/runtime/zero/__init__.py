"""ZeRO: partitioning-as-sharding (partition.py), host/NVMe tiering
(offload.py), and the reference param-context API (partition_parameters.py).
Reference: ``deepspeed/runtime/zero/`` (SURVEY.md §2.1)."""

from deepspeed_tpu.runtime.zero.partition_parameters import (  # noqa: F401
    GatheredParameters, Init)
