"""ZeRO-Infinity gradient streaming: per-layer fwd/bwd with host-resident
params AND grads.

Role of ``(R) runtime/swap_tensor/partitioned_param_swapper.py`` +
``parameter_offload.py`` on the backward side (SURVEY.md §2.1 "NVMe swap",
§7.6): the reference fetches each layer's params before use and moves each
layer's grads off-device as soon as autograd produces them.  The
whole-program jax path cannot do that — ``jax.grad`` over the layer scan
materializes the full stacked grad pytree as a device-resident program
output (VERDICT r3 weak #6).

This driver replaces the single program with five small ones, compiled once
and dispatched per layer:

  embed_fwd   (embed, tokens) -> x0
  layer_fwd   (lp_i, x_i) -> (x_{i+1}, aux_i)           [forward loop]
  head_vag    (head, x_L, labels) -> loss, d(head), d(x_L)
  layer_bwd   (lp_i, x_i, ct) -> d(lp_i), ct'            [backward loop,
               recomputes the layer forward: per-layer remat]
  embed_bwd   (embed, tokens, ct) -> d(embed)

Per layer, the host: H2D-copies one layer's params through the
:class:`~deepspeed_tpu.runtime.zero.streaming.ParamStreamer` transport
(double-buffered prefetch — layer i+1's transfer is in flight while layer
i computes; persistent staging slots; optional pinned-host routing and
int8 relay with a fused on-device dequant stage), runs the segment, and
D2H-copies the layer's grads straight into the fp32 numpy accumulators
the host optimizer consumes.  Peak device memory is O(boundary
activations + 2 layers' params + 1 layer's grads) — never O(model).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.zero.streaming import ParamStreamer


class StreamedFwdBwd:
    """Drives per-layer streamed forward+backward for a segmented model.

    ``segments`` is the dict from ``model.stream_segments()``;
    ``layer_shardings`` / ``embed_shardings`` / ``head_shardings`` are
    device-memory NamedSharding trees used for the per-segment H2D puts
    (one layer's specs = stacked specs with the leading [L] dim stripped).

    ``prefetch`` / ``int8`` / ``staging_slots`` / ``quant_block`` are the
    relay knobs threaded into the :class:`ParamStreamer` (config:
    ``offload_param.{prefetch,int8_stream,staging_slots}`` +
    ``offload_optimizer.quant_block``).
    """

    @classmethod
    def from_param_specs(cls, segments: Dict[str, Any], specs, mesh, *,
                         gas: int, use_dropout: bool,
                         **stream_kw) -> "StreamedFwdBwd":
        """Build from a full param-tree PartitionSpec tree (the engine's
        ``_param_specs`` shape): one layer's specs are the stacked specs
        with the leading [L] dim stripped; the head is the tok table when
        embeddings are tied.  Single wiring point for the engine AND the
        8B bench."""
        from jax.sharding import PartitionSpec as P

        from deepspeed_tpu.runtime.zero.partition import shardings_from_pspecs

        layer_specs = jax.tree.map(lambda s: P(*tuple(s)[1:]), specs["layers"])
        head_specs = {"final_norm": specs["final_norm"],
                      "head": (specs["embed"]["tok"] if segments["tied"]
                               else specs["lm_head"])}
        if "lm_head_bias" in specs:
            head_specs["head_bias"] = specs["lm_head_bias"]
        return cls(segments, gas=gas,
                   layer_shardings=shardings_from_pspecs(layer_specs, mesh),
                   embed_shardings=shardings_from_pspecs(specs["embed"], mesh),
                   head_shardings=shardings_from_pspecs(head_specs, mesh),
                   use_dropout=use_dropout, **stream_kw)

    def __init__(self, segments: Dict[str, Any], *, gas: int,
                 layer_shardings, embed_shardings, head_shardings,
                 use_dropout: bool, prefetch: bool = True, int8: bool = False,
                 staging_slots: int = 2, quant_block: int = 256,
                 registry=None):
        self.seg = segments
        self.gas = gas
        self.L = segments["num_layers"]
        self.moe_coef = float(segments["moe_coef"])
        self.tied = segments["tied"]
        self.use_drop = use_dropout and segments["dropout"] > 0
        self._layer_sh = layer_shardings
        self._embed_sh = embed_shardings
        self._head_sh = head_shardings
        self._rope_cache: Dict[Any, Any] = {}
        self.streamer = ParamStreamer(
            layer_shardings, int8=int8, quant_block=quant_block,
            prefetch=prefetch, staging_slots=staging_slots,
            registry=registry)
        self._src_id = None          # identity of the bound host layer tree

        layer_fwd = segments["layer_fwd"]
        head_loss = segments["head_loss"]
        embed_fwd = segments["embed_fwd"]
        use_drop = self.use_drop
        mat = self.streamer.materialize
        mat_aux = self.streamer.materialize_aux

        def lfwd(lp, x, key, cos, sin):
            # mat() is the streamer's fused consumer stage: pinned->device
            # move and/or blockwise dequant, traced INTO this program
            return layer_fwd(mat(lp), x, key, cos, sin, use_drop)

        def lbwd(lp, x, key, cos, sin, ct_y, ct_aux):
            # grads are taken w.r.t. the MATERIALIZED (compute-dtype) layer
            # tree — quantization is a transport codec, not part of the
            # differentiated function
            lp_c = mat(lp)
            _, vjp = jax.vjp(
                lambda lp_, x_: layer_fwd(lp_, x_, key, cos, sin, use_drop),
                lp_c, x)
            g_lp, ct_x = vjp((ct_y, ct_aux))
            return ct_x, g_lp

        def efwd(embed_p, tokens):
            # embed/head ride the SAME aux transport (int8 codes + fused
            # dequant when the relay is int8 — the PR 10 "embed/head stay
            # bf16" gap, closed); dense mode materializes to itself
            return embed_fwd(mat_aux("embed", embed_p), tokens)

        def hvag(head_p, x, labels, mask):
            # grads are taken w.r.t. the MATERIALIZED head tree —
            # quantization is a transport codec, not part of the
            # differentiated function (the lbwd contract)
            head_tree = mat_aux("head", head_p)

            def f(ht, x_):
                # grads scaled 1/gas exactly like the whole-program path
                return head_loss(ht, x_, labels, mask).astype(jnp.float32) / gas

            loss, (g_ht, ct_x) = jax.value_and_grad(f, argnums=(0, 1))(head_tree, x)
            return loss * gas, g_ht, ct_x

        def ebwd(embed_p, tokens, ct_x):
            embed = mat_aux("embed", embed_p)
            _, vjp = jax.vjp(lambda e: embed_fwd(e, tokens), embed)
            (g_embed,) = vjp(ct_x)
            return g_embed

        self._embed_fwd = jax.jit(efwd)
        self._layer_fwd = jax.jit(lfwd)
        self._layer_bwd = jax.jit(lbwd)
        self._head_vag = jax.jit(hvag)
        self._embed_bwd = jax.jit(ebwd)
        # abstract arg specs for each segment, recorded on first run —
        # lets tests lower+compile the per-layer programs and assert the
        # device window (memory_analysis) without holding real arrays
        self.probes: Dict[str, Any] = {}

    @staticmethod
    def _abstract(args):
        from jax.sharding import NamedSharding

        def spec(a):
            if not isinstance(a, jax.Array):
                return jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
            # keep only mesh-wide shardings: committed single-device
            # placements (rng keys etc.) would conflict at lower() time
            sh = a.sharding if isinstance(a.sharding, NamedSharding) else None
            return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)

        return jax.tree.map(spec, args)

    # ------------------------------------------------------------------
    def _rope(self, S: int, dtype):
        key = (S, jnp.dtype(dtype).name)
        if key not in self._rope_cache:
            self._rope_cache[key] = jax.jit(
                lambda: self.seg["rope"](S, dtype))()
        return self._rope_cache[key]

    def _bind_source(self, np_layers) -> None:
        """Refresh the streamer when the host tree changed (the engine
        swaps in a new compute tree every optimizer step; the micro-batches
        within a step reuse one binding — and one int8 quantization)."""
        if self._src_id != id(np_layers):
            self.streamer.refresh(np_layers)
            self._src_id = id(np_layers)

    def _put_nonlayer(self, name: str, tree, shardings):
        """Embed/head H2D through the streamer's aux transport (int8
        codes when the relay is int8; counted on the same relay ledger)."""
        return self.streamer.put_aux(name, tree, shardings,
                                     src_key=self._src_id)

    @staticmethod
    def _acc(buf_tree, grad_tree):
        jax.tree.map(
            lambda buf, g: buf.__iadd__(np.asarray(g, np.float32)),
            buf_tree, grad_tree)

    @staticmethod
    def _acc_indexed(buf_tree, i: int, grad_tree):
        def add(buf, g):
            buf[i] += np.asarray(g, np.float32)

        jax.tree.map(add, buf_tree, grad_tree)

    def _d2h_async(self, tree):
        self.streamer.record_d2h(tree)
        for leaf in jax.tree.leaves(tree):
            try:
                leaf.copy_to_host_async()
            except Exception:
                pass
        return tree

    # ------------------------------------------------------------------
    def run(self, np_params, tokens, labels, loss_mask, rng, acc_tree):
        """One micro-batch fwd+bwd.  Grads accumulate (scaled 1/gas, fp32)
        into ``acc_tree`` (numpy, mirrors the param pytree).  Returns the
        device scalar loss."""
        L = self.L
        compute_dtype = np_params["layers"]["attn"]["wq"].dtype
        cos, sin = self._rope(int(tokens.shape[1]), jnp.dtype(str(compute_dtype)))
        if self.use_drop:
            keys = list(jax.random.split(rng, L))
        else:
            keys = [jnp.zeros((2,), jnp.uint32)] * L

        self._bind_source(np_params["layers"])
        embed_dev = self._put_nonlayer("embed", np_params["embed"],
                                       self._embed_sh)
        if "embed_fwd" not in self.probes:
            self.probes["embed_fwd"] = (
                self._embed_fwd, self._abstract((embed_dev, tokens)))
        x = self._embed_fwd(embed_dev, tokens)
        del embed_dev

        # ---- forward: double-buffered layer streaming (ParamStreamer:
        # prefetch i+1 while i computes; staging slots; int8/pinned) -----
        xs = [x]            # boundary activations (device)
        auxes = []
        lp_last = None      # keep the final layer's device copy for backward
        stream = self.streamer
        stream.prefetch(0)
        for i in range(L):
            if i + 1 < L:   # overlap next layer's H2D with this compute
                stream.prefetch(i + 1)
            lp = stream.take(i)
            if i == 0 and "layer_fwd" not in self.probes:
                self.probes["layer_fwd"] = (
                    self._layer_fwd, self._abstract((lp, x, keys[i], cos, sin)))
            x, aux = self._layer_fwd(lp, x, keys[i], cos, sin)
            xs.append(x)
            auxes.append(aux)
            if i == L - 1:
                lp_last = lp
            del lp

        # ---- head: loss + first cotangent ----------------------------
        head_np = (np_params["embed"]["tok"] if self.tied
                   else np_params["lm_head"])
        ht = {"final_norm": np_params["final_norm"], "head": head_np}
        if "lm_head_bias" in np_params:
            ht["head_bias"] = np_params["lm_head_bias"]
        head_tree = self._put_nonlayer("head", ht, self._head_sh)
        if "head_vag" not in self.probes:
            self.probes["head_vag"] = (
                self._head_vag,
                self._abstract((head_tree, xs[-1], labels, loss_mask)))
        loss, g_head, ct = self._head_vag(head_tree, xs[-1], labels, loss_mask)
        del head_tree
        self._d2h_async(g_head)
        self._acc(acc_tree["final_norm"], g_head["final_norm"])
        if self.tied:
            self._acc(acc_tree["embed"]["tok"], g_head["head"])
        else:
            self._acc(acc_tree["lm_head"], g_head["head"])
        if "head_bias" in g_head:
            self._acc(acc_tree["lm_head_bias"], g_head["head_bias"])
        del g_head

        if self.moe_coef:
            aux_total = jnp.stack(auxes).sum()
            loss = loss + self.moe_coef * aux_total
        ct_aux = jnp.asarray(self.moe_coef / self.gas, jnp.float32)

        # ---- backward: stream layers in reverse (layer L-1's device
        # copy from the forward is still live — no re-upload) -----------
        stream.drop_inflight()   # forward-direction prefetches are stale
        prev_grads: Optional[Any] = None
        prev_idx = -1
        for i in range(L - 1, -1, -1):
            if i - 1 >= 0:
                stream.prefetch(i - 1)
            lp = lp_last if i == L - 1 else stream.take(i)
            lp_last = None
            if "layer_bwd" not in self.probes:
                self.probes["layer_bwd"] = (
                    self._layer_bwd,
                    self._abstract((lp, xs[i], keys[i], cos, sin, ct, ct_aux)))
            ct, g_lp = self._layer_bwd(lp, xs[i], keys[i], cos, sin, ct, ct_aux)
            del lp
            xs[i + 1] = None  # free this boundary activation
            self._d2h_async(g_lp)
            if prev_grads is not None:  # collect while layer i's bwd runs
                self._acc_indexed(acc_tree["layers"], prev_idx, prev_grads)
            prev_grads, prev_idx = g_lp, i
        if prev_grads is not None:
            self._acc_indexed(acc_tree["layers"], prev_idx, prev_grads)

        embed_dev = self._put_nonlayer("embed", np_params["embed"],
                                       self._embed_sh)
        if "embed_bwd" not in self.probes:
            self.probes["embed_bwd"] = (
                self._embed_bwd, self._abstract((embed_dev, tokens, ct)))
        g_embed = self._embed_bwd(embed_dev, tokens, ct)
        del embed_dev
        self._acc(acc_tree["embed"], g_embed)
        return loss
