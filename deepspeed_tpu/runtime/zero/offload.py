"""ZeRO-Offload / ZeRO-Infinity: host-resident optimizer states.

Reference: ``deepspeed/runtime/zero/stage_1_and_2.py`` (cpu_offload) +
``deepspeed/runtime/swap_tensor/*`` (SURVEY.md §2.1 "NVMe swap", §7.6).

Design (TPU-native): the device keeps only compute-dtype (bf16) params and
the gradient accumulator; the fp32 master params and Adam moments live on the
host (``device: cpu``) or on NVMe behind the aio library (``device: nvme``).
The optimizer-boundary step is:

  device grads --(one transfer)--> host
  DeepSpeedCPUAdam (csrc/cpu_adam, threaded C++) steps master/m/v in place
  updated master --cast--> compute dtype --(one transfer)--> device params

For NVMe, per-parameter state files are streamed through a small pinned
buffer pool with read-ahead: while parameter ``i`` is being stepped, the
read for ``i+1`` is in flight on the aio handle (the reference's
``pipelined_optimizer_swapper`` role).

The host step is synchronous with respect to the train loop by nature (the
reference's is too); grad-accumulation amortizes it.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import logger


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(kp) for kp, _ in flat]
    leaves = [v for _, v in flat]
    return paths, leaves, treedef


class OffloadedOptimizer:
    """fp32 master + Adam moments on host RAM or NVMe; steps via cpu_adam.

    ``backend`` ∈ {"cpu", "nvme"}.  For "nvme", ``swap_dir`` holds one state
    file per parameter ([master, m, v] fp32 concatenated) and reads are
    pipelined one parameter ahead through the aio handle.
    """

    def __init__(self, params_host: Any, *, backend: str = "cpu",
                 lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 swap_dir: Optional[str] = None, aio_config=None,
                 pipeline: bool = True):
        assert backend in ("cpu", "nvme"), backend
        self.backend = backend
        self.adam = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps,
                                     weight_decay=weight_decay,
                                     adamw_mode=adamw_mode)
        self.step_count = 0
        self.pipeline = pipeline
        paths, leaves, treedef = _flatten_with_paths(params_host)
        self._paths = paths
        self._treedef = treedef
        self._shapes = [np.asarray(l).shape for l in leaves]
        self._sizes = [int(np.asarray(l).size) for l in leaves]

        if backend == "cpu":
            # explicit copy: device_get hands back read-only buffers, and the
            # C++ step writes through raw pointers
            self._master: List[np.ndarray] = [
                np.array(l, dtype=np.float32, copy=True).reshape(-1)
                for l in leaves]
            self._m = [np.zeros_like(p) for p in self._master]
            self._v = [np.zeros_like(p) for p in self._master]
            self._swapper = None
        else:
            from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper

            assert swap_dir, "nvme offload requires offload_optimizer.nvme_path"
            self._swapper = OptimizerStateSwapper(swap_dir, self._sizes,
                                                  aio_config=aio_config)
            for i, l in enumerate(leaves):
                self._swapper.initialize(
                    i, np.ascontiguousarray(np.asarray(l), np.float32).reshape(-1))
            self._master = self._m = self._v = None
        logger.info("offloaded optimizer: %d tensors, %.1fM elements, backend=%s",
                    len(leaves), sum(self._sizes) / 1e6, backend)

    # ------------------------------------------------------------------
    def step(self, grads_host: List[np.ndarray], lr: Optional[float] = None
             ) -> List[np.ndarray]:
        """One Adam step over all leaves (grads as flat fp32 host arrays, in
        tree-leaf order).  Returns the updated fp32 masters (flat views)."""
        if lr is not None:
            self.adam.lr = lr
        self.step_count += 1
        n = len(self._sizes)
        if self.backend == "cpu":
            for i in range(n):
                g = np.ascontiguousarray(grads_host[i], np.float32).reshape(-1)
                self.adam._native_step(self._master[i], g, self._m[i], self._v[i],
                                       self.step_count) if self.adam._native is not None \
                    else self.adam._numpy_step(self._master[i], g, self._m[i],
                                               self._v[i], self.step_count)
            return self._master

        # NVMe: stream [master, m, v] per leaf with one-ahead read pipelining.
        out: List[np.ndarray] = []
        sw = self._swapper
        sw.prefetch(0)
        for i in range(n):
            buf = sw.wait_fetch(i)
            if self.pipeline and i + 1 < n:
                sw.prefetch(i + 1)
            sz = self._sizes[i]
            master, m, v = buf[:sz], buf[sz:2 * sz], buf[2 * sz:3 * sz]
            g = np.ascontiguousarray(grads_host[i], np.float32).reshape(-1)
            if self.adam._native is not None:
                self.adam._native_step(master, g, m, v, self.step_count)
            else:
                self.adam._numpy_step(master, g, m, v, self.step_count)
            out.append(master.copy())  # buffer is recycled; masters returned
            sw.writeback(i, buf)
        sw.drain()
        return out

    # ------------------------------------------------------------------
    def masters(self) -> List[np.ndarray]:
        """Current fp32 masters (reads from NVMe for the nvme backend)."""
        if self.backend == "cpu":
            return self._master
        out = []
        for i in range(len(self._sizes)):
            buf = self._swapper.read_sync(i)
            out.append(buf[:self._sizes[i]].copy())
        return out

    def state_dict(self) -> Dict[str, Any]:
        masters, ms, vs = [], [], []
        for i in range(len(self._sizes)):
            if self.backend == "cpu":
                masters.append(self._master[i]); ms.append(self._m[i]); vs.append(self._v[i])
            else:
                buf = self._swapper.read_sync(i)
                sz = self._sizes[i]
                masters.append(buf[:sz].copy()); ms.append(buf[sz:2*sz].copy())
                vs.append(buf[2*sz:3*sz].copy())
        return {"master": masters, "exp_avg": ms, "exp_avg_sq": vs,
                "step_count": np.asarray(self.step_count, np.int64)}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.step_count = int(sd["step_count"])
        for i in range(len(self._sizes)):
            master = np.ascontiguousarray(sd["master"][i], np.float32).reshape(-1)
            m = np.ascontiguousarray(sd["exp_avg"][i], np.float32).reshape(-1)
            v = np.ascontiguousarray(sd["exp_avg_sq"][i], np.float32).reshape(-1)
            if self.backend == "cpu":
                self._master[i][:] = master
                self._m[i][:] = m
                self._v[i][:] = v
            else:
                buf = np.concatenate([master, m, v])
                self._swapper.write_sync(i, buf)

    def write_state(self, dirpath: str) -> None:
        """Stream optimizer state to ``dirpath`` one leaf at a time (peak host
        memory = one leaf triple), replacing the materialize-everything
        ``state_dict`` path for checkpointing (VERDICT r2 weak #2)."""
        import json

        os.makedirs(dirpath, exist_ok=True)
        for i in range(len(self._sizes)):
            if self.backend == "cpu":
                master, m, v = self._master[i], self._m[i], self._v[i]
            else:
                buf = self._swapper.read_sync(i)
                sz = self._sizes[i]
                master, m, v = buf[:sz], buf[sz:2 * sz], buf[2 * sz:3 * sz]
            np.save(os.path.join(dirpath, f"leaf{i}.master.npy"), master)
            np.save(os.path.join(dirpath, f"leaf{i}.m.npy"), m)
            np.save(os.path.join(dirpath, f"leaf{i}.v.npy"), v)
        meta = {"step_count": int(self.step_count), "n": len(self._sizes),
                "sizes": [int(s) for s in self._sizes], "backend": self.backend}
        with open(os.path.join(dirpath, "meta.json"), "w") as fh:
            json.dump(meta, fh)

    def read_state(self, dirpath: str) -> None:
        """Streaming inverse of ``write_state``."""
        import json

        with open(os.path.join(dirpath, "meta.json")) as fh:
            meta = json.load(fh)
        assert meta["sizes"] == [int(s) for s in self._sizes], \
            "offload state shape mismatch"
        self.step_count = int(meta["step_count"])
        for i in range(len(self._sizes)):
            master = np.load(os.path.join(dirpath, f"leaf{i}.master.npy"))
            m = np.load(os.path.join(dirpath, f"leaf{i}.m.npy"))
            v = np.load(os.path.join(dirpath, f"leaf{i}.v.npy"))
            if self.backend == "cpu":
                self._master[i][:] = master
                self._m[i][:] = m
                self._v[i][:] = v
            else:
                self._swapper.write_sync(i, np.concatenate([master, m, v]))

    def master_tree(self) -> Any:
        """fp32 masters reassembled into the param pytree (host)."""
        return self.tree_from_masters(self.masters())

    def tree_from_masters(self, masters: List[np.ndarray]) -> Any:
        """Reassemble flat master arrays (e.g. the list ``step`` returns) into
        the param pytree without re-reading state from the backing store."""
        leaves = [np.asarray(m).reshape(s) for m, s in zip(masters, self._shapes)]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)
