"""ZeRO-Offload / ZeRO-Infinity: host-resident optimizer states.

Reference: ``deepspeed/runtime/zero/stage_1_and_2.py`` (cpu_offload) +
``deepspeed/runtime/swap_tensor/*`` (SURVEY.md §2.1 "NVMe swap", §7.6).

Design (TPU-native): the device keeps only compute-dtype (bf16) params and
the gradient accumulator; the fp32 master params and Adam moments live on the
host (``device: cpu``) or on NVMe behind the aio library (``device: nvme``).
The optimizer-boundary step is:

  device grads --(one transfer)--> host
  DeepSpeedCPUAdam (csrc/cpu_adam, threaded C++) steps master/m/v in place
  updated master --cast--> compute dtype --(one transfer)--> device params

For NVMe, per-parameter state files are streamed through a small pinned
buffer pool with read-ahead: while parameter ``i`` is being stepped, the
read for ``i+1`` is in flight on the aio handle (the reference's
``pipelined_optimizer_swapper`` role).

The host step is synchronous with respect to the train loop by nature (the
reference's is too); grad-accumulation amortizes it.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
from deepspeed_tpu.utils.logging import logger


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths = [jax.tree_util.keystr(kp) for kp, _ in flat]
    leaves = [v for _, v in flat]
    return paths, leaves, treedef


class OffloadedOptimizer:
    """fp32 master + optimizer moments on host RAM or NVMe, stepped by the
    native host kernels (cpu_adam / cpu_adagrad / cpu_lion).

    ``backend`` ∈ {"cpu", "nvme"}.  For "nvme", ``swap_dir`` holds one state
    file per parameter ([master, *aux slots] fp32 concatenated) and reads
    are pipelined one parameter ahead through the aio handle.
    ``opt_type`` ∈ {"adam", "adagrad", "lion"} selects the update family
    (reference: DeepSpeedCPUAdam / DeepSpeedCPUAdagrad / DeepSpeedCPULion).
    """

    N_AUX = {"adam": 2, "adagrad": 1, "lion": 1}
    AUX_NAMES = {"adam": ("exp_avg", "exp_avg_sq"), "adagrad": ("exp_avg_sq",),
                 "lion": ("exp_avg",)}
    # which aux slots hold a non-negative second moment (quantized in sqrt
    # space under int8_masters — the Adam8bit convention: sqrt halves the
    # dynamic range a 127-level code must span)
    SQRT_AUX = {"adam": (False, True), "adagrad": (True,), "lion": (False,)}

    def __init__(self, params_host: Any, *, backend: str = "cpu",
                 lr: float = 1e-3, betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, adamw_mode: bool = True,
                 swap_dir: Optional[str] = None, aio_config=None,
                 pipeline: bool = True, pipeline_write: bool = True,
                 opt_type: str = "adam", int8_masters: bool = False,
                 quant_block: int = 256):
        assert backend in ("cpu", "nvme"), backend
        assert opt_type in self.N_AUX, opt_type
        if int8_masters and backend != "cpu":
            raise ValueError("offload_optimizer.int8_masters supports the "
                             "cpu backend (nvme state files stay fp32 — the "
                             "aio path already pipelines its bandwidth)")
        self.int8_masters = bool(int8_masters)
        self.quant_block = int(quant_block)
        self.backend = backend
        self.opt_type = opt_type
        if opt_type == "adam":
            self.adam = DeepSpeedCPUAdam(lr=lr, betas=betas, eps=eps,
                                         weight_decay=weight_decay,
                                         adamw_mode=adamw_mode)
            self._stepper = self.adam
        elif opt_type == "adagrad":
            from deepspeed_tpu.ops.adagrad import DeepSpeedCPUAdagrad

            self.adam = None
            self._stepper = DeepSpeedCPUAdagrad(lr=lr, eps=eps,
                                                weight_decay=weight_decay)
        else:
            from deepspeed_tpu.ops.lion import DeepSpeedCPULion

            self.adam = None
            self._stepper = DeepSpeedCPULion(lr=lr, betas=betas,
                                             weight_decay=weight_decay)
        self.step_count = 0
        self.pipeline = pipeline            # read-ahead (aio pipeline_read)
        self.pipeline_write = pipeline_write  # async write-back
        self.n_aux = self.N_AUX[opt_type]
        paths, leaves, treedef = _flatten_with_paths(params_host)
        self._paths = paths
        self._treedef = treedef
        self._shapes = [np.asarray(l).shape for l in leaves]
        self._sizes = [int(np.asarray(l).size) for l in leaves]

        if backend == "cpu" and self.int8_masters:
            # ZeRO-Infinity int8 host tier: master + moments live as
            # blockwise int8 (q + fp32 block scales) — ~(1+n_aux) bytes/param
            # of host RAM instead of 4*(1+n_aux), and the relay ships the
            # int8 code (engine._step_offload / ParamStreamer dequantize on
            # device).  The step dequantizes one leaf to fp32, runs the
            # native kernel, and requantizes — only O(leaf) fp32 ever exists.
            from deepspeed_tpu.comm.quant import quantize_blockwise_np

            self._master = None
            self._aux = None
            self._swapper = None
            self._master_q: List = []
            self._aux_q: List[List] = [[] for _ in range(self.n_aux)]
            sqrt_aux = self.SQRT_AUX[opt_type]
            for l in leaves:
                a = np.asarray(l, np.float32).reshape(-1)
                self._master_q.append(
                    quantize_blockwise_np(a, self.quant_block))
                for k in range(self.n_aux):
                    self._aux_q[k].append(quantize_blockwise_np(
                        np.zeros_like(a), self.quant_block,
                        sqrt_space=sqrt_aux[k]))
        elif backend == "cpu":
            # explicit copy: device_get hands back read-only buffers, and the
            # C++ step writes through raw pointers
            self._master: List[np.ndarray] = [
                np.array(l, dtype=np.float32, copy=True).reshape(-1)
                for l in leaves]
            self._aux = [[np.zeros_like(p) for p in self._master]
                         for _ in range(self.n_aux)]
            self._swapper = None
        else:
            from deepspeed_tpu.runtime.swap_tensor import OptimizerStateSwapper

            assert swap_dir, "nvme offload requires offload_optimizer.nvme_path"
            self._swapper = OptimizerStateSwapper(swap_dir, self._sizes,
                                                  aio_config=aio_config,
                                                  n_slots=1 + self.n_aux)
            for i, l in enumerate(leaves):
                self._swapper.initialize(
                    i, np.ascontiguousarray(np.asarray(l), np.float32).reshape(-1))
            self._master = None
            self._aux = None
        logger.info("offloaded optimizer: %d tensors, %.1fM elements, "
                    "backend=%s, type=%s%s", len(leaves),
                    sum(self._sizes) / 1e6, backend, opt_type,
                    ", int8 blockwise masters+moments" if self.int8_masters
                    else "")

    # legacy accessors (adam layout) kept for checkpoints/tests
    @property
    def _m(self):
        return self._aux[0] if self._aux is not None else None

    @property
    def _v(self):
        return self._aux[1] if self._aux is not None and self.n_aux > 1 else None

    # -- int8 host-tier codec (comm/quant.py blockwise transport) ------
    def _dequant_master(self, i: int) -> np.ndarray:
        from deepspeed_tpu.comm.quant import dequantize_blockwise_np

        q, s = self._master_q[i]
        return dequantize_blockwise_np(q, s, self._sizes[i])

    def _dequant_aux(self, i: int) -> List[np.ndarray]:
        from deepspeed_tpu.comm.quant import dequantize_blockwise_np

        sqrt_aux = self.SQRT_AUX[self.opt_type]
        return [dequantize_blockwise_np(*self._aux_q[k][i],
                                        n=self._sizes[i],
                                        sqrt_space=sqrt_aux[k])
                for k in range(self.n_aux)]

    def _requant_leaf(self, i: int, master: np.ndarray,
                      aux: List[np.ndarray]) -> None:
        from deepspeed_tpu.comm.quant import quantize_blockwise_np

        sqrt_aux = self.SQRT_AUX[self.opt_type]
        self._master_q[i] = quantize_blockwise_np(master, self.quant_block)
        for k in range(self.n_aux):
            a = aux[k]
            if sqrt_aux[k]:
                # guard tiny negative fp noise out of the sqrt-space code
                a = np.maximum(a, 0.0)
            self._aux_q[k][i] = quantize_blockwise_np(
                a, self.quant_block, sqrt_space=sqrt_aux[k])

    def relay_leaf(self, i: int):
        """(q int8 [nb, block], scale fp32 [nb, 1]) of master leaf ``i`` —
        the int8 relay payload the engine ships H2D with an on-device
        dequant stage instead of a wide compute-dtype array."""
        assert self.int8_masters
        return self._master_q[i]

    def _step_leaf(self, master: np.ndarray, g: np.ndarray, aux: List[np.ndarray]):
        st = self._stepper
        if self.opt_type == "adam":
            if st._native is not None:
                st._native_step(master, g, aux[0], aux[1], self.step_count)
            else:
                st._numpy_step(master, g, aux[0], aux[1], self.step_count)
        else:
            if st._native is not None:
                st._native_step(master, g, aux[0])
            else:
                st._numpy_step(master, g, aux[0])

    # ------------------------------------------------------------------
    # streaming per-leaf API: begin_step -> step_leaf* -> end_step.
    # The engine overlaps D2H grad transfers, the host update, and the H2D
    # param writeback leaf-wise through this interface (reference:
    # pipelined_optimizer_swapper overlap; VERDICT r2 item 4).
    # ------------------------------------------------------------------
    def begin_step(self, lr: Optional[float] = None) -> None:
        if lr is not None:
            self._stepper.lr = lr
        self.step_count += 1
        if self.backend == "nvme" and self._sizes:
            self._swapper.prefetch(0)

    def _fetch_leaf(self, i: int):
        """(master, aux, release_token|None) for leaf i, with read-ahead.
        Under ``int8_masters`` the fp32 views are transient dequants of the
        int8 store; the token routes them back through requantization."""
        if self.backend == "cpu" and self.int8_masters:
            master = self._dequant_master(i)
            aux = self._dequant_aux(i)
            return master, aux, ("q", master, aux)
        if self.backend == "cpu":
            return self._master[i], [a[i] for a in self._aux], None
        buf = self._swapper.wait_fetch(i)
        if self.pipeline and i + 1 < len(self._sizes):
            self._swapper.prefetch(i + 1)
        sz = self._sizes[i]
        master = buf[:sz]
        aux = [buf[(k + 1) * sz:(k + 2) * sz] for k in range(self.n_aux)]
        return master, aux, buf

    def _release_leaf(self, i: int, buf) -> None:
        if buf is None:
            return
        if isinstance(buf, tuple) and buf[0] == "q":
            self._requant_leaf(i, buf[1], buf[2])
            return
        if self.pipeline_write:
            self._swapper.writeback(i, buf)
        else:
            self._swapper.write_sync(i, buf)

    def step_leaf(self, i: int, g: np.ndarray,
                  return_master: bool = True) -> Optional[np.ndarray]:
        """Step one leaf from an fp32 flat grad; returns the fp32 master.
        Under ``int8_masters`` the returned master is the post-requant
        view — exactly what the int8 store (and the relay) now holds, so
        device params and host masters can never drift apart.  A caller
        that only needs the side effect (the engine's int8 relay ships
        ``relay_leaf`` instead) passes ``return_master=False`` to skip
        that O(leaf) dequant."""
        assert g.size == self._sizes[i], (
            f"leaf {i} grad size {g.size} != {self._sizes[i]} (grads must "
            f"follow tree-leaf order — the native kernel would read past "
            f"a short buffer)")
        master, aux, buf = self._fetch_leaf(i)
        self._step_leaf(master, g, aux)
        if not return_master:
            self._release_leaf(i, buf)
            return None
        # copy BEFORE release: an nvme writeback may recycle the buffer the
        # master view aliases into a concurrent prefetch
        out = master if buf is None else master.copy()
        self._release_leaf(i, buf)
        if self.int8_masters:
            return self._dequant_master(i)
        return out

    def step_leaf_bf16(self, i: int, g_bf16: np.ndarray,
                       out_bf16: np.ndarray) -> np.ndarray:
        """Step one leaf from a bf16 flat grad, writing the updated params in
        bf16 straight into ``out_bf16`` — the csrc ``ds_adam_step_bf16g``
        fast path (no fp32 grad conversion, no separate downcast pass)."""
        import ctypes

        assert self.opt_type == "adam" and self.adam is not None
        lib = self.adam._native
        if lib is None or self.int8_masters:
            # numpy fallback, and the int8 store: convert and take the fp32
            # path (the int8 fetch/requant seam lives there; the engine's
            # int8 relay ships relay_leaf(), not this bf16 buffer)
            master = self.step_leaf(i, np.asarray(g_bf16, np.float32).reshape(-1))
            out_bf16[:] = master.astype(out_bf16.dtype)
            return out_bf16
        master, aux, buf = self._fetch_leaf(i)
        b1, b2 = self.adam.betas
        lib.ds_adam_step_bf16g(
            ctypes.c_int64(master.size),
            master.ctypes.data_as(ctypes.c_void_p),
            g_bf16.ctypes.data_as(ctypes.c_void_p),
            out_bf16.ctypes.data_as(ctypes.c_void_p),
            aux[0].ctypes.data_as(ctypes.c_void_p),
            aux[1].ctypes.data_as(ctypes.c_void_p),
            ctypes.c_int64(self.step_count), ctypes.c_float(self.adam.lr),
            ctypes.c_float(b1), ctypes.c_float(b2),
            ctypes.c_float(self.adam.eps), ctypes.c_float(self.adam.weight_decay),
            ctypes.c_int(int(self.adam.adamw_mode)))
        self._release_leaf(i, buf)
        return out_bf16

    def end_step(self) -> None:
        if self.backend == "nvme":
            self._swapper.drain()

    def step(self, grads_host: List[np.ndarray], lr: Optional[float] = None
             ) -> List[np.ndarray]:
        """One optimizer step over all leaves (grads as flat fp32 host
        arrays, in tree-leaf order).  Returns the updated fp32 masters."""
        self.begin_step(lr=lr)
        out = [self.step_leaf(i, np.ascontiguousarray(grads_host[i],
                                                      np.float32).reshape(-1))
               for i in range(len(self._sizes))]
        self.end_step()
        return out

    # ------------------------------------------------------------------
    def masters(self) -> List[np.ndarray]:
        """Current fp32 masters (reads from NVMe for the nvme backend;
        dequantized views of the int8 store under ``int8_masters``)."""
        if self.backend == "cpu" and self.int8_masters:
            return [self._dequant_master(i) for i in range(len(self._sizes))]
        if self.backend == "cpu":
            return self._master
        out = []
        for i in range(len(self._sizes)):
            buf = self._swapper.read_sync(i)
            out.append(buf[:self._sizes[i]].copy())
        return out

    def _leaf_states(self, i: int) -> List[np.ndarray]:
        """[master, *aux] flat views/copies for leaf i (fp32 — checkpoints
        stay format-compatible across int8_masters on/off; the int8 store
        requantizes losslessly on load, since dequantized values are exact
        multiples of their block scale)."""
        if self.backend == "cpu" and self.int8_masters:
            return [self._dequant_master(i)] + self._dequant_aux(i)
        if self.backend == "cpu":
            return [self._master[i]] + [a[i] for a in self._aux]
        buf = self._swapper.read_sync(i)
        sz = self._sizes[i]
        return [buf[k * sz:(k + 1) * sz].copy() for k in range(1 + self.n_aux)]

    def _set_leaf_states(self, i: int, states: List[np.ndarray]) -> None:
        states = [np.ascontiguousarray(s, np.float32).reshape(-1) for s in states]
        if self.backend == "cpu" and self.int8_masters:
            self._requant_leaf(i, states[0], states[1:])
        elif self.backend == "cpu":
            self._master[i][:] = states[0]
            for a, s in zip(self._aux, states[1:]):
                a[i][:] = s
        else:
            self._swapper.write_sync(i, np.concatenate(states))

    def state_dict(self) -> Dict[str, Any]:
        names = ("master",) + self.AUX_NAMES[self.opt_type]
        out: Dict[str, Any] = {name: [] for name in names}
        for i in range(len(self._sizes)):
            for name, arr in zip(names, self._leaf_states(i)):
                out[name].append(arr)
        out["step_count"] = np.asarray(self.step_count, np.int64)
        return out

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        names = ("master",) + self.AUX_NAMES[self.opt_type]
        self.step_count = int(sd["step_count"])
        for i in range(len(self._sizes)):
            self._set_leaf_states(i, [sd[name][i] for name in names])

    def write_state(self, dirpath: str) -> None:
        """Stream optimizer state to ``dirpath`` one leaf at a time (peak host
        memory = one leaf's states), replacing the materialize-everything
        ``state_dict`` path for checkpointing (VERDICT r2 weak #2)."""
        import json

        os.makedirs(dirpath, exist_ok=True)
        names = ("master",) + self.AUX_NAMES[self.opt_type]
        for i in range(len(self._sizes)):
            for name, arr in zip(names, self._leaf_states(i)):
                np.save(os.path.join(dirpath, f"leaf{i}.{name}.npy"), arr)
        meta = {"step_count": int(self.step_count), "n": len(self._sizes),
                "sizes": [int(s) for s in self._sizes], "backend": self.backend,
                "opt_type": self.opt_type}
        with open(os.path.join(dirpath, "meta.json"), "w") as fh:
            json.dump(meta, fh)

    def read_state(self, dirpath: str) -> None:
        """Streaming inverse of ``write_state``."""
        import json

        with open(os.path.join(dirpath, "meta.json")) as fh:
            meta = json.load(fh)
        assert meta["sizes"] == [int(s) for s in self._sizes], \
            "offload state shape mismatch"
        assert meta.get("opt_type", "adam") == self.opt_type, \
            "offload optimizer type mismatch"
        self.step_count = int(meta["step_count"])
        names = ("master",) + self.AUX_NAMES[self.opt_type]
        for i in range(len(self._sizes)):
            self._set_leaf_states(
                i, [np.load(os.path.join(dirpath, f"leaf{i}.{name}.npy"))
                    for name in names])

    def master_tree(self) -> Any:
        """fp32 masters reassembled into the param pytree (host)."""
        return self.tree_from_masters(self.masters())

    def tree_from_masters(self, masters: List[np.ndarray]) -> Any:
        """Reassemble flat master arrays (e.g. the list ``step`` returns) into
        the param pytree without re-reading state from the backing store."""
        leaves = [np.asarray(m).reshape(s) for m, s in zip(masters, self._shapes)]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)
