"""Layer-chunked compute/collective overlap for ZeRO (ROADMAP open item 1).

The GSPMD train step leaves collective *placement* to XLA: ZeRO-3 params are
sharded and the partitioner inserts the all-gathers where it likes — in
practice hoisted to the program head, so the whole parameter tree gathers
before the first matmul and comm serializes against compute.  The device
profiler (PR 5) measures exactly that serialization as
``ds_profile_gap_seconds``.  This module is the consumer of that number
(T3, arXiv:2401.16677; prefetch-while-compute discipline of ZeRO-Infinity,
arXiv:2104.07857): an *explicit* bucketed schedule the compiler cannot
re-serialize, built from the model's streamed per-layer segments
(``model.stream_segments()``, the same contract the ZeRO-Infinity host
tier drives):

- parameter leaves are grouped into ordered **buckets**: the embedding
  piece, then the stacked transformer layers in chunks of
  ``zero_optimization.overlap_bucket_layers`` layers (slices of the
  leading ``[L]`` dim, which the overlap partitioner never shards), then
  the head piece (final norm + lm_head);
- the forward gathers each bucket with explicit per-leaf
  ``lax.all_gather`` collectives inside a full-manual ``shard_map`` —
  bucket *i+1*'s gather is sequenced (via ``lax.optimization_barrier``
  ties) to start no earlier than bucket *i*'s input, so the scheduler may
  run it concurrently with bucket *i*'s matmuls but cannot hoist the whole
  tree to the program head;
- the backward needs no hand-scheduled collectives for ZeRO-3: the AD
  transpose of a tiled ``all_gather`` IS ``psum_scatter`` — each bucket's
  gradient reduce-scatter materializes exactly where that bucket's
  backward produces its gradients, interleaved with the remaining
  backward compute (emitted via :func:`_scoped_all_gather`'s custom VJP
  so it carries its own ``ds_comm_reduce_scatter`` scope instead of
  inheriting the forward gather's).  Layer buckets are wrapped in ``jax.checkpoint`` so
  the backward re-gathers (the ZeRO-3 2x-gather schedule) instead of
  holding gathered params as residuals;
- stages 1/2 (replicated params) skip the forward gathers; their per-
  bucket gradient reduction (``psum_scatter`` into the sharded stage-2
  accumulator, ``pmean`` for stage 1) is applied per bucket on the
  separate per-bucket grad values the bucketed forward yields, chained on
  a virtual comm stream by barriers so the ops stay distinct (no combiner
  re-serialization) while each may start as soon as its bucket's backward
  finishes.

Every gather/reduce is wrapped in the ``ds_comm_<op>`` ``jax.named_scope``
the device-trace post-processor matches, so ``/profilez`` captures show
the per-bucket schedule and the measured comm/compute overlap lands in
``ds_overlap_hidden_comm_seconds_est``.

Loss semantics are identical to the GSPMD path (same segments, same
1/gas scaling, global-batch-mean gradients); only the schedule differs.
The engine activates this path when ``zero_optimization.overlap_comm`` is
true and the configuration is eligible (see ``overlap_inactive_reason``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm import collectives_q as cq
from deepspeed_tpu.runtime.zero.partition import choose_pspec, params_pspecs
from deepspeed_tpu.utils.logging import logger

__all__ = ["OverlapSchedule", "QCommOpts", "plan_buckets",
           "layerwise_pspecs", "unpack_lm_batch"]

# the data-parallel axes the overlap step is manual over; param shards live
# on SHARD_AXIS (the ZeRO convention everywhere else in runtime/zero)
DATA_AXES = ("dp", "fsdp", "ep")
SHARD_AXIS = "fsdp"
# sentinel claiming the stacked-layer dim during spec choice (stripped
# before the spec leaves this module)
_LAYER_DIM = "__overlap_layer_dim__"


def plan_buckets(num_layers: int, bucket_layers: int) -> List[Tuple[int, int]]:
    """Contiguous ``[start, stop)`` layer ranges covering ``num_layers``."""
    bl = max(1, int(bucket_layers))
    return [(i, min(i + bl, num_layers)) for i in range(0, num_layers, bl)]


def unpack_lm_batch(batch):
    """(tokens, labels, loss_mask) for the LM batch forms the built-in
    models accept, or None for forms the segment-driven schedule cannot
    route (same contract as the streamed-offload driver)."""
    if isinstance(batch, (tuple, list)) and len(batch) == 2:
        return batch[0], batch[1], None
    if isinstance(batch, dict) and "tokens" in batch and "labels" in batch:
        return batch["tokens"], batch["labels"], batch.get("loss_mask")
    return None


def layerwise_pspecs(params: Any, mesh: Mesh, shard: bool,
                     persistence_threshold: int = 0,
                     logical_specs: Any = None) -> Any:
    """``params_pspecs`` variant that never shards dim 0 of stacked-layer
    leaves: the bucketed schedule slices layer ranges along that dim inside
    the manual region, which requires it device-local.  Non-layer leaves
    keep the standard chooser."""
    specs = params_pspecs(params, mesh, shard=shard,
                          persistence_threshold=persistence_threshold,
                          logical_specs=logical_specs)
    if not shard or not (isinstance(params, dict) and "layers" in params):
        return specs

    overridden: List[str] = []

    def spec_for(leaf, logical):
        nd = getattr(leaf, "ndim", len(getattr(leaf, "shape", ())))
        base = list(logical) if logical is not None else [None] * nd
        while len(base) < nd:
            base.append(None)
        if base[0] is None:
            base[0] = _LAYER_DIM
        s = choose_pspec(leaf.shape, mesh, min_size=persistence_threshold,
                         existing=P(*base))
        out = list(s)
        if out and out[0] is not None:
            # dim 0 is device-local, PERIOD: _split slices layer ranges
            # along it inside the manual region.  A client logical spec
            # claiming it with a real mesh extent is overridden loudly
            # (an extent-1 claim is placement-identical to None).
            if out[0] != _LAYER_DIM:
                axes = (out[0] if isinstance(out[0], (tuple, list))
                        else (out[0],))
                if any(mesh.shape.get(a, 1) > 1 for a in axes):
                    overridden.append(str(out[0]))
            out[0] = None
        return P(*out)

    lspecs = (logical_specs.get("layers")
              if isinstance(logical_specs, dict) else None)
    if lspecs is None:
        layers = jax.tree.map(lambda l: spec_for(l, None), params["layers"])
    else:
        layers = jax.tree.map(spec_for, params["layers"], lspecs)
    if overridden:
        logger.warning(
            "overlap_comm: %d stacked-layer leaves claimed sharding on the "
            "layer dim (axes %s) via logical_pspecs — overridden to "
            "device-local (the bucketed schedule slices that dim in-region)",
            len(overridden), sorted(set(overridden)))
    out = dict(specs)
    out["layers"] = layers
    return out


# ---------------------------------------------------------------------------
# spec helpers
# ---------------------------------------------------------------------------


def _sharded_dims(spec: P, mesh: Mesh) -> List[Tuple[int, str]]:
    """(dim, axis) pairs with mesh extent > 1 — the dims a leaf actually
    communicates over."""
    out = []
    for dim, part in enumerate(tuple(spec)):
        if part is None:
            continue
        for ax in (part if isinstance(part, (tuple, list)) else (part,)):
            if mesh.shape.get(ax, 1) > 1:
                out.append((dim, ax))
    return out


def _tie(tree: Any, anchor: Any) -> Any:
    """Barrier-tie: ``tree``'s values become available no earlier than
    ``anchor`` — the sequencing primitive pinning gather *i+1* behind
    bucket *i*'s input (forward) and reduce *k* behind reduce *k+1*'s
    output (backward/comm chain).  Differentiable via the compat-shim
    ``optimization_barrier`` AD rules (utils/compat.py)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    out = jax.lax.optimization_barrier(tuple(leaves) + (anchor,))
    return jax.tree_util.tree_unflatten(treedef, out[:-1])


def _tiled_gathers(leaf, dims_axes):
    # the ds_comm_all_gather scope lives HERE, inside the custom-VJP body,
    # not at the call site: the bwd rule's ops inherit the call site's
    # name stack, so a call-site scope would prefix the backward
    # reduce-scatter with ds_comm_all_gather too and the device-trace
    # matcher (which collects EVERY ds_comm_<op> in the op name) would
    # double-attribute it
    with jax.named_scope("ds_comm_all_gather"):
        for dim, ax in dims_axes:
            leaf = jax.lax.all_gather(leaf, ax, axis=dim, tiled=True)
    return leaf


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _scoped_all_gather(leaf, dims_axes):
    """Tiled all-gather over each (dim, axis) with a custom VJP: the AD
    transpose of ``all_gather`` IS ``psum_scatter``, but the automatic
    transpose inherits the FORWARD'S ``ds_comm_all_gather`` named scope
    (HLO op_name ``transpose(jvp(ds_comm_all_gather))/reduce-scatter``),
    which the device-trace matcher would misattribute.  The custom bwd
    emits the same ``psum_scatter`` under its own
    ``ds_comm_reduce_scatter`` scope so per-op device series stay honest."""
    return _tiled_gathers(leaf, dims_axes)


def _scoped_all_gather_fwd(leaf, dims_axes):
    return _tiled_gathers(leaf, dims_axes), None


def _scoped_all_gather_bwd(dims_axes, _res, ct):
    with jax.named_scope("ds_comm_reduce_scatter"):
        for dim, ax in reversed(dims_axes):
            ct = jax.lax.psum_scatter(ct, ax, scatter_dimension=dim,
                                      tiled=True)
    return (ct,)


_scoped_all_gather.defvjp(_scoped_all_gather_fwd, _scoped_all_gather_bwd)


class QCommOpts(NamedTuple):
    """Quantized-transport switches for the bucketed schedule
    (``comm_quantization`` config block -> engine -> here).  ``all_gather``
    quantizes the per-bucket forward parameter gathers (int8 codes + fp32
    block scales on the wire — the ZeRO++ qwAG shape composed with the
    bucketed stream); ``reduce_scatter`` quantizes the AD-transpose /
    stage-2 gradient reduce-scatters (the qgZ shape).  Byte accounting
    stays on the analytic per-execution comm plan (``comm_plan_entries``
    emits q ops with dense-twin bytes), so the collectives here run with
    ``record=False`` — the trace-time and per-execution feeds never
    double-count (monitor/comms.py contract)."""

    all_gather: bool = False
    reduce_scatter: bool = False
    block: int = 256


def _q_tiled_gathers(leaf, dims_axes, block):
    # scope lives inside collectives_q (ds_comm_q_all_gather) — same
    # custom-VJP reasoning as _tiled_gathers: the bwd must not inherit it
    for dim, ax in dims_axes:
        leaf = cq.q_all_gather_dim(leaf, ax, dim, block=block,
                                   record=False)
    return leaf


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _scoped_all_gather_q(leaf, dims_axes, block, q_fwd, q_bwd):
    """Quantized-transport twin of :func:`_scoped_all_gather`: the
    forward gather ships int8 codes when ``q_fwd``; the custom bwd emits
    the per-bucket reduce-scatter as a quantized exchange when ``q_bwd``
    (cotangents leave the producing bucket as codes) — each under its own
    ``ds_comm_q_*`` scope so per-op device series stay honest."""
    if q_fwd:
        return _q_tiled_gathers(leaf, dims_axes, block)
    return _tiled_gathers(leaf, dims_axes)


def _scoped_all_gather_q_fwd(leaf, dims_axes, block, q_fwd, q_bwd):
    return _scoped_all_gather_q(leaf, dims_axes, block, q_fwd, q_bwd), None


def _scoped_all_gather_q_bwd(dims_axes, block, q_fwd, q_bwd, _res, ct):
    if q_bwd:
        for dim, ax in reversed(dims_axes):
            ct = cq.q_reduce_scatter_dim(ct, ax, dim, block=block,
                                         record=False)
        return (ct,)
    with jax.named_scope("ds_comm_reduce_scatter"):
        for dim, ax in reversed(dims_axes):
            ct = jax.lax.psum_scatter(ct, ax, scatter_dimension=dim,
                                      tiled=True)
    return (ct,)


_scoped_all_gather_q.defvjp(_scoped_all_gather_q_fwd,
                            _scoped_all_gather_q_bwd)


class BucketInfo(NamedTuple):
    """One schedule bucket, for tests / the analytic comm plan."""

    name: str
    kind: str                 # "embed" | "layers" | "head"
    start: int                # layer range (kind == "layers" only)
    stop: int
    gathers_per_micro: int    # 2 = rematerialized (backward re-gathers)


class OverlapSchedule:
    """Bucketed compute/collective schedule for one engine configuration.

    Built once at state init (``DeepSpeedEngine._setup_overlap``); provides
    the accum body the engine compiles under full-manual ``shard_map``, the
    leaf->bucket assignment, and the chunked analytic comm-plan entries.
    """

    def __init__(self, *, segments: Dict[str, Any], params: Any,
                 param_specs: Any, acc_specs: Any, mesh: Mesh,
                 zero_stage: int, compute_dtype, bucket_layers: int,
                 use_dropout: bool, remat: bool,
                 qcomm: QCommOpts = QCommOpts()):
        self.seg = segments
        self.qcomm = qcomm
        self.mesh = mesh
        self.zero_stage = zero_stage
        self.compute_dtype = compute_dtype
        self.L = int(segments["num_layers"])
        self.buckets = plan_buckets(self.L, bucket_layers)
        self.tied = bool(segments["tied"])
        self.moe_coef = float(segments["moe_coef"])
        self.use_dropout = use_dropout and segments["dropout"] > 0
        self.remat = remat
        self.param_specs = param_specs
        self.acc_specs = acc_specs
        self._shapes = jax.tree.map(lambda a: tuple(a.shape), params)
        self._has_lm_head = "lm_head" in params
        self._has_head_bias = "lm_head_bias" in params

    # -- structure ------------------------------------------------------
    def _split(self, params: Any) -> Dict[str, Any]:
        """Params tree -> ordered pieces the forward consumes; the layer
        buckets are static slices of the stacked [L] leaves (dim 0 is
        device-local by construction — see :func:`layerwise_pspecs`)."""
        head = {"final_norm": params["final_norm"]}
        if self._has_lm_head:
            head["lm_head"] = params["lm_head"]
        if self._has_head_bias:
            head["lm_head_bias"] = params["lm_head_bias"]
        return {
            "embed": params["embed"],
            "buckets": [jax.tree.map(
                lambda a: jax.lax.slice_in_dim(a, b0, b1, axis=0),
                params["layers"]) for b0, b1 in self.buckets],
            "head": head,
        }

    def _split_specs(self, specs: Any) -> Dict[str, Any]:
        head = {"final_norm": specs["final_norm"]}
        if self._has_lm_head:
            head["lm_head"] = specs["lm_head"]
        if self._has_head_bias:
            head["lm_head_bias"] = specs["lm_head_bias"]
        return {"embed": specs["embed"],
                "buckets": [specs["layers"]] * len(self.buckets),
                "head": head}

    def bucket_infos(self) -> List[BucketInfo]:
        infos = [BucketInfo("embed", "embed", 0, 0, 1)]
        for i, (b0, b1) in enumerate(self.buckets):
            infos.append(BucketInfo(f"layers[{b0}:{b1}]", "layers", b0, b1,
                                    2 if self.remat else 1))
        infos.append(BucketInfo("head", "head", 0, 0, 1))
        return infos

    def bucket_assignment(self) -> Dict[str, str]:
        """Flat ``param leaf id -> bucket name`` map (test surface: every
        leaf lands in exactly one bucket, buckets follow layer order).
        Stacked-layer leaves are identified per layer range, so the ranges
        of one stacked leaf must partition ``[0, L)``."""
        out = {}

        def add(tree, prefix, bucket):
            for path, _ in jax.tree_util.tree_leaves_with_path(
                    tree, is_leaf=lambda x: isinstance(x, tuple)):
                out[prefix + jax.tree_util.keystr(path)] = bucket

        add(self._shapes["embed"], "embed", "embed")
        for b0, b1 in self.buckets:
            add(self._shapes["layers"], f"layers[{b0}:{b1}]",
                f"layers[{b0}:{b1}]")
        for key in ("final_norm", "lm_head", "lm_head_bias"):
            if key in self._shapes:
                add(self._shapes[key], key, "head")
        return out

    # -- analytic comm plan (chunked) -----------------------------------
    def comm_plan_entries(self) -> List[Tuple[str, int, int, str, int]]:
        """Per-bucket ``(op, calls, bytes, dtype, world)`` micro entries for
        the ``ds_comm_*`` ledger — one entry per bucket per direction, so
        call counts and bytes reflect the chunked schedule, not one
        tree-wide op.  Bytes are in the compute dtype (the dtype the
        explicit collectives actually move; the GSPMD plan counted the
        stage>=2 reduce in the accumulation dtype because GSPMD reduced the
        accumulator — here the reduce-scatter is the gather's transpose on
        the compute-dtype cotangent).  Leaves replicated in BOTH layouts
        reduce via pmean and land in per-bucket ``all_reduce`` entries.
        Boundary entries are unchanged by the overlap path (the engine
        composes them separately)."""
        mesh = self.mesh
        c_item = jnp.dtype(self.compute_dtype).itemsize
        cname = jnp.dtype(self.compute_dtype).name
        dp_world = 1
        for a in DATA_AXES:
            dp_world *= mesh.shape.get(a, 1)

        def piece_shapes(kind, start=0, stop=0):
            if kind == "layers":
                frac = (stop - start) / max(1, self.L)
                return self._shapes["layers"], self.param_specs["layers"], \
                    self.acc_specs["layers"], frac
            if kind == "embed":
                return self._shapes["embed"], self.param_specs["embed"], \
                    self.acc_specs["embed"], 1.0
            keys = [k for k in ("final_norm", "lm_head", "lm_head_bias")
                    if k in self._shapes]
            return ({k: self._shapes[k] for k in keys},
                    {k: self.param_specs[k] for k in keys},
                    {k: self.acc_specs[k] for k in keys}, 1.0)

        micro: List[Tuple[str, int, int, str, int]] = []
        for info in self.bucket_infos():
            shapes, pspec, aspec, frac = piece_shapes(info.kind, info.start,
                                                      info.stop)
            flat_sh = jax.tree_util.tree_leaves(
                shapes, is_leaf=lambda x: isinstance(x, tuple))
            flat_p = jax.tree_util.tree_leaves(
                pspec, is_leaf=lambda s: isinstance(s, P))
            flat_a = jax.tree_util.tree_leaves(
                aspec, is_leaf=lambda s: isinstance(s, P))
            g_rows, r_rows, ar_rows = [], [], []

            def rest_world(dims):
                w = 1
                used = {ax for _, ax in dims}
                for a in DATA_AXES:
                    if a not in used:
                        w *= mesh.shape.get(a, 1)
                return w

            for shape, ps, asp in zip(flat_sh, flat_p, flat_a):
                nbytes = int((int(np.prod(shape)) if shape else 1)
                             * c_item * frac)
                gdims = _sharded_dims(ps, mesh)
                adims = _sharded_dims(asp, mesh)
                if gdims:
                    w = 1
                    for _, ax in gdims:
                        w *= mesh.shape.get(ax, 1)
                    g_rows.append((nbytes, w))
                    r_rows.append((nbytes, w))   # the gather's transpose
                    # residual pmean over the data axes the scatter did not
                    # cover (_reduce_tree's rest-axis all_reduce) — on the
                    # shard-sized cotangent
                    rw = rest_world(gdims)
                    if rw > 1:
                        ar_rows.append((max(1, nbytes // w), rw))
                elif adims:
                    w = 1
                    for _, ax in adims:
                        w *= mesh.shape.get(ax, 1)
                    r_rows.append((nbytes, w))
                    rw = rest_world(adims)
                    if rw > 1:
                        ar_rows.append((max(1, nbytes // w), rw))
                elif dp_world > 1:
                    ar_rows.append((nbytes, dp_world))

            qc = self.qcomm

            def qbytes(nbytes: int) -> int:
                # int8 codes + one fp32 scale per block, per element
                return int(nbytes / c_item * (1 + 4.0 / qc.block))

            def add(op, rows, mult=1, quantized=False):
                if not rows:
                    return
                dense = mult * sum(b for b, _ in rows)
                world = max(w for _, w in rows)
                calls = mult * len(rows)
                if quantized:
                    # quantized transport: q op slug, wire bytes =
                    # codes+scales, dense twin rides as the 6th element
                    # as (bytes, dense dtype) so the twin series' dtype
                    # label matches record_q's (CommMetrics.commit)
                    micro.append((f"q_{op}", calls, qbytes(dense), "int8",
                                  world, (dense, cname)))
                else:
                    micro.append((op, calls, dense, cname, world))

            if self.zero_stage == 3:
                add("all_gather", g_rows, mult=info.gathers_per_micro,
                    quantized=qc.all_gather)
            if self.zero_stage >= 2:
                add("reduce_scatter", r_rows,
                    quantized=qc.reduce_scatter)
            else:
                ar_rows = ar_rows + r_rows   # stage<2: everything pmeans
            add("all_reduce", ar_rows)
        return micro

    def hideable_comm_fraction(self) -> float:
        """Fraction of per-micro collective bytes the schedule can overlap
        with compute: everything except the first bucket's forward gather
        (nothing precedes it) and the final reduction (nothing follows it
        inside the micro-step).  Analytic — the measured number is the
        device-trace ``overlapped_comm_s``."""
        entries = self.comm_plan_entries()
        total = sum(e[2] for e in entries)
        if not total:
            return 0.0
        gathers = [e for e in entries if e[0].endswith("all_gather")]
        reduces = [e for e in entries if not e[0].endswith("all_gather")]
        exposed = 0
        if gathers:
            exposed += gathers[0][2]   # first bucket's gather (conservative)
        if reduces:
            # entries run embed -> layers -> head (forward order); the
            # backward reduces head-FIRST and embed-LAST, so the embed
            # bucket's reduce (entry order [0]) is the temporally final,
            # truly exposed one
            exposed += reduces[0][2]
        return max(0.0, 1.0 - exposed / total)

    # -- collectives ----------------------------------------------------
    def _gather_tree(self, tree: Any, spec_tree: Any) -> Any:
        """Cast to compute dtype then all-gather each leaf's sharded dims
        (tiled ring gather; its transpose is the per-bucket reduce-scatter
        the backward needs)."""
        mesh = self.mesh
        cdtype = self.compute_dtype
        qc = self.qcomm

        def g(leaf, spec):
            if (jnp.issubdtype(leaf.dtype, jnp.floating)
                    and leaf.dtype != cdtype):
                leaf = leaf.astype(cdtype)
            dims = tuple((d, a) for d, a in _sharded_dims(spec, mesh))
            if dims:
                if qc.all_gather or qc.reduce_scatter:
                    leaf = _scoped_all_gather_q(leaf, dims, qc.block,
                                                qc.all_gather,
                                                qc.reduce_scatter)
                else:
                    leaf = _scoped_all_gather(leaf, dims)
            return leaf

        return jax.tree.map(g, tree, spec_tree)

    def _reduce_tree(self, gtree: Any, spec_tree: Any,
                     acc_spec_tree: Any) -> Any:
        """Normalize one bucket's raw backward grads to the global-batch
        MEAN in the accumulator's layout.  Three leaf cases:

        - gathered in forward (stage 3 sharded leaf): the ``all_gather``
          transpose already reduce-scattered over ``fsdp`` — divide by the
          fsdp extent and pmean the remaining data axes;
        - replicated param, sharded accumulator (stage 2): explicit
          ``psum_scatter`` on the accumulator's sharded dim;
        - replicated accumulator: plain pmean (all-reduce).
        """
        mesh = self.mesh

        def r(g, pspec, aspec):
            gathered = _sharded_dims(pspec, mesh)
            if gathered:
                w = 1
                for _, ax in gathered:
                    w *= mesh.shape.get(ax, 1)
                rest = tuple(a for a in DATA_AXES
                             if a not in {ax for _, ax in gathered})
                g = g / w
                if any(mesh.shape.get(a, 1) > 1 for a in rest):
                    with jax.named_scope("ds_comm_all_reduce"):
                        g = jax.lax.pmean(g, rest)
                return g
            target = _sharded_dims(aspec, mesh)
            if target:
                w = 1
                if self.qcomm.reduce_scatter:
                    # stage-2 explicit reduce-scatter as a quantized
                    # exchange (qgZ shape; scope inside collectives_q)
                    for dim, ax in target:
                        g = cq.q_reduce_scatter_dim(
                            g, ax, dim, block=self.qcomm.block,
                            record=False)
                        w *= mesh.shape.get(ax, 1)
                else:
                    with jax.named_scope("ds_comm_reduce_scatter"):
                        for dim, ax in target:
                            g = jax.lax.psum_scatter(
                                g, ax, scatter_dimension=dim, tiled=True)
                            w *= mesh.shape.get(ax, 1)
                g = g / w
                rest = tuple(a for a in DATA_AXES
                             if a not in {ax for _, ax in target})
                if any(mesh.shape.get(a, 1) > 1 for a in rest):
                    with jax.named_scope("ds_comm_all_reduce"):
                        g = jax.lax.pmean(g, rest)
                return g
            if any(mesh.shape.get(a, 1) > 1 for a in DATA_AXES):
                with jax.named_scope("ds_comm_all_reduce"):
                    g = jax.lax.pmean(g, DATA_AXES)
            return g

        return jax.tree.map(r, gtree, spec_tree, acc_spec_tree)

    # -- the bucketed forward + loss ------------------------------------
    def _ce_weight(self, labels, mask, axes):
        """Per-shard CE weight making the sharded masked mean exact: the
        model's loss is ``nll_sum / valid_count`` over the LOCAL batch
        shard, so a plain pmean of shard losses diverges from the GSPMD
        path's GLOBAL masked mean whenever valid-token counts (-100
        ignore_index / loss_mask) differ across data shards.  Scaling each
        shard's CE by ``local_valid * world / global_valid`` makes both
        the reported loss and the reduced gradients equal the global
        masked mean exactly (weight == 1 when counts are uniform).  Same
        valid semantics as ``models/transformer.cross_entropy`` (shifted
        labels >= 0, optionally & shifted loss_mask > 0)."""
        valid = labels[:, 1:] >= 0
        if mask is not None:
            valid = valid & (mask[:, 1:] > 0)
        cnt = valid.sum().astype(jnp.float32)
        if not axes:
            return jnp.float32(1.0)
        world = 1
        for a in axes:
            world *= self.mesh.shape.get(a, 1)
        total = jax.lax.psum(cnt, axes)
        return cnt * world / jnp.maximum(total, 1.0)

    def _forward_loss(self, pieces: Dict[str, Any], tokens, labels, mask,
                      rng, ce_weight):
        """Bucket-chunked forward to the scalar LM loss (count-weighted
        local-batch mean — ``pmean`` across shards yields the exact global
        masked mean, see :meth:`_ce_weight`).  Differentiating this w.r.t.
        ``pieces`` yields per-bucket grads as separate values — each
        bucket's reduce can start mid-backward."""
        seg = self.seg
        sspecs = self._split_specs(self.param_specs)
        S = int(tokens.shape[1])
        cos, sin = seg["rope"](S, jnp.dtype(self.compute_dtype))
        if self.use_dropout:
            keys = jax.random.split(rng, self.L)
        else:
            keys = jnp.zeros((self.L,), jnp.uint32)
        use_drop = self.use_dropout
        layer_fwd = seg["layer_fwd"]
        layer_spec = self.param_specs["layers"]   # shared by every bucket

        with jax.named_scope("overlap_embed"):
            embed_full = self._gather_tree(pieces["embed"], sspecs["embed"])
        x = seg["embed_fwd"](embed_full, tokens)
        aux_total = jnp.zeros((), jnp.float32)

        def bucket_body(shards, x_in, keys_b):
            full = self._gather_tree(shards, layer_spec)

            def scan_body(c, xs):
                lp, k = xs
                y, aux = layer_fwd(lp, c, k, cos, sin, use_drop)
                return y, aux.astype(jnp.float32)

            y, auxes = jax.lax.scan(scan_body, x_in, (full, keys_b))
            return y, jnp.sum(auxes)

        if self.remat:
            # default policy saves nothing: the backward re-gathers the
            # bucket (the ZeRO-3 2x schedule) and recomputes its layers —
            # gathered params never persist as residuals
            bucket_body = jax.checkpoint(bucket_body, prevent_cse=False)

        prev_x = None
        for i, (b0, b1) in enumerate(self.buckets):
            shards = pieces["buckets"][i]
            if prev_x is not None:
                # gather i may start once bucket i-1's INPUT exists — at
                # most one bucket of lookahead, concurrent with bucket
                # i-1's compute
                shards = _tie(shards, prev_x)
            prev_x = x
            with jax.named_scope(f"overlap_b{i}"):
                x, aux = bucket_body(shards, x, keys[b0:b1])
            aux_total = aux_total + aux

        head_shards = pieces["head"]
        if prev_x is not None:
            head_shards = _tie(head_shards, prev_x)
        with jax.named_scope("overlap_head"):
            head_full = self._gather_tree(head_shards, sspecs["head"])
        head_tree = {"final_norm": head_full["final_norm"],
                     "head": (embed_full["tok"] if self.tied
                              else head_full["lm_head"])}
        if self._has_head_bias:
            head_tree["head_bias"] = head_full["lm_head_bias"]
        # weight applies to the masked-mean CE only: the MoE aux loss is
        # an unmasked per-shard batch mean, for which plain pmean is exact
        # (shards are equal-sized)
        loss = seg["head_loss"](head_tree, x, labels, mask) * ce_weight
        if self.moe_coef:
            loss = loss + self.moe_coef * aux_total
        return loss

    # -- the accum body the engine compiles under shard_map -------------
    def make_accum(self, gas: int, fp16: bool):
        """Build ``accum_local(state, batch, rng) -> (state', loss)`` for
        full-manual ``shard_map`` over the mesh.  Semantics match the GSPMD
        ``accum``: grads of ``loss * scale / gas`` accumulate into
        ``state.grad_acc`` (global-batch mean layout), loss returned
        unscaled as the global mean."""
        mesh = self.mesh
        sspecs = self._split_specs(self.param_specs)
        aspecs = self._split_specs(self.acc_specs)
        buckets = self.buckets

        def accum_local(state, batch, rng):
            unpacked = unpack_lm_batch(batch)
            if unpacked is None:  # engine checks before dispatch; belt+braces
                raise ValueError(
                    "overlap_comm requires (tokens, labels[, loss_mask]) "
                    "batches — see zero_optimization.overlap_comm docs")
            tokens, labels, mask = unpacked
            scale = (state.scaler.scale if fp16 else jnp.float32(1.0))
            axes = tuple(a for a in DATA_AXES if mesh.shape.get(a, 1) > 1)
            # data-only scalar (labels/mask), computed outside the grad —
            # no cotangent ever flows through the psum
            ce_w = self._ce_weight(labels, mask, axes)

            def loss_f(pieces):
                loss = self._forward_loss(pieces, tokens, labels, mask, rng,
                                          ce_w)
                return (loss.astype(jnp.float32) * scale) / gas, loss

            with jax.named_scope("ds_fwd_bwd"):
                pieces = self._split(state.params)
                grads, loss = jax.grad(loss_f, has_aux=True)(pieces)

                # reduce pieces on a barrier-chained virtual comm stream in
                # backward-production order (head first, embed last): each
                # reduce may start as soon as its bucket's backward is done,
                # and the chain keeps the collectives distinct + ordered
                order = (["head"]
                         + [f"b{i}" for i in
                            range(len(buckets) - 1, -1, -1)]
                         + ["embed"])
                g_by = {"head": grads["head"], "embed": grads["embed"]}
                s_by = {"head": sspecs["head"], "embed": sspecs["embed"]}
                a_by = {"head": aspecs["head"], "embed": aspecs["embed"]}
                for i in range(len(buckets)):
                    g_by[f"b{i}"] = grads["buckets"][i]
                    s_by[f"b{i}"] = sspecs["buckets"][i]
                    a_by[f"b{i}"] = aspecs["buckets"][i]
                reduced: Dict[str, Any] = {}
                chain = None
                for name in order:
                    g = g_by[name]
                    if chain is not None:
                        g = _tie(g, chain)
                    red = self._reduce_tree(g, s_by[name], a_by[name])
                    leaves = jax.tree_util.tree_leaves(red)
                    if leaves:
                        chain = leaves[0]
                    reduced[name] = red

                acc = state.grad_acc

                def add(a, g):
                    return a + g.astype(a.dtype)

                new_acc = dict(acc)
                new_acc["embed"] = jax.tree.map(add, acc["embed"],
                                                reduced["embed"])
                bucket_gs = [reduced[f"b{i}"] for i in range(len(buckets))]

                def addcat(a, *gs):
                    parts = [jax.lax.slice_in_dim(a, b0, b1, axis=0)
                             + g.astype(a.dtype)
                             for (b0, b1), g in zip(buckets, gs)]
                    return (jnp.concatenate(parts, axis=0)
                            if len(parts) > 1 else parts[0])

                new_acc["layers"] = jax.tree.map(addcat, acc["layers"],
                                                 *bucket_gs)
                for key in ("final_norm", "lm_head", "lm_head_bias"):
                    if key in acc:
                        new_acc[key] = jax.tree.map(add, acc[key],
                                                    reduced["head"][key])
            loss_out = jax.lax.pmean(loss, axes) if axes else loss
            return state._replace(grad_acc=new_acc), loss_out

        return accum_local
