"""Host->device parameter streaming: double-buffered prefetch, persistent
staging slots, pinned-host routing, int8 relay.

The measured 8B host-tiered rung (BENCH_r05) moves ~48GB per micro-batch at
~14MB/s effective host<->device bandwidth — the RELAY, not compute, is the
wall (ROADMAP item 3; ZeRO-Infinity arXiv:2104.07857 / ZeRO-Offload
arXiv:2101.06840 attack exactly this regime).  This module owns the layer
transport for ``runtime/zero/stream_grad.py`` and shrinks/hides it three
ways:

- **double-buffered prefetch** — :meth:`ParamStreamer.prefetch` dispatches
  layer ``i+1``'s H2D while layer ``i`` computes (the PR 6 barrier-tied
  bucket idiom applied to the memory tier; here the "barrier" is dispatch
  order — ``device_put`` transfers run outside program execution and
  overlap device compute).  ``take(i)`` finding its layer already in
  flight is a prefetch HIT (``ds_offload_prefetch_hits_total``); the
  transport order never changes the math, so prefetch on/off is
  loss-IDENTICAL (tier-1 pinned).
- **persistent staging slots** — on one-memory-space backends each fetched
  layer is re-staged into one of ``staging_slots`` pre-allocated device
  buffers via a donated compiled copy, so steady state holds exactly N
  slot buffers instead of churning a fresh allocation per layer per
  micro-batch.  On pinned-host backends the put targets ``pinned_host``
  directly (the staging tier device DMA reads from) and the layer program
  opens with the in-jit device move — ``transformer.to_dev``'s idiom.
- **int8 relay** — with ``int8=True`` each layer ships as blockwise int8 +
  fp32 block scales (``comm/quant.py``) and :meth:`materialize` fuses the
  dequant into the consuming layer program: ~2x fewer relay bytes than
  bf16, ~4x fewer than fp32.  Payloads are replicated (the sharded int8
  relay belongs to the quantized-collective layer, ROADMAP item 2, which
  reuses the same codec).

Telemetry (docs/OBSERVABILITY.md "Offload streaming"): relay bytes by
direction (``ds_offload_relay_bytes_total{dir=}``), per-take residual
stall (``ds_offload_relay_seconds`` — how long the consumer actually
waited on the relay; ~0 when prefetch fully hides it), prefetch
hits/misses.  All one-branch no-ops while the registry is disabled; the
stall measurement only runs when telemetry is on (it synchronizes on the
fetched layer, which the consumer was about to do anyway).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm.quant import (DEFAULT_BLOCK, dequantize_tree,
                                      quantize_tree_np)


def _tree_nbytes(tree) -> int:
    return sum(int(np.prod(np.shape(a))) * np.dtype(
        getattr(a, "dtype", np.float32)).itemsize
        for a in jax.tree.leaves(tree))


class RelayMeter:
    """The shared ``ds_offload_*`` instruments (one registration per
    process registry; both the streamer and the grad D2H side feed it)."""

    def __init__(self, registry=None):
        if registry is None:
            from deepspeed_tpu.monitor.metrics import get_registry

            registry = get_registry()
        self.registry = registry
        self.h2d_bytes = registry.counter(
            "ds_offload_relay_bytes_total",
            "bytes moved across the offload host<->device relay",
            labels={"dir": "h2d"})
        self.d2h_bytes = registry.counter(
            "ds_offload_relay_bytes_total",
            "bytes moved across the offload host<->device relay",
            labels={"dir": "d2h"})
        self.stall = registry.histogram(
            "ds_offload_relay_seconds",
            "host wall seconds attributed to the offload relay: streamed "
            "path = residual stall per consumed layer fetch (0 when "
            "prefetch fully hid the transfer); optimizer boundary = the "
            "grads-down/params-up window (measured only while telemetry "
            "is on)")
        self.hits = registry.counter(
            "ds_offload_prefetch_hits_total",
            "layer fetches already in flight when consumed")
        self.misses = registry.counter(
            "ds_offload_prefetch_misses_total",
            "layer fetches dispatched on demand (prefetch off or behind)")


class ParamStreamer:
    """Per-layer H2D transport over a stacked ``[L, ...]`` host tree.

    ``layer_shardings``: device NamedSharding tree for ONE layer (stacked
    specs with the leading [L] dim stripped — the ``StreamedFwdBwd``
    contract).  ``refresh(np_layers)`` (re)binds the host source — called
    once at init and after every optimizer step (the int8 mode requantizes
    there, so the relay always ships the current weights).

    Transport payloads are host numpy per layer: the value slice, or the
    (q, scale) pair under int8.  :meth:`materialize` is the TRACEABLE
    stage the consuming layer program opens with (pinned->device move
    and/or fused dequant); plain device-memory fp transport materializes
    to the fetched tree itself.
    """

    def __init__(self, layer_shardings, *, int8: bool = False,
                 quant_block: int = DEFAULT_BLOCK, prefetch: bool = True,
                 staging_slots: int = 2, registry=None,
                 compute_dtype=None):
        from deepspeed_tpu.accelerator.real_accelerator import (
            host_memory_kind, supports_pinned_host)

        self._layer_sh = layer_shardings
        self.int8 = bool(int8)
        self.quant_block = int(quant_block)
        self.prefetch_enabled = bool(prefetch)
        self.staging_slots = max(1, int(staging_slots))
        self.pinned = supports_pinned_host()
        self._host_kind = host_memory_kind()
        self.meter = RelayMeter(registry)
        self._compute_dtype = compute_dtype
        # host source (set by refresh)
        self._np_layers = None
        self._q_layers = None            # per-layer QuantizedTree list
        self._layer_spec = None          # one layer's ShapeDtypeStructs
        self.num_layers = 0
        # in-flight fetches: i -> payload (device arrays)
        self._inflight: Dict[int, Any] = {}
        # non-layer (embed/head) transport: name -> (src_key, host payload)
        # quantized once per source binding, shipped per call
        self._aux_q: Dict[str, Any] = {}
        self._aux_spec: Dict[str, Any] = {}
        self._restage = None             # compiled slot-recycling copy
        self._slots = None               # staging ring (device payloads)
        self._slot_idx = 0
        if self.pinned:
            from jax.sharding import NamedSharding

            self._put_sh = jax.tree.map(
                lambda s: NamedSharding(s.mesh, s.spec,
                                        memory_kind=self._host_kind),
                layer_shardings)
        else:
            self._put_sh = layer_shardings

    # ------------------------------------------------------------------
    # host source
    # ------------------------------------------------------------------
    def refresh(self, np_layers: Any) -> None:
        """(Re)bind the stacked host tree.  int8: re-quantize per layer —
        host CPU work amortized over the micro-batches of the next step."""
        self._np_layers = np_layers
        first = jax.tree.map(lambda a: np.asarray(a)[0], np_layers)
        self._layer_spec = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), first)
        self.num_layers = int(np.asarray(
            jax.tree.leaves(np_layers)[0]).shape[0])
        if self.int8:
            self._q_layers = [
                quantize_tree_np(
                    jax.tree.map(lambda a, i=i: np.asarray(a)[i], np_layers),
                    self.quant_block)
                for i in range(self.num_layers)]
        self._inflight.clear()

    def _host_payload(self, i: int):
        if self.int8:
            qt = self._q_layers[i]
            return {"q": qt.q, "scale": qt.scale}
        return jax.tree.map(lambda a: np.asarray(a)[i], self._np_layers)

    def _payload_nbytes(self, payload) -> int:
        return _tree_nbytes(payload)

    # ------------------------------------------------------------------
    # transport
    # ------------------------------------------------------------------
    def _put(self, payload):
        if self.int8:
            # replicated codes (+ the pinned hop where advertised): the
            # leaf shapes are [nb, block]/[nb, 1], unrelated to the layer
            # shardings
            if self.pinned:
                from jax.sharding import SingleDeviceSharding

                kind = self._host_kind
                dev = jax.devices()[0]
                sh = SingleDeviceSharding(dev, memory_kind=kind)
                return jax.tree.map(lambda a: jax.device_put(a, sh), payload)
            return jax.tree.map(jax.device_put, payload)
        dev = jax.device_put(payload, self._put_sh)
        if not self.pinned and self.staging_slots:
            dev = self._restage_into_slot(dev)
        return dev

    def _restage_into_slot(self, fresh):
        """Recycle one of the persistent staging buffers: a donated
        compiled copy writes the fresh transfer into the ring slot, so the
        per-layer device_put temporary frees immediately and steady state
        holds exactly ``staging_slots`` layer-sized buffers.

        The reuse contract needs payloads consumed ONLY as jit inputs
        (the streamed layer programs): exporting a numpy view of a
        payload (``np.asarray``) marks its buffer externally referenced
        and the next donation of that slot safely falls back to a fresh
        allocation (measured — correctness is never at stake, only the
        reuse)."""
        if self._restage is None:
            sh = self._layer_sh

            @functools.partial(jax.jit, donate_argnums=(0,),
                               out_shardings=sh)
            def restage(slot, fresh):
                # output values = fresh, WRITTEN INTO the donated slot
                # buffers (a bare pass-through would alias the output to
                # ``fresh``'s own buffer and leave the donation unused —
                # measured; the scatter-overwrite form pins the alias to
                # the slot)
                return jax.tree.map(lambda s, f: s.at[...].set(f),
                                    slot, fresh)

            self._restage = restage
            zeros = jax.jit(
                lambda: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype), self._layer_spec),
                out_shardings=sh)
            self._slots = [zeros() for _ in range(self.staging_slots)]
        slot = self._slots[self._slot_idx]
        out = self._restage(slot, fresh)
        self._slots[self._slot_idx] = out
        self._slot_idx = (self._slot_idx + 1) % self.staging_slots
        return out

    def prefetch(self, i: int) -> None:
        """Start layer ``i``'s H2D now (no-op when already in flight or
        prefetch is disabled)."""
        if not self.prefetch_enabled or i in self._inflight:
            return
        self._dispatch(i)

    def _dispatch(self, i: int) -> None:
        payload = self._host_payload(i)
        if self.meter.registry.enabled:
            self.meter.h2d_bytes.inc(self._payload_nbytes(payload))
        self._inflight[i] = self._put(payload)

    def take(self, i: int):
        """The payload for layer ``i`` (device arrays), consuming the
        in-flight entry.  Counts prefetch hit/miss; measures the residual
        stall while telemetry is on."""
        hit = i in self._inflight
        if not hit:
            self._dispatch(i)
        payload = self._inflight.pop(i)
        if self.meter.registry.enabled:
            (self.meter.hits if hit else self.meter.misses).inc()
            t0 = time.perf_counter()
            jax.block_until_ready(payload)
            self.meter.stall.record(time.perf_counter() - t0)
        return payload

    def put_aux(self, name: str, tree, shardings, src_key=None):
        """Non-layer (embed/head) H2D through the same relay codec.

        The layer stream went int8 in PR 10 but embed/head stayed dense
        ("embed/head stay bf16" — ROADMAP item 3 leftover); this closes
        it: with ``int8=True`` the tree ships as blockwise codes + scales
        (quantized ONCE per source binding — ``src_key`` identifies the
        host tree generation, so the fwd/bwd re-puts of one step reuse
        one quantization) and :meth:`materialize_aux` fuses the dequant
        into the consuming program.  Dense mode is the plain device_put
        the caller used before.  Either way the payload bytes land on the
        ``ds_offload_relay_bytes_total{dir="h2d"}`` ledger."""
        if not self.int8:
            if self.meter.registry.enabled:
                self.meter.h2d_bytes.inc(_tree_nbytes(tree))
            return jax.device_put(tree, shardings)
        from deepspeed_tpu.comm.quant import quantize_tree_np

        cached = self._aux_q.get(name)
        if cached is None or cached[0] != src_key:
            qt = quantize_tree_np(
                jax.tree.map(np.asarray, tree), self.quant_block)
            self._aux_q[name] = (src_key, qt)
            self._aux_spec[name] = qt.spec
        qt = self._aux_q[name][1]
        payload = {"q": qt.q, "scale": qt.scale}
        if self.meter.registry.enabled:
            self.meter.h2d_bytes.inc(_tree_nbytes(payload))
        if self.pinned:
            from jax.sharding import SingleDeviceSharding

            sh = SingleDeviceSharding(jax.devices()[0],
                                      memory_kind=self._host_kind)
            return jax.tree.map(lambda a: jax.device_put(a, sh), payload)
        return jax.tree.map(jax.device_put, payload)

    def materialize_aux(self, name: str, payload, dtype=None):
        """TRACEABLE twin of :meth:`materialize` for :meth:`put_aux`
        payloads (fused dequant / pinned->device move; dense passes
        through)."""
        if not self.int8:
            return payload
        dtype = dtype or self._compute_dtype
        q, s = payload["q"], payload["scale"]
        if self.pinned:
            q = jax.tree.map(
                lambda a: jax.device_put(a, jax.memory.Space.Device), q)
            s = jax.tree.map(
                lambda a: jax.device_put(a, jax.memory.Space.Device), s)
        return dequantize_tree(q, s, self._aux_spec[name], dtype=dtype)

    def drop_inflight(self) -> None:
        """Forget queued prefetches (direction change mid fwd/bwd: the
        backward walks layers in reverse, so a stale forward prefetch
        would pin a buffer nobody will take)."""
        self._inflight.clear()

    # ------------------------------------------------------------------
    # traceable consumer stage
    # ------------------------------------------------------------------
    def materialize(self, payload, dtype=None):
        """TRACEABLE: payload -> the layer's compute tree inside the
        consuming program — the fused dequant stage (int8) and/or the
        pinned->device move.  Plain fp device transport passes through."""
        dtype = dtype or self._compute_dtype
        if self.int8:
            q, s = payload["q"], payload["scale"]
            if self.pinned:
                q = jax.tree.map(
                    lambda a: jax.device_put(a, jax.memory.Space.Device), q)
                s = jax.tree.map(
                    lambda a: jax.device_put(a, jax.memory.Space.Device), s)
            return dequantize_tree(q, s, self._layer_spec, dtype=dtype)
        if self.pinned:
            from jax.sharding import NamedSharding

            def move(a, sh):
                if sh.mesh is None or sh.mesh.empty:
                    return jax.device_put(a, jax.memory.Space.Device)
                return jax.device_put(
                    a, NamedSharding(sh.mesh, sh.spec, memory_kind="device"))

            return jax.tree.map(move, payload, self._layer_sh)
        return payload

    # -- accounting hooks for the D2H (grad) side ----------------------
    def record_d2h(self, tree) -> None:
        if self.meter.registry.enabled:
            self.meter.d2h_bytes.inc(_tree_nbytes(tree))
