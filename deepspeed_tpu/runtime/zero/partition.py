"""ZeRO partitioning as sharding specs.

TPU-native replacement for the reference's ZeRO optimizers
(``deepspeed/runtime/zero/stage_1_and_2.py`` + ``stage3.py`` +
``partition_parameters.py``, SURVEY.md §2.1): there is no runtime
bookkeeping — no flattened buffers, no IPG buckets, no gather/release hooks,
no trace-based prefetcher.  A stage is a *placement policy*:

- stage 0: params, grads, optimizer state replicated; gradients all-reduced.
- stage 1: optimizer state sharded over the ``fsdp`` axis.
- stage 2: + gradients reduce-scattered into the sharded accumulator.
- stage 3: + parameters sharded over ``fsdp`` (GSPMD inserts the all-gathers
  in forward/backward and overlaps them with compute — the compiler replaces
  the reference's prefetch coordinator, SURVEY.md §3.3 note).

``choose_pspec`` picks, per parameter, which dimension to shard: the largest
dimension divisible by the axis size.  Parameters smaller than
``persistence_threshold`` stay replicated — the same role as the reference's
``stage3_param_persistence_threshold`` (keep small params resident) with the
same config key.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import axis_size
from deepspeed_tpu.utils.logging import logger


def choose_pspec(shape: Tuple[int, ...], mesh: Mesh, axis: str = "fsdp",
                 min_size: int = 0, existing: Optional[P] = None) -> P:
    """Pick a PartitionSpec sharding one dimension of ``shape`` over ``axis``.

    Chooses the largest dimension divisible by the axis size; dimensions
    already claimed in ``existing`` (e.g. by tensor parallelism) are skipped.
    Returns the existing/replicated spec when nothing divides or the tensor is
    below ``min_size`` elements.
    """
    n = axis_size(mesh, axis)
    base = list(existing) if existing is not None else [None] * len(shape)
    while len(base) < len(shape):
        base.append(None)
    if n <= 1 or int(np.prod(shape or (1,))) < max(min_size, n):
        return P(*base)
    candidates = [(dim_size, i) for i, dim_size in enumerate(shape)
                  if base[i] is None and dim_size % n == 0]
    if not candidates:
        return P(*base)
    _, dim = max(candidates)
    base[dim] = axis
    return P(*base)


def params_pspecs(params: Any, mesh: Mesh, shard: bool, axis: str = "fsdp",
                  persistence_threshold: int = 0, logical_specs: Any = None) -> Any:
    """PartitionSpec tree for a parameter pytree.

    ``shard=False`` (stages 0-2) leaves everything replicated apart from any
    ``logical_specs`` (tensor-parallel annotations).  ``shard=True`` (stage 3)
    additionally shards each large-enough param over ``axis``.
    """
    def spec_for(leaf, logical):
        if not shard:
            return logical if logical is not None else P()
        return choose_pspec(leaf.shape, mesh, axis=axis, min_size=persistence_threshold,
                            existing=logical)

    if logical_specs is None:
        return jax.tree.map(lambda l: spec_for(l, None), params)
    return jax.tree.map(spec_for, params, logical_specs)


def shardings_from_pspecs(pspecs: Any, mesh: Mesh) -> Any:
    return jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                        is_leaf=lambda x: isinstance(x, P))


def opt_state_pspecs(opt_state_shapes: Any, mesh: Mesh, shard: bool, axis: str = "fsdp",
                     persistence_threshold: int = 0) -> Any:
    """PartitionSpec tree for an optax optimizer state.

    Optimizer moments have the same shapes as their params, so the same
    chooser yields consistent placement; scalars (step counts) replicate.
    """
    def spec_for(leaf):
        shape = getattr(leaf, "shape", ())
        if not shard or len(shape) == 0:
            return P()
        return choose_pspec(shape, mesh, axis=axis, min_size=persistence_threshold)

    return jax.tree.map(spec_for, opt_state_shapes)


def describe_partitioning(params: Any, pspecs: Any) -> str:
    """Human-readable partition report (reference: ds_report-style)."""
    lines = []
    flat_p = jax.tree_util.tree_leaves_with_path(params)
    flat_s = jax.tree_util.tree_leaves(pspecs, is_leaf=lambda x: isinstance(x, P))
    sharded = replicated = 0
    for (path, leaf), spec in zip(flat_p, flat_s):
        name = jax.tree_util.keystr(path)
        if any(s is not None for s in spec):
            sharded += 1
            lines.append(f"  {name}: {leaf.shape} -> {spec}")
        else:
            replicated += 1
    lines.insert(0, f"partitioning: {sharded} sharded, {replicated} replicated params")
    return "\n".join(lines)
