"""zero.Init / GatheredParameters — reference-parity param-context API.

Reference: ``deepspeed/runtime/zero/partition_parameters.py`` (SURVEY.md
§2.1 "zero.Init / partitioned params"; the ``GatheredParameters`` ctx mgr is
verified-in-SURVEY API used by HF at (L1:344-346)).

TPU-native semantics: parameters are jax arrays whose ZeRO partitioning is a
*sharding*, so "gather" = fetch to host (numpy, mutable), "repartition" =
``device_put`` back with the original shardings.  ``GatheredParameters``
yields the mutable host tree; mutations made inside the context are written
back on exit (matching the reference's modifier_rank contract — on TPU every
process runs the same modification, or rank 0's result is broadcast).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger


class Init:
    """``with deepspeed.zero.Init():`` — reference context that makes modules
    materialize pre-partitioned.  The TPU engine already abstract-inits and
    shards on create (engine.lazy_init_from_batch), so this context is a
    compatibility no-op that records its config for introspection."""

    def __init__(self, module=None, data_parallel_group=None, mem_efficient_linear=True,
                 remote_device=None, pin_memory=False, config_dict_or_path=None,
                 config=None, enabled=True, dtype=None, mpu=None):
        self.enabled = enabled
        self.remote_device = remote_device
        self.dtype = dtype

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class GatheredParameters:
    """Gather -> modify -> repartition (reference ctx mgr).

    ``params``: a pytree of jax arrays (e.g. ``engine.state.params`` or a
    subtree), or an ``(engine, subpath)`` pair via ``engine=``/``path=``.
    Inside the context, ``.params`` is a mutable numpy tree; on exit the
    (possibly modified) values are re-placed with their original shardings.
    When ``engine`` is given, the engine's live state is updated in place.
    """

    def __init__(self, params: Any = None, modifier_rank: Optional[int] = 0,
                 fwd_module=None, enabled: bool = True, engine: Any = None):
        self.enabled = enabled
        self.engine = engine
        # 0/1 Adam stacks worker replicas on a leading [W] axis; users see
        # the model-shaped view and writes broadcast to every replica
        self._stacked_engine = (params is None and engine is not None
                                and getattr(engine, "_onebit_stacked", False))
        if params is not None:
            self._src = params
        elif engine is not None:
            self._src = (engine.module_params() if self._stacked_engine
                         else engine.state.params)
        else:
            self._src = None
        if self._src is None:
            raise ValueError("GatheredParameters needs params or engine=")
        self.params: Any = None
        self._shardings = None

    def __enter__(self):
        if not self.enabled:
            self.params = self._src
            return self.params
        self._shardings = jax.tree.map(
            lambda a: a.sharding if isinstance(a, jax.Array) else None, self._src)
        # mutable host copies (device_get hands back read-only buffers)
        self.params = jax.tree.map(
            lambda a: np.array(jax.device_get(a)), self._src)
        return self.params

    def __exit__(self, exc_type, exc, tb):
        if not self.enabled or exc_type is not None:
            return False
        if self._stacked_engine:
            # broadcast each (possibly modified) model-shaped value back to
            # every worker replica with the live stacked shardings
            live = self.engine.state.params
            stacked_sh = self.engine._param_shardings
            replaced = jax.tree.map(
                lambda host, leaf, sh: jax.device_put(
                    np.broadcast_to(np.asarray(host, leaf.dtype)[None],
                                    leaf.shape), sh),
                self.params, live, stacked_sh)
            self.engine.state = self.engine.state._replace(params=replaced)
            self.result = replaced
            return False
        replaced = jax.tree.map(
            lambda host, sh: jax.device_put(host, sh) if sh is not None else host,
            self.params, self._shardings)
        if self.engine is not None:
            if self._src is self.engine.state.params:
                self.engine.state = self.engine.state._replace(params=replaced)
            else:
                logger.warning("GatheredParameters: engine given but params is "
                               "a subtree; caller must reinstall .result")
        self.result = replaced
        return False
