"""Activation checkpointing (reference:
``deepspeed/runtime/activation_checkpointing/``, SURVEY.md §2.1)."""

from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (  # noqa: F401
    CudaRNGStatesTracker, checkpoint, checkpoint_wrapper, configure,
    get_cuda_rng_tracker, is_configured, model_parallel_cuda_manual_seed)
