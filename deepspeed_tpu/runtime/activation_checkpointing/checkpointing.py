"""Activation checkpointing API.

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(SURVEY.md §2.1): Megatron-compatible ``checkpoint()`` + ``configure()`` +
the CUDA RNG state tracker for reproducible dropout under recompute.

TPU-native mapping:
- ``checkpoint(fn, *args)`` -> ``jax.checkpoint`` (recompute-in-backward is
  a compiler transform, not autograd hooks).  Policies map the reference
  knobs: ``partition_activations`` -> saveable residuals carry their
  sharding (GSPMD keeps them sharded — nothing to do at runtime);
  ``cpu_checkpointing`` -> the "offload_dots" remat policy
  (``jax.checkpoint_policies.offload_dot_with_no_batch_dims``): saved
  matmul outputs page to pinned host memory in forward and stream back in
  backward, so they stop occupying HBM between the passes — the
  reference's checkpoint-to-CPU semantics as a compiler memory-space
  annotation instead of explicit D2H copies.
- Reproducible dropout under recompute is STRUCTURAL in jax: dropout draws
  from explicit PRNG keys, so the recompute replays the same keys by
  construction — the reference's ``CudaRNGStatesTracker`` machinery exists
  to recreate that property in a stateful-RNG world.  The tracker class is
  provided for API parity and manages named jax keys.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import logger

_CONFIG: Dict[str, Any] = {
    "partition_activations": False,
    "cpu_checkpointing": False,
    "contiguous_memory_optimization": False,
    "number_checkpoints": None,
    "synchronize": False,
    "profile": False,
}


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None) -> None:
    """Reference entry point: record the subsystem config (the engine pushes
    the same section into model remat settings at init)."""
    if deepspeed_config is not None:
        ac = getattr(deepspeed_config, "activation_checkpointing", None)
        if ac is not None:
            _CONFIG.update(partition_activations=ac.partition_activations,
                           cpu_checkpointing=ac.cpu_checkpointing,
                           contiguous_memory_optimization=ac.contiguous_memory_optimization,
                           number_checkpoints=ac.number_checkpoints)
    for key, val in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("number_checkpoints", num_checkpoints),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize", synchronize), ("profile", profile)):
        if val is not None:
            _CONFIG[key] = val
    logger.info("activation checkpointing configured: %s", _CONFIG)


def is_configured() -> bool:
    return True


def checkpoint(function: Callable, *args, policy: Optional[Any] = None):
    """Megatron-compatible ``checkpoint(fn, *args)``: runs ``fn`` now and
    recomputes it in backward (``jax.checkpoint``).  Dropout reproducibility
    is inherent (explicit keys)."""
    ckpt = jax.checkpoint(function, policy=policy, prevent_cse=False)
    return ckpt(*args)


def checkpoint_wrapper(function: Callable, policy: Optional[Any] = None) -> Callable:
    """Decorator form used by model code."""
    return jax.checkpoint(function, policy=policy, prevent_cse=False)


class CudaRNGStatesTracker:
    """API-parity RNG tracker (reference: reproducible dropout under
    recompute).  jax dropout keys are explicit, so 'tracking' is just a
    named-key registry; ``fork`` hands out a fresh split deterministically."""

    def __init__(self):
        self._states: Dict[str, jax.Array] = {}

    def reset(self) -> None:
        self._states.clear()

    def get_states(self):
        return dict(self._states)

    def set_states(self, states) -> None:
        self._states = dict(states)

    def add(self, name: str, seed: int) -> None:
        if name in self._states:
            raise Exception(f"seed {name} already exists")
        self._states[name] = jax.random.PRNGKey(seed)

    def fork(self, name: str = "model-parallel-rng"):
        import contextlib

        @contextlib.contextmanager
        def _fork():
            if name not in self._states:
                raise Exception(f"seed {name} not added")
            self._states[name], sub = jax.random.split(self._states[name])
            yield sub

        return _fork()


_RNG_TRACKER = CudaRNGStatesTracker()


def get_cuda_rng_tracker() -> CudaRNGStatesTracker:
    return _RNG_TRACKER


def model_parallel_cuda_manual_seed(seed: int) -> None:
    """Reference parity: register the model-parallel dropout seed."""
    _RNG_TRACKER.reset()
    _RNG_TRACKER.add("model-parallel-rng", seed + 2718)
