"""Hybrid engine: one model flipping between training and inference (RLHF).

Reference: ``deepspeed/runtime/hybrid_engine.py`` (SURVEY.md §2.1 "Hybrid
engine (RLHF)"): in RLHF loops the actor alternates between ZeRO-3 training
steps and fast generation; the reference re-gathers/releases params and
swaps kernels per phase.

TPU-native: params are immutable sharded arrays, so the "flip" is free —
the inference engine reads the training state's params directly (same
buffers; ``device_put`` only reshards if the serving layout differs).  No
gather, no kernel swap, no copies when layouts agree.
"""

from __future__ import annotations

from typing import Any, Optional

import jax

from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """Training engine + generation on the live weights (reference class)."""

    def __init__(self, *args, inference_config: Optional[dict] = None, **kwargs):
        super().__init__(*args, **kwargs)
        self._inference_config = dict(inference_config or {})
        self._infer_engine = None
        self._in_generate = False

    # -- reference API ---------------------------------------------------
    def eval(self):
        self._training = False
        return self

    def train(self, mode: bool = True):
        self._training = mode
        return self

    def _inference_engine(self):
        from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
        from deepspeed_tpu.inference.engine import InferenceEngine

        if self._infer_engine is None:
            cfg = dict(self._inference_config)
            cfg.setdefault("dtype", "bfloat16" if self.bfloat16_enabled
                           else ("float16" if self.fp16_enabled else "float32"))
            cfg.setdefault("max_out_tokens", 2048)
            self._infer_engine = InferenceEngine(
                self.module, DeepSpeedInferenceConfig(**cfg), mesh=self.mesh)
            log_dist("hybrid engine: inference path initialized", ranks=[0])
        return self._infer_engine

    def generate(self, input_ids, **kwargs):
        """Generate with the CURRENT training weights — the RLHF actor's
        experience-collection phase.  Weights are shared by reference; the
        inference engine reshards lazily only if layouts differ."""
        if self.state is None:
            raise RuntimeError("generate() before training state exists")
        engine = self._inference_engine()
        if engine._params is None or self._params_stale:
            # module_params(): model-shaped view (0/1 Adam stacks replicas)
            engine.set_params(self.module_params())
            self._params_stale = False
        return engine.generate(input_ids, **kwargs)

    @property
    def _params_stale(self) -> bool:
        # params change on every optimizer step; track by step count
        cur = self._host_steps
        stale = getattr(self, "_gen_step_sync", -1) != cur
        return stale

    @_params_stale.setter
    def _params_stale(self, value: bool) -> None:
        if not value:
            self._gen_step_sync = self._host_steps
