"""Config plumbing shared by all ds_config sections.

TPU-native analog of the reference's ``deepspeed/runtime/config_utils.py``
(SURVEY.md §2.1 "Config system"): a pydantic base model that

- accepts the string ``"auto"`` for any leaf and resolves it to the field
  default while recording which keys were auto (the engine may later overwrite
  those with model-dependent values, mirroring the reference's
  ``reduce_bucket_size = hidden**2`` style fills);
- supports key deprecation/migration (old name → new name with a warning);
- tolerates unknown keys with a warning instead of a hard error, so configs
  written for the reference keep loading.
"""

from __future__ import annotations

from typing import Any, ClassVar, Dict, List, Set

from pydantic import BaseModel, ConfigDict, PrivateAttr, model_validator

from deepspeed_tpu.utils.logging import logger

AUTO = "auto"


class DeepSpeedConfigModel(BaseModel):
    """Base class for every ds_config section model."""

    model_config = ConfigDict(extra="allow", populate_by_name=True, validate_assignment=True,
                              arbitrary_types_allowed=True, protected_namespaces=())

    # Map of deprecated key -> new key, overridden by subclasses.
    DEPRECATED_FIELDS: ClassVar[Dict[str, str]] = {}

    # Recorded list of field names that were "auto" in the source config.
    _auto_keys: List[str] = PrivateAttr(default_factory=list)

    @model_validator(mode="before")
    @classmethod
    def _resolve_auto_and_deprecated(cls, values: Any) -> Any:
        if not isinstance(values, dict):
            return values
        values = dict(values)
        auto_keys: Set[str] = set()
        # Deprecated-key migration.
        deprecated = getattr(cls, "DEPRECATED_FIELDS", {}) or {}
        for old, new in deprecated.items():
            if old in values:
                if new in values and values[new] != values[old]:
                    raise ValueError(
                        f"Config specifies both deprecated '{old}' and its replacement '{new}' with different values")
                logger.warning("Config key '%s' is deprecated; use '%s'", old, new)
                values.setdefault(new, values.pop(old))
        # "auto" resolution: fall back to the field default, remember the key.
        for name, field in cls.model_fields.items():
            key = field.alias or name
            candidates = [key, name]
            for k in candidates:
                if k in values and isinstance(values[k], str) and values[k] == AUTO:
                    auto_keys.add(name)
                    if field.default_factory is not None:
                        values[k] = field.default_factory()
                    else:
                        values[k] = field.default
        values["_ds_auto_keys"] = sorted(auto_keys)
        return values

    def model_post_init(self, __context: Any) -> None:
        extra = getattr(self, "model_extra", None) or {}
        auto = extra.pop("_ds_auto_keys", [])
        self._auto_keys = list(auto)
        known = set(type(self).model_fields)
        for key in extra:
            if key not in known and not key.startswith("_"):
                logger.warning("%s: ignoring unknown config key '%s'", type(self).__name__, key)

    def was_auto(self, field_name: str) -> bool:
        return field_name in self._auto_keys

    def fill_auto(self, field_name: str, value: Any) -> None:
        """Overwrite a field that the user left as "auto" with a computed value."""
        if self.was_auto(field_name):
            object.__setattr__(self, field_name, value)


def get_scalar_param(config_dict: Dict, name: str, default: Any) -> Any:
    """Dotted-path config query, e.g. ``zero_optimization.stage``."""
    node: Any = config_dict
    for part in name.split("."):
        if not isinstance(node, dict) or part not in node:
            return default
        node = node[part]
    return node
