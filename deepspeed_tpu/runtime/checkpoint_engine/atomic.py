"""Crash-atomic checkpoint layout: staging, manifests, the ``latest``
pointer, and the walk-back to the newest valid tag.

A checkpoint interrupted mid-write must never be able to masquerade as a
valid restore point — on TPU, preemption is a routine scheduling event,
and for ZeRO-Infinity-scale state the checkpoint is the ONLY recovery
path.  The contract (docs/RESILIENCE.md):

- **Staging**: a save writes every file into ``<save_dir>/tmp.<tag>``.
  The ``tmp.`` prefix is the invariant: directory listings of valid tags
  (``list_tags``) never return staged dirs, so a kill at ANY byte offset
  during the write leaves only debris the next save clears.
- **Manifest**: ``MANIFEST.json`` records, per file, size + sha256 (plus
  world_size / zero_stage / format version).  It is written LAST inside
  the stage, after fsyncing every data file, so its presence certifies
  the stage was fully written.
- **Publish**: the stage is renamed into place (``os.rename`` — atomic on
  POSIX within a filesystem) and the parent directory fsynced.  Only then
  is the ``latest`` pointer updated, itself via tmp + ``os.replace``.
- **Verify**: ``verify_dir`` re-checks the manifest (existence + size,
  and checksums at ``level="full"``) before a load trusts the bytes.
  Directories without a manifest are reported as ``no_manifest`` — the
  caller decides whether to accept them (legacy checkpoints predate the
  manifest) or skip them.

Deliberately stdlib-only (no jax, no package-relative imports):
``tools/ckpt_verify.py`` execs this file by path so operators can audit a
checkpoint directory from any box.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "MANIFEST.json"
FORMAT_VERSION = 1
TMP_PREFIX = "tmp."            # staged (uncommitted) checkpoint dirs
TRASH_PREFIX = ".trash."       # pre-publish rename target for a stale tag
LATEST_NAME = "latest"

__all__ = ["MANIFEST_NAME", "FORMAT_VERSION", "TMP_PREFIX", "TRASH_PREFIX",
           "LATEST_NAME", "CheckpointStatus", "sha256_file", "fsync_file",
           "fsync_dir", "stage_path", "write_manifest", "verify_dir",
           "deep_verify", "read_latest", "write_latest", "list_tags",
           "publish_dir", "clear_stage", "sweep_trash"]


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Flush a directory entry (rename/create durability).  Platforms that
    cannot fsync a directory fd (some network filesystems) degrade to a
    no-op — the rename ordering still holds, only its durability window
    widens."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def stage_path(save_dir: str, tag: str) -> str:
    return os.path.join(save_dir, TMP_PREFIX + str(tag))


def _walk_files(ckpt_dir: str) -> List[str]:
    """Relative paths ('/'-separated) of every file under ``ckpt_dir``,
    excluding the manifest itself; sorted for a stable manifest."""
    out = []
    for root, _dirs, files in os.walk(ckpt_dir):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), ckpt_dir)
            rel = rel.replace(os.sep, "/")
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def write_manifest(ckpt_dir: str, tag: str,
                   extra: Optional[Dict[str, Any]] = None,
                   fsync: bool = True) -> Dict[str, Any]:
    """Checksum every file in ``ckpt_dir`` and write ``MANIFEST.json``
    (tmp + ``os.replace``), fsyncing the data files first and the manifest
    and directory after — the stage is durable before it can be
    published."""
    files: Dict[str, Dict[str, Any]] = {}
    for rel in _walk_files(ckpt_dir):
        path = os.path.join(ckpt_dir, rel.replace("/", os.sep))
        if fsync:
            fsync_file(path)
        files[rel] = {"nbytes": os.path.getsize(path),
                      "sha256": sha256_file(path)}
    manifest = {"format_version": FORMAT_VERSION, "tag": str(tag),
                "time_unix": time.time(), "files": files}
    if extra:
        manifest.update(extra)
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    tmp = mpath + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1, sort_keys=True, default=str)
        fh.flush()
        if fsync:
            os.fsync(fh.fileno())
    os.replace(tmp, mpath)
    if fsync:
        fsync_dir(ckpt_dir)
    return manifest


class CheckpointStatus:
    """Result of ``verify_dir``: ``state`` is one of ``valid`` /
    ``missing`` (no such directory) / ``no_manifest`` (pre-manifest
    layout — loadable but unverifiable) / ``corrupt`` (manifest present
    but contradicted by the bytes on disk)."""

    def __init__(self, state: str, problems: Optional[List[str]] = None,
                 manifest: Optional[Dict[str, Any]] = None):
        self.state = state
        self.problems = problems or []
        self.manifest = manifest

    @property
    def ok(self) -> bool:
        return self.state == "valid"

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"CheckpointStatus({self.state!r}, problems={self.problems})"


def verify_dir(ckpt_dir: str, level: str = "full") -> CheckpointStatus:
    """Verify a checkpoint directory against its manifest.

    ``level="fast"`` checks existence + size only (retention GC);
    ``level="full"`` additionally re-hashes every file (load path,
    offline audit)."""
    if not os.path.isdir(ckpt_dir):
        return CheckpointStatus("missing", [f"no such directory: {ckpt_dir}"])
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.exists(mpath):
        return CheckpointStatus("no_manifest",
                                [f"no {MANIFEST_NAME} in {ckpt_dir}"])
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as exc:
        return CheckpointStatus("corrupt", [f"unreadable manifest: {exc}"])
    files = manifest.get("files")
    if not isinstance(files, dict):
        return CheckpointStatus("corrupt", ["manifest has no files map"],
                                manifest)
    problems: List[str] = []
    for rel, meta in sorted(files.items()):
        path = os.path.join(ckpt_dir, rel.replace("/", os.sep))
        if not os.path.exists(path):
            problems.append(f"missing file: {rel}")
            continue
        size = os.path.getsize(path)
        if size != int(meta.get("nbytes", -1)):
            problems.append(f"size mismatch: {rel} is {size}B, manifest "
                            f"says {meta.get('nbytes')}B")
            continue
        if level == "full" and meta.get("sha256"):
            got = sha256_file(path)
            if got != meta["sha256"]:
                problems.append(f"checksum mismatch: {rel}")
    if problems:
        return CheckpointStatus("corrupt", problems, manifest)
    return CheckpointStatus("valid", manifest=manifest)


def _sha256_range(path: str, offset: int, nbytes: int,
                  chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        fh.seek(offset)
        left = nbytes
        while left > 0:
            block = fh.read(min(chunk, left))
            if not block:
                break
            h.update(block)
            left -= len(block)
    return h.hexdigest()


def deep_verify(ckpt_dir: str) -> List[str]:
    """Chunk-level verification of the sharded payload layout
    (``tools/ckpt_verify.py --deep``; docs/RESILIENCE.md).

    The manifest's per-file sha256 (``verify_dir(level="full")``) proves a
    file changed; this pass reads every ``index_p*.json`` under
    ``ckpt_dir`` and re-hashes each recorded CHUNK byte range against the
    per-chunk ``sha256`` the sharded writer stores, so a bit flip is
    reported with the offending shard path AND pytree leaf — and two
    structural checks corruption of the index itself would hide behind:
    chunk ranges must lie inside their bin file, and a leaf's chunks must
    cover exactly its global element count (missing shard files
    under-cover).  Returns a list of problem strings (empty = clean).
    Checkpoints written before per-chunk hashes verify structurally only.

    Stdlib-only on purpose: ``tools/ckpt_verify.py`` execs this module by
    file path on operator boxes with no numpy/jax."""
    problems: List[str] = []
    for root, _dirs, files in os.walk(ckpt_dir):
        idx_names = sorted(n for n in files
                           if n.startswith("index_p") and n.endswith(".json"))
        if not idx_names:
            continue
        sub = os.path.relpath(root, ckpt_dir).replace(os.sep, "/")
        sub = "" if sub == "." else sub + "/"
        sizes = {n: os.path.getsize(os.path.join(root, n))
                 for n in files if not n.endswith(".json")}
        # leaf -> [total chunk elements, total declared elements] across
        # ALL process indexes (a leaf's chunks may span writers)
        coverage: Dict[str, List[int]] = {}
        for idx_name in idx_names:
            try:
                with open(os.path.join(root, idx_name)) as fh:
                    index = json.load(fh)
            except (OSError, ValueError) as exc:
                problems.append(f"{sub}{idx_name}: unreadable index ({exc})")
                continue
            for key, meta in sorted(index.items()):
                shape = meta.get("shape", [])
                want = 1
                for d in shape:
                    want *= int(d)
                cov = coverage.setdefault(key, [0, want])
                for k, ch in enumerate(meta.get("chunks", [])):
                    where = f"{sub}{ch.get('file', '?')} leaf {key!r} chunk {k}"
                    elems = 1
                    for a, b in ch.get("index", []):
                        elems *= max(0, int(b) - int(a))
                    fsize = sizes.get(ch.get("file"))
                    off, nb = int(ch.get("offset", -1)), int(ch.get("nbytes", -1))
                    if fsize is None or off < 0 or nb < 0 or off + nb > fsize:
                        problems.append(
                            f"{where}: byte range [{off}, {off + nb}) "
                            f"outside shard file (size {fsize})")
                        continue
                    # only structurally-sound chunks count toward leaf
                    # coverage (a truncated/missing shard must surface as
                    # under-coverage, not silently "cover" its region)
                    cov[0] += elems
                    rec = ch.get("sha256")
                    if rec:
                        got = _sha256_range(os.path.join(root, ch["file"]),
                                            off, nb)
                        if got != rec:
                            problems.append(f"{where}: chunk checksum "
                                            f"mismatch")
        for key, (have, want) in sorted(coverage.items()):
            if have < want:
                problems.append(f"{sub}: leaf {key!r} under-covered "
                                f"({have} of {want} elements; missing "
                                f"shard files?)")
    return problems


def read_latest(save_dir: str) -> Optional[str]:
    path = os.path.join(save_dir, LATEST_NAME)
    try:
        with open(path) as fh:
            tag = fh.read().strip()
        return tag or None
    except OSError:
        return None


def write_latest(save_dir: str, tag: str) -> None:
    """Atomic ``latest`` update: tmp + fsync + ``os.replace`` + dir fsync.
    A crash leaves either the old pointer or the new one, never a torn
    write."""
    path = os.path.join(save_dir, LATEST_NAME)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        fh.write(str(tag))
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    fsync_dir(save_dir)


def list_tags(save_dir: str) -> List[str]:
    """Published checkpoint tags in ``save_dir``, newest first.

    A tag is a non-hidden directory not carrying the ``tmp.`` stage
    prefix that looks like a checkpoint (has a manifest, or the legacy
    ``model_states`` payload).  Ordering key: manifest ``time_unix``,
    falling back to directory mtime for legacy tags."""
    if not os.path.isdir(save_dir):
        return []
    out = []
    for name in os.listdir(save_dir):
        if name.startswith(TMP_PREFIX) or name.startswith("."):
            continue
        path = os.path.join(save_dir, name)
        if not os.path.isdir(path):
            continue
        t = None
        mpath = os.path.join(path, MANIFEST_NAME)
        if os.path.exists(mpath):
            try:
                with open(mpath) as fh:
                    t = float(json.load(fh).get("time_unix", 0.0))
            except (OSError, ValueError):
                t = None
        elif not any(n.startswith("model_states")
                     for n in os.listdir(path)):
            continue
        if t is None:
            t = os.path.getmtime(path)
        out.append((t, name))
    return [name for _t, name in sorted(out, reverse=True)]


def clear_stage(save_dir: str, tag: str) -> None:
    """Remove a stale staged dir and any renamed-aside ``.trash.`` copies
    of this tag (debris of a crashed earlier save/publish)."""
    stage = stage_path(save_dir, tag)
    if os.path.isdir(stage):
        shutil.rmtree(stage, ignore_errors=True)
    prefix = f"{TRASH_PREFIX}{tag}."
    try:
        names = os.listdir(save_dir)
    except OSError:
        return
    for name in names:
        if name.startswith(prefix):
            shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)


def sweep_trash(save_dir: str) -> List[str]:
    """Remove every ``.trash.*`` dir (a publish that crashed between
    rename-aside and cleanup leaks one, checkpoint-sized).  Returns the
    names removed.  Safe after a completed publish: a live publish deletes
    its own trash before returning."""
    removed = []
    try:
        names = os.listdir(save_dir)
    except OSError:
        return removed
    for name in names:
        if name.startswith(TRASH_PREFIX):
            shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)
            removed.append(name)
    return removed


def publish_dir(stage_dir: str, final_dir: str) -> None:
    """Atomically rename the fully-written stage into place.

    Re-saving an existing tag cannot be atomic (POSIX rename refuses a
    non-empty target): the stale tag is first renamed aside to a hidden
    ``.trash.`` name — invisible to ``list_tags`` — so the worst crash
    window leaves the tag briefly ABSENT (the loader walks back), never
    half-overwritten."""
    trash = None
    if os.path.exists(final_dir):
        parent, name = os.path.split(final_dir)
        trash = os.path.join(parent, f"{TRASH_PREFIX}{name}.{os.getpid()}")
        os.rename(final_dir, trash)
    os.rename(stage_dir, final_dir)
    fsync_dir(os.path.dirname(final_dir) or ".")
    if trash is not None:
        shutil.rmtree(trash, ignore_errors=True)
