"""Pluggable checkpoint backends (reference:
``deepspeed/runtime/checkpoint_engine/``, SURVEY.md §2.1 "Checkpoint engine").

The default backend serializes the state pytree with flax msgpack (gathering
sharded arrays to host); the sharded tensorstore/OCDBT backend for large
models lives in ``deepspeed_tpu/checkpoint/`` (SURVEY.md §5.4 TPU note).
"""

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (CheckpointEngine,
                                                                       MsgpackCheckpointEngine)

__all__ = ["CheckpointEngine", "MsgpackCheckpointEngine"]
