"""Pluggable checkpoint backends (reference:
``deepspeed/runtime/checkpoint_engine/``, SURVEY.md §2.1 "Checkpoint engine").

``ShardedCheckpointEngine`` is the default: per-process shard files + JSON
index, streamed writes, resharding reads (the multi-host-safe
tensorstore/OCDBT shape of SURVEY.md §5.4).  ``MsgpackCheckpointEngine``
remains for small single-file payloads (inference exports, tools).
"""

from deepspeed_tpu.runtime.checkpoint_engine import atomic
from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import (CheckpointEngine,
                                                                       MsgpackCheckpointEngine)
from deepspeed_tpu.runtime.checkpoint_engine.sharded import (ShardedCheckpointEngine,
                                                             is_sharded_checkpoint)

__all__ = ["CheckpointEngine", "MsgpackCheckpointEngine",
           "ShardedCheckpointEngine", "is_sharded_checkpoint", "atomic"]
