"""Sharded, multi-host-safe checkpoint engine.

Reference: ``deepspeed/runtime/checkpoint_engine/`` + the per-rank
``*_zero_pp_rank_*`` shard files of the reference layout (SURVEY.md §5.4).
The TPU-native design is the tensorstore/OCDBT shape the survey prescribes,
dependency-free:

- **Each process writes only its addressable shards** — no full gather, ever.
  A leaf's bytes land in the writing process's ``shard_p{N}.bin``; replica
  deduplication keeps exactly one copy of every global element
  (``replica_id == 0``).
- **A JSON index per process** (``index_p{N}.json``) records, per pytree
  leaf: global shape, dtype, and the chunks (global slice -> file, offset,
  nbytes).  The checkpoint is the union of all (bin, index) pairs.
- **Streaming**: shards are copied device->host and written one at a time;
  peak host memory is O(largest shard), not O(model).
- **Resharding load**: ``load`` assembles any requested slice from the
  recorded chunks, so a checkpoint saved on one mesh/ZeRO stage loads on any
  other (``jax.make_array_from_callback`` with the target sharding — each
  device reads only the byte ranges it needs).

Scalars/ints and non-jax leaves are written by process 0 only (they are
replicated by construction).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from deepspeed_tpu.runtime.checkpoint_engine.checkpoint_engine import CheckpointEngine
from deepspeed_tpu.utils.logging import logger


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _keystr(kp) -> str:
    return jax.tree_util.keystr(kp)


def _norm_index(idx, shape) -> List[List[int]]:
    """Slice tuple -> [[start, stop], ...] with Nones resolved."""
    out = []
    for sl, dim in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


class ShardedCheckpointEngine(CheckpointEngine):
    """Per-process shard files + JSON index; resharding reads."""

    def __init__(self, config_params: Any = None):
        super().__init__(config_params)
        self.max_bytes_in_flight = 0  # peak single host buffer, for tests

    # ------------------------------------------------------------------
    # save
    # ------------------------------------------------------------------
    def save(self, state_dict: Any, path: str) -> None:
        proc = jax.process_index()
        os.makedirs(path, exist_ok=True)
        flat = jax.tree_util.tree_flatten_with_path(state_dict)[0]
        index: Dict[str, Any] = {}
        bin_name = f"shard_p{proc}.bin"
        bin_path = os.path.join(path, bin_name)
        offset = 0
        with open(bin_path + ".tmp", "wb") as fh:
            for kp, leaf in flat:
                key = _keystr(kp)
                chunks = []
                if isinstance(leaf, jax.Array):
                    shape = tuple(leaf.shape)
                    dtype = str(np.dtype(leaf.dtype))
                    for shard in leaf.addressable_shards:
                        if shard.replica_id != 0:
                            continue
                        data = np.asarray(shard.data)  # ONE shard on host
                        self.max_bytes_in_flight = max(self.max_bytes_in_flight,
                                                       data.nbytes)
                        raw = data.tobytes()
                        fh.write(raw)
                        # per-CHUNK sha256: deep verification
                        # (tools/ckpt_verify.py --deep) pinpoints the
                        # corrupted shard/leaf, not just the file
                        chunks.append({"index": _norm_index(shard.index, shape),
                                       "file": bin_name, "offset": offset,
                                       "nbytes": int(data.nbytes),
                                       "sha256":
                                           hashlib.sha256(raw).hexdigest()})
                        offset += data.nbytes
                else:
                    arr = np.asarray(leaf)
                    shape, dtype = tuple(arr.shape), str(arr.dtype)
                    if proc == 0:  # replicated host value: one writer
                        self.max_bytes_in_flight = max(self.max_bytes_in_flight,
                                                       arr.nbytes)
                        raw = np.ascontiguousarray(arr).tobytes()
                        fh.write(raw)
                        chunks.append({"index": [[0, d] for d in shape],
                                       "file": bin_name, "offset": offset,
                                       "nbytes": int(arr.nbytes),
                                       "sha256":
                                           hashlib.sha256(raw).hexdigest()})
                        offset += arr.nbytes
                index[key] = {"shape": list(shape), "dtype": dtype,
                              "chunks": chunks}
        os.replace(bin_path + ".tmp", bin_path)
        idx_path = os.path.join(path, f"index_p{proc}.json")
        with open(idx_path + ".tmp", "w") as fh:
            json.dump(index, fh)
        os.replace(idx_path + ".tmp", idx_path)

    # ------------------------------------------------------------------
    # load
    # ------------------------------------------------------------------
    @staticmethod
    def read_index(path: str) -> Dict[str, Any]:
        """Union of all per-process indexes (chunk lists concatenate)."""
        merged: Dict[str, Any] = {}
        names = sorted(n for n in os.listdir(path)
                       if n.startswith("index_p") and n.endswith(".json"))
        if not names:
            raise FileNotFoundError(f"no index_p*.json in {path}")
        for name in names:
            with open(os.path.join(path, name)) as fh:
                part = json.load(fh)
            for key, meta in part.items():
                if key in merged:
                    merged[key]["chunks"].extend(meta["chunks"])
                else:
                    merged[key] = meta
        return merged

    @staticmethod
    def _read_region(path: str, meta: Dict[str, Any], region: List[List[int]]
                     ) -> np.ndarray:
        """Assemble one global region from the stored chunks (reads only
        intersecting byte ranges via memmap)."""
        dtype = _np_dtype(meta["dtype"])
        shape = tuple(b - a for a, b in region)
        out = np.zeros(shape, dtype)
        covered = 0
        for ch in meta["chunks"]:
            cidx = ch["index"]
            inter = [(max(a0, b0), min(a1, b1))
                     for (a0, a1), (b0, b1) in zip(cidx, region)]
            if any(lo >= hi for lo, hi in inter):
                continue
            cshape = tuple(b - a for a, b in cidx)
            mm = np.memmap(os.path.join(path, ch["file"]), dtype=dtype,
                           mode="r", offset=ch["offset"],
                           shape=cshape if cshape else (1,))
            src = tuple(slice(lo - a, hi - a)
                        for (lo, hi), (a, _) in zip(inter, cidx))
            dst = tuple(slice(lo - b, hi - b)
                        for (lo, hi), (b, _) in zip(inter, region))
            if cshape:
                out[dst] = mm[src]
            else:
                out = mm[0].copy().reshape(())
            covered += int(np.prod([hi - lo for lo, hi in inter])) if cshape else 1
        want = int(np.prod(shape)) if shape else 1
        if covered < want:
            raise ValueError(f"checkpoint region under-covered: have {covered} "
                             f"of {want} elements (missing shard files?)")
        return out

    def load(self, path: str, target: Any = None, shardings: Any = None) -> Any:
        """Load a sharded checkpoint directory.

        - ``shardings`` (pytree of NamedSharding, same structure as saved):
          builds jax Arrays where each device reads only its slice.
        - ``target`` (pytree of array-likes): returns numpy leaves shaped
          like target (full assembly), preserving target structure.
        - neither: flat {keystr: ndarray} dict.
        """
        index = self.read_index(path)

        def full(key, meta):
            region = [[0, d] for d in meta["shape"]]
            return self._read_region(path, meta, region)

        if shardings is not None:
            flat, treedef = jax.tree_util.tree_flatten_with_path(shardings)
            leaves = []
            for kp, sh in flat:
                key = _keystr(kp)
                if key not in index:
                    raise KeyError(f"checkpoint {path} missing leaf {key}")
                meta = index[key]
                shape = tuple(meta["shape"])
                dtype = _np_dtype(meta["dtype"])

                def cb(idx, meta=meta, shape=shape, dtype=dtype):
                    region = _norm_index(idx, shape)
                    out = self._read_region(path, meta, region)
                    # NB: ascontiguousarray would promote 0-d to (1,)
                    return out if out.flags["C_CONTIGUOUS"] else np.ascontiguousarray(out)

                leaves.append(jax.make_array_from_callback(
                    shape, sh, cb, dtype=dtype))
            return jax.tree_util.tree_unflatten(treedef, leaves)
        if target is not None:
            flat, treedef = jax.tree_util.tree_flatten_with_path(target)
            leaves = []
            for kp, tgt in flat:
                key = _keystr(kp)
                if key not in index:
                    raise KeyError(f"checkpoint {path} missing leaf {key}")
                arr = full(key, index[key])
                leaves.append(arr)
            return jax.tree_util.tree_unflatten(treedef, leaves)
        return {key: full(key, meta) for key, meta in index.items()}


def is_sharded_checkpoint(path: str) -> bool:
    return os.path.isdir(path) and any(
        n.startswith("index_p") for n in os.listdir(path))


_KEY_SEG = __import__("re").compile(
    r"\[<flat index (\d+)>\]|\[(?:'([^']*)'|(\d+))\]|\.([A-Za-z_]\w*)")


def nest_keystrs(flat: Dict[str, Any]) -> Dict[Any, Any]:
    """{"['a'][0].count": v} -> {"a": {0: {"count": v}}}.

    Handles every jax keystr segment form: DictKey ``['k']``, SequenceKey
    ``[0]``, GetAttrKey ``.name`` (namedtuples in optimizer states), and
    FlattenedIndexKey ``[<flat index 0>]``.  Tools (zero_to_fp32, universal
    checkpoint) use this to re-nest the flat index keys into a pytree-shaped
    dict without knowing the original treedef."""
    out: Dict[Any, Any] = {}
    for key, val in flat.items():
        segs: List[Any] = []
        for m in _KEY_SEG.finditer(key):
            flat_idx, dkey, seq_idx, attr = m.groups()
            if flat_idx is not None:
                segs.append(int(flat_idx))
            elif dkey is not None:
                segs.append(dkey)
            elif seq_idx is not None:
                segs.append(int(seq_idx))
            else:
                segs.append(attr)
        if not segs:
            segs = [key]
        cur = out
        for s in segs[:-1]:
            cur = cur.setdefault(s, {})
        cur[segs[-1]] = val
    return out
