"""Checkpoint engine ABC + msgpack default backend."""

from __future__ import annotations

import abc
import os
from typing import Any

import jax

from deepspeed_tpu.utils.logging import logger


class CheckpointEngine(abc.ABC):
    """Save/load backend contract (reference: ``CheckpointEngine`` ABC)."""

    def __init__(self, config_params: Any = None):
        self.config_params = config_params

    def create(self, tag: str) -> None:
        logger.info("checkpoint: starting tag %s", tag)

    @abc.abstractmethod
    def save(self, state_dict: Any, path: str) -> None: ...

    @abc.abstractmethod
    def load(self, path: str, target: Any = None) -> Any: ...

    def commit(self, tag: str) -> bool:
        logger.info("checkpoint: committed tag %s", tag)
        return True


class MsgpackCheckpointEngine(CheckpointEngine):
    """flax-msgpack serialization of a full pytree (single-file-per-process).

    Sharded jax arrays are gathered to host on save; ``load`` returns numpy
    leaves which the caller re-shards via device_put with the target
    shardings (so a checkpoint saved under one ZeRO stage loads under any
    other — the cross-stage load matrix of SURVEY.md §4).
    """

    def save(self, state_dict: Any, path: str) -> None:
        from flax import serialization

        data = serialization.to_bytes(jax.device_get(state_dict))
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())  # crash-atomicity: durable before publish
        os.replace(tmp, path)

    def load(self, path: str, target: Any = None) -> Any:
        from flax import serialization

        with open(path, "rb") as fh:
            data = fh.read()
        if target is not None:
            return serialization.from_bytes(target, data)
        return serialization.msgpack_restore(data)
