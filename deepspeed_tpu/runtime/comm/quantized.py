"""Quantized / compressed collectives over named mesh axes.

Reference: ``deepspeed/runtime/comm/nccl.py`` (cupy sign-compressed
allreduce with error feedback for the 1-bit optimizers) and the ZeRO++
quantized collectives (``quantized_gradients``/qgZ all-to-all; SURVEY.md
§2.1 rows 26-27, PAPERS.md EQuARX).  TPU-native design: the compression
math is jnp (VPU-friendly bit packing), the transport is XLA collectives
(``all_to_all``/``all_gather``) over a named axis inside ``shard_map`` —
ICI carries int8/uint8 payloads instead of bf16/fp32.

All functions are *in-manual-region* primitives: call them inside a
``shard_map`` body with the axis name.  Comm volume is recorded through the
``comm`` façade so CommsLogger can assert the reduction.

- ``block_quantize`` / ``block_dequantize``: per-block absmax int8.
- ``quantized_all_gather``: int8 payload + fp32 scales, dequantize after.
- ``quantized_reduce_scatter``: qgZ shape — quantize once, all_to_all the
  int8 blocks, dequantize + reduce locally in fp32 (one quantization error
  per element, not log(P)).
- ``compressed_allreduce``: 1-bit sign compression with error feedback,
  the exact two-phase (worker -> server -> worker) scheme of the
  reference's NcclBackend.compressed_allreduce, signs bit-packed 8/byte.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm import comm as comm_api
from deepspeed_tpu.profiling.trace import scope as _scope

DEFAULT_BLOCK = 256


def _pad_to(x: jnp.ndarray, multiple: int) -> Tuple[jnp.ndarray, int]:
    n = x.size
    pad = (-n) % multiple
    flat = x.reshape(-1)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    return flat, pad


def block_quantize(x, block: int = DEFAULT_BLOCK):
    """Per-block symmetric absmax int8 quantization (delegates to the
    shared quantizer in ops/pallas/quantizer.py; the XLA path is used here
    because these run inside shard_map manual regions).

    Returns (q int8 [nblocks, block], scale fp32 [nblocks, 1], pad).
    """
    from deepspeed_tpu.ops.pallas.quantizer import quantize

    q, scale, pad = quantize(x, bits=8, block=block, impl="xla")
    return q, scale[:, None], pad


def block_dequantize(q, scale, pad: int, shape, dtype=jnp.float32):
    from deepspeed_tpu.ops.pallas.quantizer import dequantize

    return dequantize(q, scale.reshape(-1), pad, shape, dtype=dtype)


def pack_signs(x) -> jnp.ndarray:
    """fp tensor -> uint8 bitmap (1 bit/element, 8 elements/byte).
    Sign convention: bit=1 for x >= 0."""
    flat, _ = _pad_to(x, 8)
    bits = (flat.reshape(-1, 8) >= 0).astype(jnp.uint8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    return (bits * weights).sum(axis=-1).astype(jnp.uint8)


def unpack_signs(packed, n: int) -> jnp.ndarray:
    """uint8 bitmap -> {-1, +1} fp32 of length n."""
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    bits = (packed[:, None] & weights) > 0
    signs = jnp.where(bits, 1.0, -1.0).reshape(-1)[:n]
    return signs.astype(jnp.float32)


# ---------------------------------------------------------------------------
# in-shard_map collectives
# ---------------------------------------------------------------------------

def quantized_all_gather(x, axis: str, block: int = DEFAULT_BLOCK):
    """All-gather with int8 payload: each rank contributes its (quantized)
    local x; result is the dequantized concatenation along dim 0."""
    q, scale, pad = block_quantize(x, block)
    comm_api.comms_logger.record("q_all_gather", axis, q)
    with _scope("ds_comm_q_all_gather"):
        qg = lax.all_gather(q, axis, axis=0, tiled=False)       # [P, nb, block]
        sg = lax.all_gather(scale, axis, axis=0, tiled=False)   # [P, nb, 1]
    P = qg.shape[0]
    parts = (qg.astype(jnp.float32) * sg).reshape(P, -1)
    if pad:
        parts = parts[:, : parts.shape[1] - pad]
    return parts.reshape((P * x.shape[0],) + x.shape[1:]).astype(x.dtype)


def quantized_reduce_scatter(x, axis: str, block: int = DEFAULT_BLOCK):
    """Reduce-scatter with int8 transport (qgZ shape): quantize the local
    tensor once, all_to_all the int8 shards, dequantize and sum in fp32.

    ``x``: full local tensor, leading dim divisible by the axis size.
    Returns this rank's reduced shard (x.shape[0] // P leading dim).
    """
    import functools as _ft
    import numpy as _np

    P = lax.axis_size(axis)
    shard = x.shape[0] // P
    shard_elems = shard * int(_np.prod(x.shape[1:])) if x.ndim > 1 else shard
    xs = x.reshape(P, shard_elems)
    # quantize each destination shard separately so blocks never span shard
    # boundaries and scales travel with their blocks
    q, scale, _ = jax.vmap(_ft.partial(block_quantize, block=block))(xs)
    comm_api.comms_logger.record("q_reduce_scatter", axis, q)
    with _scope("ds_comm_q_reduce_scatter"):
        qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
        st = lax.all_to_all(scale, axis, split_axis=0, concat_axis=0, tiled=False)
    parts = (qt.astype(jnp.float32) * st).sum(axis=0)       # [nb, block]
    flat = parts.reshape(-1)[:shard_elems]
    return flat.reshape((shard,) + x.shape[1:]).astype(x.dtype)


def compressed_allreduce(x, error, server_error, axis: str):
    """1-bit sign-compressed allreduce with two-level error feedback
    (reference: NcclBackend.compressed_allreduce).

    x: local fp tensor; error/server_error: this rank's feedback buffers
    (same shape as x / x.size//P).  Returns (averaged tensor, new_error,
    new_server_error).  Transport: uint8 bitmaps (1 bit/element) + one fp32
    scale per rank-chunk, via all_to_all + all_gather.
    """
    P = lax.axis_size(axis)
    shape = x.shape
    n = x.size
    chunk = -(-n // P)  # ceil; pad so chunks are equal
    compensated = x.astype(jnp.float32) + error.astype(jnp.float32)
    flat, _ = _pad_to(compensated, P * 8)
    chunk = flat.size // P
    # worker compression: per-chunk L1 scale * sign
    chunks = flat.reshape(P, chunk)
    scale_w = jnp.mean(jnp.abs(chunks), axis=-1, keepdims=True)      # [P, 1]
    signs_w = jnp.where(chunks >= 0, 1.0, -1.0)
    new_error = (flat - (scale_w * signs_w).reshape(-1))[:n].reshape(shape)
    packed = jax.vmap(pack_signs)(chunks)                            # [P, chunk//8]
    comm_api.comms_logger.record("compressed_allreduce", axis, packed)
    # exchange: rank r receives chunk r from every rank
    with _scope("ds_comm_compressed_allreduce"):
        recv = lax.all_to_all(packed, axis, split_axis=0, concat_axis=0,
                              tiled=False)                           # [P, chunk//8]
        recv_scale = lax.all_to_all(scale_w, axis, split_axis=0, concat_axis=0,
                                    tiled=False)                     # [P, 1]
    decoded = jax.vmap(lambda p: unpack_signs(p, chunk))(recv)       # [P, chunk]
    avg = (decoded * recv_scale).mean(axis=0)                        # [chunk]
    # server compression of the averaged chunk, with server error feedback
    avg_comp = avg + server_error.astype(jnp.float32)
    scale_s = jnp.mean(jnp.abs(avg_comp))
    signs_s = jnp.where(avg_comp >= 0, 1.0, -1.0)
    new_server_error = avg_comp - scale_s * signs_s
    packed_s = pack_signs(avg_comp)[None]                            # [1, chunk//8]
    comm_api.comms_logger.record("compressed_allgather", axis, packed_s)
    with _scope("ds_comm_compressed_allgather"):
        gathered = lax.all_gather(packed_s[0], axis, axis=0, tiled=False)  # [P, chunk//8]
        gathered_scale = lax.all_gather(scale_s, axis, axis=0)       # [P]
    out = (jax.vmap(lambda p: unpack_signs(p, chunk))(gathered)
           * gathered_scale[:, None]).reshape(-1)[:n]
    return out.reshape(shape).astype(x.dtype), new_error, new_server_error
