"""Quantized / compressed collectives over named mesh axes — THIN layer.

The blockwise-int8 collectives that used to live here (the ZeRO++ qwAG /
qgZ specials) are now thin delegations into the comm-layer transport
``deepspeed_tpu/comm/collectives_q.py`` (ROADMAP item 2: int8 comm is a
property of the comm layer, not a ZeRO++ special).  The public surface —
``block_quantize`` / ``block_dequantize`` / ``quantized_all_gather`` /
``quantized_reduce_scatter`` — is unchanged; the codec is the shared
``comm/quant.py`` blockwise absmax form (the offload relay / int8 host
master codec), so every int8 byte in the system round-trips through ONE
implementation.

What stays here: :func:`compressed_allreduce` — the 1-bit sign
compression with two-level error feedback of the 1-bit optimizers
(reference: ``deepspeed/runtime/comm/nccl.py`` NcclBackend), which is a
different codec (1 bit + L1 scale, not blockwise int8) owned by the
onebit path.  Its int8 sibling with single-level error feedback is
``collectives_q.q_all_reduce``.

All functions are *in-manual-region* primitives: call them inside a
``shard_map`` body with the axis name.  Comm volume is recorded through
the ``comm`` façade so CommsLogger can assert the reduction.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm import collectives_q as cq
from deepspeed_tpu.comm import comm as comm_api
from deepspeed_tpu.comm.quant import (dequantize_blockwise,
                                      quantize_blockwise)
from deepspeed_tpu.profiling.trace import scope as _scope

DEFAULT_BLOCK = 256


def _pad_to(x: jnp.ndarray, multiple: int) -> Tuple[jnp.ndarray, int]:
    n = x.size
    pad = (-n) % multiple
    flat = x.reshape(-1)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    return flat, pad


def block_quantize(x, block: int = DEFAULT_BLOCK):
    """Per-block symmetric absmax int8 quantization via the shared
    ``comm/quant.py`` codec (the offload-relay / host-master convention).

    Returns (q int8 [nblocks, block], scale fp32 [nblocks, 1], pad).
    """
    q, scale = quantize_blockwise(x.astype(jnp.float32).reshape(-1),
                                  block=block)
    return q, scale, q.size - x.size


def block_dequantize(q, scale, pad: int, shape, dtype=jnp.float32):
    return dequantize_blockwise(q, scale.reshape(-1, 1), shape, dtype)


def pack_signs(x) -> jnp.ndarray:
    """fp tensor -> uint8 bitmap (1 bit/element, 8 elements/byte).
    Sign convention: bit=1 for x >= 0."""
    flat, _ = _pad_to(x, 8)
    bits = (flat.reshape(-1, 8) >= 0).astype(jnp.uint8)
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    return (bits * weights).sum(axis=-1).astype(jnp.uint8)


def unpack_signs(packed, n: int) -> jnp.ndarray:
    """uint8 bitmap -> {-1, +1} fp32 of length n."""
    weights = (2 ** jnp.arange(8, dtype=jnp.uint8))
    bits = (packed[:, None] & weights) > 0
    signs = jnp.where(bits, 1.0, -1.0).reshape(-1)[:n]
    return signs.astype(jnp.float32)


# ---------------------------------------------------------------------------
# in-shard_map collectives (delegations into comm/collectives_q.py)
# ---------------------------------------------------------------------------

def quantized_all_gather(x, axis: str, block: int = DEFAULT_BLOCK):
    """All-gather with int8 payload: each rank contributes its (quantized)
    local x; result is the dequantized concatenation along dim 0."""
    return cq.q_all_gather(x, axis, block=block)


def quantized_reduce_scatter(x, axis: str, block: int = DEFAULT_BLOCK):
    """Reduce-scatter with int8 transport (qgZ shape): quantize once,
    all_to_all the int8 blocks, dequantize + reduce locally in fp32 (one
    quantization error per element, not log(P)).

    ``x``: full local tensor, leading dim divisible by the axis size.
    Returns this rank's reduced shard (x.shape[0] // P leading dim).
    """
    return cq.q_reduce_scatter(x, axis, block=block)


def compressed_allreduce(x, error, server_error, axis: str):
    """1-bit sign-compressed allreduce with two-level error feedback
    (reference: NcclBackend.compressed_allreduce).

    x: local fp tensor; error/server_error: this rank's feedback buffers
    (same shape as x / x.size//P).  Returns (averaged tensor, new_error,
    new_server_error).  Transport: uint8 bitmaps (1 bit/element) + one fp32
    scale per rank-chunk, via all_to_all + all_gather.
    """
    P = lax.axis_size(axis)
    shape = x.shape
    n = x.size
    chunk = -(-n // P)  # ceil; pad so chunks are equal
    compensated = x.astype(jnp.float32) + error.astype(jnp.float32)
    flat, _ = _pad_to(compensated, P * 8)
    chunk = flat.size // P
    # worker compression: per-chunk L1 scale * sign
    chunks = flat.reshape(P, chunk)
    scale_w = jnp.mean(jnp.abs(chunks), axis=-1, keepdims=True)      # [P, 1]
    signs_w = jnp.where(chunks >= 0, 1.0, -1.0)
    new_error = (flat - (scale_w * signs_w).reshape(-1))[:n].reshape(shape)
    packed = jax.vmap(pack_signs)(chunks)                            # [P, chunk//8]
    comm_api.comms_logger.record("compressed_allreduce", axis, packed)
    # exchange: rank r receives chunk r from every rank
    with _scope("ds_comm_compressed_allreduce"):
        recv = lax.all_to_all(packed, axis, split_axis=0, concat_axis=0,
                              tiled=False)                           # [P, chunk//8]
        recv_scale = lax.all_to_all(scale_w, axis, split_axis=0, concat_axis=0,
                                    tiled=False)                     # [P, 1]
    decoded = jax.vmap(lambda p: unpack_signs(p, chunk))(recv)       # [P, chunk]
    avg = (decoded * recv_scale).mean(axis=0)                        # [chunk]
    # server compression of the averaged chunk, with server error feedback
    avg_comp = avg + server_error.astype(jnp.float32)
    scale_s = jnp.mean(jnp.abs(avg_comp))
    signs_s = jnp.where(avg_comp >= 0, 1.0, -1.0)
    new_server_error = avg_comp - scale_s * signs_s
    packed_s = pack_signs(avg_comp)[None]                            # [1, chunk//8]
    comm_api.comms_logger.record("compressed_allgather", axis, packed_s)
    with _scope("ds_comm_compressed_allgather"):
        gathered = lax.all_gather(packed_s[0], axis, axis=0, tiled=False)  # [P, chunk//8]
        gathered_scale = lax.all_gather(scale_s, axis, axis=0)       # [P]
    out = (jax.vmap(lambda p: unpack_signs(p, chunk))(gathered)
           * gathered_scale[:, None]).reshape(-1)[:n]
    return out.reshape(shape).astype(x.dtype), new_error, new_server_error
