"""Specialized comm paths (reference: ``deepspeed/runtime/comm/``,
SURVEY.md §2.1 rows 26-27): quantized/compressed collectives.  Coalesced
collectives are delivered by GSPMD bucketing (SURVEY §2.1 row 26 "by
design"); the quantized set lives in ``quantized.py``."""

from deepspeed_tpu.runtime.comm.quantized import (  # noqa: F401
    block_dequantize, block_quantize, compressed_allreduce, pack_signs,
    quantized_all_gather, quantized_reduce_scatter, unpack_signs)
