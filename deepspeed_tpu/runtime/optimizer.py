"""Optimizer construction from the ds_config ``optimizer`` section.

TPU-native analog of the reference's ``_configure_optimizer`` path
(SURVEY.md §3.2): the same type names (Adam, AdamW, FusedAdam, CPUAdam, Lamb,
FusedLamb, Lion, Adagrad, SGD, OneBitAdam, ZeroOneAdam, OneBitLamb) mapped to
optax gradient transformations.  The "fused" variants select the Pallas fused
update kernel (deepspeed_tpu/ops/adam/fused_adam.py) where beneficial; on the
jnp path XLA fuses the elementwise update chain anyway, which is most of what
CUDA fused-Adam bought.

1-bit variants are REAL when built through the engine: ``DeepSpeedEngine``
routes OneBitAdam/OneBitLamb/ZeroOneAdam to the shard_map error-feedback path
(``runtime/fp16/onebit/``) before this builder is consulted.  This module's
1-bit branch is only reachable when ``build_optimizer`` is called directly
(bypassing the engine) — there is no compressed-comm context in that case, so
it falls back dense with a loud warning naming the engine path.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Union

import optax

from deepspeed_tpu.utils.logging import logger

Schedule = Union[float, Callable[[Any], Any]]

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
FUSED_ADAM = "fusedadam"
CPU_ADAM = "deepspeedcpuadam"
LAMB_OPTIMIZER = "lamb"
FUSED_LAMB = "fusedlamb"
LION_OPTIMIZER = "lion"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
ONEBIT_ADAM = "onebitadam"
ZERO_ONE_ADAM = "zerooneadam"
ONEBIT_LAMB = "onebitlamb"
MUON = "muon"
ADAM_8BIT = "adam8bit"
ADAMW_8BIT = "adamw8bit"


def _adam_args(params: Dict[str, Any]) -> Dict[str, Any]:
    betas = params.get("betas", (0.9, 0.999))
    return dict(b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-8))


def build_optimizer(type_name: str, params: Dict[str, Any],
                    lr: Optional[Schedule] = None) -> optax.GradientTransformation:
    """Build an optax transformation for a ds_config optimizer type."""
    name = type_name.lower().replace("_", "").replace("-", "")
    p = dict(params)
    learning_rate: Schedule = lr if lr is not None else p.get("lr", 1e-3)
    wd = p.get("weight_decay", 0.0)

    if name in (ONEBIT_ADAM, ZERO_ONE_ADAM, ONEBIT_LAMB):
        # The engine never reaches this branch: it builds the real
        # compressed-communication optimizer (runtime/fp16/onebit/) before
        # consulting build_optimizer.  A direct build_optimizer() call has no
        # mesh/shard_map context to run error feedback over, so it degrades
        # dense — loudly, since training would otherwise silently diverge
        # from the named algorithm.
        logger.warning(
            "%s built via build_optimizer() directly: the compressed-"
            "communication path lives in the engine (deepspeed_tpu.initialize "
            "routes it to runtime/fp16/onebit); falling back to the DENSE %s "
            "update", type_name,
            "Lamb" if name == ONEBIT_LAMB else "AdamW")
        name = LAMB_OPTIMIZER if name == ONEBIT_LAMB else ADAMW_OPTIMIZER

    if name in (ADAM_8BIT, ADAMW_8BIT):
        # int8 blockwise optimizer states (~2 bytes/param for m+v instead of
        # 8) — the memory lever that fits the >1B single-chip training rung.
        from deepspeed_tpu.ops.adam.adam8bit import adam8bit

        a = _adam_args(p)
        return adam8bit(learning_rate, b1=a["b1"], b2=a["b2"], eps=a["eps"],
                        weight_decay=wd, block=p.get("block_size", 512),
                        min_quant_size=p.get("min_quant_size", 4096))
    if name == FUSED_ADAM:
        # The Pallas single-pass update kernel (ops/pallas/fused_adam.py);
        # "torch_adam": true opts back into the plain optax path, mirroring
        # the reference's escape hatch from the CUDA kernel.
        if not p.get("torch_adam", False):
            from deepspeed_tpu.ops.adam.fused_adam import fused_adam

            return fused_adam(
                learning_rate, weight_decay=wd,
                adam_w_mode=p.get("adam_w_mode", p.get("adamw_mode", True)),
                **_adam_args(p))
        name = ADAM_OPTIMIZER
    if name in (ADAM_OPTIMIZER, CPU_ADAM):
        # adam_w_mode (reference FusedAdam arg) selects decoupled weight decay.
        adam_w_mode = p.get("adam_w_mode", p.get("adamw_mode", True))
        if adam_w_mode:
            return optax.adamw(learning_rate, weight_decay=wd, **_adam_args(p))
        return optax.chain(optax.add_decayed_weights(wd) if wd else optax.identity(),
                           optax.adam(learning_rate, **_adam_args(p)))
    if name == ADAMW_OPTIMIZER:
        return optax.adamw(learning_rate, weight_decay=wd, **_adam_args(p))
    if name == FUSED_LAMB:
        # Pallas two-phase LAMB kernel (norm reductions fused into the
        # moment-update pass); "torch_lamb": true opts back into optax.
        if not p.get("torch_lamb", False):
            from deepspeed_tpu.ops.pallas.fused_lamb import fused_lamb

            a = _adam_args(p)
            return fused_lamb(learning_rate, beta1=a["b1"], beta2=a["b2"],
                              eps=p.get("eps", 1e-6), weight_decay=wd)
        name = LAMB_OPTIMIZER
    if name == LAMB_OPTIMIZER:
        return optax.lamb(learning_rate, weight_decay=wd, **_adam_args(p))
    if name == LION_OPTIMIZER:
        betas = p.get("betas", (0.9, 0.99))
        return optax.lion(learning_rate, b1=betas[0], b2=betas[1], weight_decay=wd)
    if name in (ADAGRAD_OPTIMIZER, "deepspeedcpuadagrad"):
        return optax.adagrad(learning_rate, eps=p.get("eps", 1e-10))
    if name == SGD_OPTIMIZER:
        return optax.sgd(learning_rate, momentum=p.get("momentum", 0.0),
                         nesterov=p.get("nesterov", False))
    if name == MUON:
        from deepspeed_tpu.ops.adam.muon import muon

        return muon(learning_rate, weight_decay=wd, momentum=p.get("momentum", 0.95),
                    nesterov=p.get("nesterov", True), ns_steps=p.get("ns_steps", 5))
    raise ValueError(f"Unknown optimizer type {type_name!r}")


def build_from_config(ds_config, lr_schedule: Optional[Schedule] = None) -> optax.GradientTransformation:
    """Build the optimizer the engine will use (reference: config "optimizer"
    section; falls back to AdamW when absent, with a log, since the engine
    must have an optimizer to train)."""
    if ds_config.optimizer is None:
        logger.info("no optimizer section in config; defaulting to AdamW(lr=1e-3)")
        return build_optimizer("AdamW", {"lr": 1e-3}, lr=lr_schedule)
    return build_optimizer(ds_config.optimizer.type, ds_config.optimizer.params, lr=lr_schedule)
