"""The DeepSpeed engine, TPU-native.

Analog of the reference's ``deepspeed/runtime/engine.py`` (SURVEY.md §2.1
"Engine", §3.2, §3.3) with a functional core: all training math lives in two
jitted, donated, mesh-sharded functions —

- ``_accum``: one micro-batch forward+backward; gradients (loss-scaled,
  divided by gradient_accumulation_steps) are added into a persistent
  accumulator whose sharding implements the ZeRO stage (reduce-scatter falls
  out of GSPMD when the accumulator is sharded over ``fsdp``).
- ``_apply``: the accumulation-boundary step — overflow check (fp16), unscale,
  global-norm clip, optax update, loss-scale transition, skip-on-overflow via
  select (the reference's eager "skip step" becomes a branchless where).

The imperative reference API (``engine.forward`` / ``backward`` / ``step``,
SURVEY.md §3.3) is preserved on top: ``forward`` runs the fused
forward+backward micro-step (dispatch is async on TPU, so this costs nothing
extra), ``backward`` is the recorded no-op that keeps user loops working, and
``step`` applies the update at the accumulation boundary.

ZeRO stages are placement policies (see runtime/zero/partition.py): the engine
computes PartitionSpecs for params/optimizer/accumulator once, then relies on
XLA/GSPMD for all-gathers, reduce-scatters, and comm/compute overlap — the
TPU replacement for the reference's bucketed IPG reducer and trace-based
prefetcher (SURVEY.md §3.3 TPU note).
"""

from __future__ import annotations

import functools
import json
import math
import os
import time
from typing import Any, Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm
from deepspeed_tpu.comm.mesh import batch_sharding, get_global_mesh, mesh_from_config
from deepspeed_tpu.monitor.comms import comm_metrics
from deepspeed_tpu.monitor.flight_recorder import get_flight_recorder
from deepspeed_tpu.monitor.goodput import get_goodput_ledger
from deepspeed_tpu.monitor.goodput_core import analytic_comm_seconds
from deepspeed_tpu.monitor.memory import MemoryTelemetry, device_resident_bytes
from deepspeed_tpu.monitor.metrics import get_registry
from deepspeed_tpu.monitor.monitor import MonitorMaster
from deepspeed_tpu.monitor.request_trace import get_step_timeline
from deepspeed_tpu.profiling.flops import TrainFlopsMeter, lm_flops_per_token
from deepspeed_tpu.profiling.trace import annotate, perfetto_supported
from deepspeed_tpu.runtime import optimizer as opt_builder
from deepspeed_tpu.runtime.checkpoint_engine import (MsgpackCheckpointEngine,
                                                     ShardedCheckpointEngine)
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, shard_batch
from deepspeed_tpu.runtime.fp16 import loss_scaler as scaler_lib
from deepspeed_tpu.runtime.lr_schedules import LRSchedulerShim, get_lr_schedule
from deepspeed_tpu.runtime.utils import (clip_grad_norm, global_norm, has_overflow,
                                         tree_num_params)
from deepspeed_tpu.runtime.zero.partition import (describe_partitioning, opt_state_pspecs,
                                                  params_pspecs, shardings_from_pspecs)
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer


class TrainState(NamedTuple):
    """The complete, donated training state pytree."""

    params: Any
    opt_state: Any
    grad_acc: Any
    global_steps: jnp.ndarray  # i32: optimizer steps actually applied
    scaler: scaler_lib.LossScaleState


# training-numerics gauges published at every optimizer boundary while the
# registry is enabled (values the engine already computes for _report);
# the namespace guard registers these explicitly so docs can't drift
TRAIN_STEP_GAUGES = {
    "ds_train_loss":
        "loss at the last optimizer boundary (the _report value, "
        "published every boundary while telemetry is on)",
    "ds_train_grad_norm":
        "global grad norm at the last optimizer boundary (pre-clip "
        "value from the step program)",
}


def _spec_world(spec, mesh) -> int:
    """Product of the mesh-axis extents a PartitionSpec shards over."""
    axes = []
    for part in spec:
        if part is None:
            continue
        axes.extend(part if isinstance(part, (tuple, list)) else (part,))
    w = 1
    for a in axes:
        w *= mesh.shape.get(a, 1)
    return max(1, w)


def _build_comm_plan(params, param_specs, acc_specs, mesh, zero_stage,
                     compute_dtype, acc_dtype, overlap_sched=None):
    """Analytic per-step collective volumes for the GSPMD ZeRO path.

    GSPMD inserts the ZeRO collectives implicitly (sharded accumulator ->
    reduce-scatter, sharded params -> all-gather), so there is no wrapper
    call site to count at.  What the schedule MUST move is still fully
    determined by the partitioning specs, so the engine commits this plan
    into the ``ds_comm_*`` series once per executed micro-batch/boundary:

    - stage 3: every sharded param all-gathers twice per micro-batch
      (forward + backward — the reference ZeRO-3 schedule);
    - stage >= 2: gradients reduce-scatter into the sharded accumulator
      once per micro-batch; stages 0/1 all-reduce them instead;
    - stages 1/2: the boundary update on sharded optimizer state implies
      one param all-gather back to the replicated layout.

    With ``overlap_sched`` (the layer-chunked explicit schedule,
    runtime/zero/overlap.py) the MICRO entries come from the schedule's
    own per-bucket accounting — per-bucket call counts and bytes, in the
    dtype the explicit collectives actually move — so the ``ds_comm_*``
    series stays honest when ``overlap_comm`` is on.  Boundary entries
    keep the GSPMD arithmetic (the overlap path leaves the boundary
    update on the GSPMD path).

    Returns ``{"micro": [entries], "boundary": [entries]}`` with entries
    shaped for :meth:`CommMetrics.commit`; empty lists when the mesh has no
    extent to communicate over.  Device-measured truth lives in the xplane
    trace — this is the byte ledger, not a timer.
    """
    dp_world = 1
    for a in ("dp", "fsdp", "ep"):
        dp_world *= mesh.shape.get(a, 1)
    c_item = jnp.dtype(compute_dtype).itemsize
    a_item = jnp.dtype(acc_dtype).itemsize
    cname = jnp.dtype(compute_dtype).name
    aname = jnp.dtype(acc_dtype).name

    p_leaves = jax.tree_util.tree_leaves(params)
    spec_leaves = jax.tree_util.tree_leaves(
        param_specs, is_leaf=lambda s: isinstance(s, P))
    acc_spec_leaves = jax.tree_util.tree_leaves(
        acc_specs, is_leaf=lambda s: isinstance(s, P))

    gather_bytes = gather_calls = 0
    gather_world = 1
    total_bytes = 0
    for leaf, spec in zip(p_leaves, spec_leaves):
        nbytes = int(np.prod(leaf.shape)) * c_item if leaf.shape else c_item
        total_bytes += nbytes
        w = _spec_world(spec, mesh)
        if w > 1:
            gather_bytes += nbytes
            gather_calls += 1
            gather_world = max(gather_world, w)

    rs_bytes = rs_calls = 0
    rs_world = 1
    for leaf, spec in zip(p_leaves, acc_spec_leaves):
        nbytes = int(np.prod(leaf.shape)) * a_item if leaf.shape else a_item
        w = _spec_world(spec, mesh)
        if w > 1:
            rs_bytes += nbytes
            rs_calls += 1
            rs_world = max(rs_world, w)

    micro: List[Tuple[str, int, int, str, int]] = []
    boundary: List[Tuple[str, int, int, str, int]] = []
    if overlap_sched is not None:
        micro = overlap_sched.comm_plan_entries()
        if zero_stage in (1, 2) and dp_world > 1 and total_bytes:
            boundary.append(("all_gather", len(p_leaves), total_bytes,
                             cname, dp_world))
        return {"micro": micro, "boundary": boundary}
    if zero_stage == 3 and gather_bytes:
        micro.append(("all_gather", 2 * gather_calls, 2 * gather_bytes,
                      cname, gather_world))
    if zero_stage >= 2 and rs_bytes:
        micro.append(("reduce_scatter", rs_calls, rs_bytes, aname, rs_world))
    elif dp_world > 1 and total_bytes:
        # replicated accumulator: each micro-batch's grads all-reduce over
        # the data axes (bytes in the accumulation dtype)
        micro.append(("all_reduce", len(p_leaves),
                      total_bytes * a_item // c_item, aname, dp_world))
    if zero_stage in (1, 2) and dp_world > 1 and total_bytes:
        # sharded-optimizer update -> updated params gather back replicated
        boundary.append(("all_gather", len(p_leaves), total_bytes, cname,
                         dp_world))
    return {"micro": micro, "boundary": boundary}


@functools.lru_cache(maxsize=None)
def _owned_copy(sharding):
    # memoized per sharding — a fresh jit(lambda) per call would re-trace
    # (dispatch cache keys on function identity); same pattern as the
    # make_array compat shim
    return jax.jit(lambda x: x.copy(), out_shardings=sharding)


@functools.lru_cache(maxsize=None)
def _dequant_put(shape, dtype_name, sharding):
    """Memoized compiled blockwise dequant for the int8 offload relay:
    (q int8 [nb, block], scale fp32 [nb, 1]) -> compute-dtype param leaf.
    Only the int8 payload crosses host->device; the wide array exists as a
    runtime-owned program output (safe to donate downstream)."""
    from deepspeed_tpu.comm.quant import dequantize_blockwise

    dt = jnp.dtype(dtype_name)
    return jax.jit(lambda q, s: dequantize_blockwise(q, s, shape, dt),
                   out_shardings=sharding)


def _owned_device_put(x, sharding):
    """``device_put`` that returns RUNTIME-OWNED buffers.

    The CPU runtime zero-copies aligned host numpy arrays, so the returned
    jax Array ALIASES the caller's buffer — and donating such an aliased
    array into a persistent-cache-DESERIALIZED executable corrupts it (the
    jaxlib bug the ``make_array_from_callback`` compat shim works around;
    reproduced here as the offload + grad-accumulation train going NaN
    from step 2 exactly when ``/tmp/dstpu_xla_cache`` is warm — the accum
    fn donates ``state.params``, which ``_step_offload`` rebuilds from
    host optimizer output every boundary).  Real accelerators copy H2D, so
    the extra device-side copy is CPU-only."""
    arr = jax.device_put(x, sharding)
    if jax.default_backend() != "cpu":
        return arr
    return _owned_copy(sharding)(arr)


def _owned_device_put_tree(tree, shardings):
    """Tree-valued :func:`_owned_device_put`: ``device_put`` a whole host
    tree, then (CPU only) reroute every leaf through the memoized compiled
    copy so no leaf aliases caller memory.  Used on every path that
    rebuilds ``state`` leaves from HOST arrays — checkpoint load, the
    pinned-refresh ``state`` property, the param-offload optimizer commit —
    because those leaves are donated into the compiled accum/apply fns on
    the next dispatch (dslint rule DSL001)."""
    arr = jax.device_put(tree, shardings)
    if jax.default_backend() != "cpu":
        return arr
    return jax.tree.map(lambda a: _owned_copy(a.sharding)(a), arr)


def _flight_guard(fn):
    """Dump the flight recorder (once) before re-raising an unhandled
    exception out of an engine entry point."""

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        try:
            return fn(self, *args, **kwargs)
        except Exception as exc:
            self._flight_crash(exc)
            raise

    return wrapped


class DeepSpeedEngine:
    def __init__(self, args=None, model=None, optimizer=None, model_parameters=None,
                 training_data=None, lr_scheduler=None, mpu=None, dist_init_required=None,
                 collate_fn=None, config=None, mesh=None, rng=None, loss_fn=None,
                 param_pspecs=None):
        if model is None and loss_fn is None:
            raise ValueError("deepspeed_tpu.initialize requires a model (flax module or "
                             "callable (params, batch, rng) -> loss)")
        self.module = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.collate_fn = collate_fn
        self.mpu = mpu

        if isinstance(config, DeepSpeedConfig):
            self.config = config
        else:
            # With an explicit mesh (and no mpu — the mpu's DP group keeps
            # reference precedence), the batch triad's world size is the
            # mesh's data-parallel extent (dp × fsdp × ep carry batch shards).
            ws = None
            ws_mesh = mesh if mesh is not None else get_global_mesh(create_default=False)
            if ws_mesh is not None and mpu is None:
                ws = comm.get_data_parallel_world_size(ws_mesh)
            self.config = DeepSpeedConfig(config, mpu=mpu, world_size=ws)
        comm.init_distributed(dist_init_required=dist_init_required, config=self.config)
        self.mesh = mesh or get_global_mesh()
        comm.set_global_mesh(self.mesh)
        comm.configure(deepspeed_config=self.config)

        self.zero_stage = self.config.zero_config.stage
        self.fp16_enabled = self.config.fp16_enabled
        self.bfloat16_enabled = self.config.bfloat16_enabled
        self.compute_dtype = self.config.dtype()

        # ZeRO-Offload / ZeRO-Infinity (SURVEY.md §2.1 rows "NVMe swap",
        # "ZeRO stage 1+2" cpu_offload): optimizer states live on host RAM or
        # NVMe; the device holds compute-dtype params + grad accumulator only.
        off_cfg = self.config.zero_config.offload_optimizer
        self._offload_device = off_cfg.device if off_cfg is not None else "none"
        self._offload = self._offload_device in ("cpu", "nvme")
        self._offload_opt = None
        self._relay_meter = None
        self._streamed = None
        self._np_params = None
        self._pinned_stale = False
        self._onebit_stacked = False
        if self._offload:
            log_dist(f"ZeRO-Offload: optimizer states -> {self._offload_device}"
                     + (f" ({off_cfg.nvme_path})" if self._offload_device == "nvme"
                        else ""), ranks=[0])
        p_off = self.config.zero_config.offload_param
        self._param_offload = p_off is not None and p_off.device in ("cpu", "nvme")
        if self._param_offload:
            # ZeRO-Infinity parameter tiering: compute-dtype params live in
            # pinned host memory; the model streams each scanned layer to the
            # device on demand (bounded window).  Implies host-resident
            # optimizer states (reference: offload_param requires
            # offload_optimizer in practice).
            if not self._offload:
                self._offload = True
                self._offload_device = p_off.device
            if self.config.fp16_enabled:
                raise ValueError("offload_param does not support fp16 loss "
                                 "scaling; use bf16 (TPU-native) instead")
            # NOTE: validated end-to-end on the CPU mesh and in small
            # real-TPU programs; the remote-tunnel TPU runtime in this
            # environment intermittently faults on programs with many
            # concurrent pinned-host DMA streams (runtime bug, reproduced
            # with minimal non-framework programs too) — on direct-attached
            # TPU VMs the standard memories API path below is the supported
            # configuration.
            log_dist(f"ZeRO-Infinity: params tiered to {p_off.device} "
                     "(per-layer device streaming)", ranks=[0])
        # 1-bit optimizers (reference: fp16/onebit/): need per-worker local
        # gradients, so the engine runs accum/apply under full-manual
        # shard_map over the data axes.  Like the reference, incompatible
        # with ZeRO >= 2, fp16 loss scaling, and model parallelism.
        _opt_name = (self.config.optimizer.type.lower().replace("_", "").replace("-", "")
                     if self.config.optimizer else "")
        self._onebit = (_opt_name in ("onebitadam", "zerooneadam", "onebitlamb")
                        and not self._offload)
        if (_opt_name in ("onebitadam", "zerooneadam", "onebitlamb")
                and self._offload):
            logger.warning("%s with offload_optimizer: the compressed-"
                           "communication path does not combine with host-"
                           "offloaded states (reference constraint); states "
                           "will be stepped by DeepSpeedCPUAdam instead",
                           self.config.optimizer.type)
        if self._onebit:
            if self.zero_stage >= 2:
                raise ValueError("1-bit optimizers do not support ZeRO stage >= 2 "
                                 "(reference constraint)")
            if self.fp16_enabled:
                raise ValueError("1-bit optimizers require bf16/fp32 (no fp16 "
                                 "loss scaling)")
            bad = [a for a in ("tp", "sp", "pp") if self.mesh.shape.get(a, 1) > 1]
            if bad:
                raise ValueError(f"1-bit optimizers do not support model "
                                 f"parallelism (axes {bad} > 1)")
            if self.config.gradient_clipping:
                # incompatible by construction (clipping local grads breaks
                # error feedback); the reference silently ignores the knob —
                # a one-shot warning is too easy to miss in a config sweep
                raise ValueError(
                    "gradient_clipping is not supported with 1-bit "
                    "optimizers (clipping local grads would break error "
                    "feedback) — remove gradient_clipping or use a dense "
                    "optimizer")
            log_dist(f"1-bit optimizer active: {self.config.optimizer.type} "
                     f"(compressed momentum exchange after freeze_step)", ranks=[0])
        # ZeRO++ (SURVEY §2.3; VERDICT r3 item 3): quantized weight
        # all-gathers / gradient reduce-scatters + hpZ secondary partition,
        # on the full-manual shard_map path (runtime/zero/zeropp.py).
        zc = self.config.zero_config
        cq = self.config.comm_quantization
        self._qcomm = cq
        # the comm_quantization gather/scatter sites are the comm-layer
        # spellings of the ZeRO++ flags: at stage 3 (and without
        # overlap_comm, which owns its own quantized schedule) they
        # activate the ZeRO++ path by themselves — either spelling alone
        # turns the seam on (config docstring contract)
        want_zpp = (zc.zero_quantized_weights or zc.zero_quantized_gradients
                    or zc.zero_hpz_partition_size > 1
                    or (self.zero_stage == 3 and not zc.overlap_comm
                        and (cq.q_all_gather or cq.q_reduce_scatter)))
        self._zeropp = False
        self._zeropp_reason = None
        if want_zpp:
            bad = [a for a in ("tp", "sp", "pp", "ep")
                   if self.mesh.shape.get(a, 1) > 1]
            P = self.mesh.shape.get("fsdp", 1)
            z = zc.zero_hpz_partition_size
            if self.zero_stage != 3:
                self._zeropp_reason = "requires ZeRO stage 3 (sharded params)"
            elif self._offload or self._onebit:
                self._zeropp_reason = ("not combinable with offload or 1-bit "
                                       "optimizers")
            elif self.fp16_enabled:
                self._zeropp_reason = "requires bf16/fp32 (no fp16 loss scaling)"
            elif bad:
                self._zeropp_reason = (f"model/expert-parallel axes {bad} are "
                                       "not supported on the ZeRO++ path")
            elif P <= 1:
                self._zeropp_reason = "needs an fsdp mesh axis > 1"
            elif z > 1 and P % z:
                self._zeropp_reason = f"hpz size {z} must divide fsdp={P}"
            else:
                self._zeropp = True
                log_dist(
                    f"ZeRO++ active: qw={zc.zero_quantized_weights} "
                    f"qg={zc.zero_quantized_gradients} hpz={max(1, z)} "
                    f"over fsdp={P}", ranks=[0])
        # Layer-chunked compute/collective overlap (runtime/zero/overlap.py;
        # ROADMAP open item 1): ``zero_optimization.overlap_comm: true``
        # replaces the GSPMD-placed ZeRO collectives with an explicit
        # per-layer-bucket schedule so comm hides under the matmuls.
        # Config-level eligibility decided here (audit warns on the knob
        # while ineligible); the model-level half (stream_segments, stacked
        # param layout) resolves at state init.
        self._overlap = False
        self._overlap_sched = None
        self._overlap_reason = None
        self._overlap_want = False
        if zc.overlap_comm:
            bad = [a for a in ("tp", "sp", "pp", "ep")
                   if self.mesh.shape.get(a, 1) > 1]
            if self.zero_stage not in (1, 2, 3):
                self._overlap_reason = ("requires ZeRO stage 1-3 (stage 0 "
                                        "has no sharded state to schedule)")
            elif self._offload or self._param_offload:
                self._overlap_reason = ("offload paths already own their "
                                        "own streaming schedule")
            elif self._onebit:
                self._overlap_reason = ("1-bit optimizers keep local grads "
                                        "(no collective to chunk)")
            elif self._zeropp:
                self._overlap_reason = ("ZeRO++ runs its own quantized "
                                        "collective schedule")
            elif bad:
                self._overlap_reason = (
                    f"model/expert-parallel axes {bad} are not supported "
                    "on the overlap path"
                    + (" (the pipelined program already overlaps its "
                       "boundary rings with stage compute — XLA schedules "
                       "the ppermute hops against the scan body)"
                       if "pp" in bad else ""))
            elif loss_fn is not None:
                self._overlap_reason = ("a client loss_fn cannot route "
                                        "through the model's layer segments")
            else:
                self._overlap_want = True
        # Unified quantized-collective transport (comm/collectives_q.py;
        # ROADMAP item 2): the `comm_quantization` block opts individual
        # call sites into int8 comm.  The grad_all_reduce site routes the
        # ZeRO stage 0/1/2 boundary gradient sync through an explicit
        # manual-region q_all_reduce with an error-feedback residual
        # carried as engine state; the other sites thread through the
        # overlap schedule, ZeRO++, MoE dispatch and the sequence ring.
        self._qcomm_grads = False
        self._qcomm_grads_reason = None
        self._qcomm_residual = None
        if cq.q_grad_all_reduce:
            # ep counts as a bad axis here, not a data axis: expert
            # params shard over ep, and the manual region would feed a
            # full-E dispatch into an E/ep-local expert tree (trace
            # crash) — and q_all_reduce over ep would average DIFFERENT
            # experts' gradient shards together
            bad = [a for a in ("tp", "sp", "pp", "ep")
                   if self.mesh.shape.get(a, 1) > 1]
            data_world = 1
            for a in ("dp", "fsdp", "ep"):
                data_world *= self.mesh.shape.get(a, 1)
            if self.zero_stage > 2:
                self._qcomm_grads_reason = (
                    "stage 3 has no boundary grad all-reduce — its "
                    "gathers/scatters quantize via overlap_comm or the "
                    "ZeRO++ flags")
            elif self._offload or self._param_offload:
                self._qcomm_grads_reason = (
                    "offloaded grads cross the host relay, not a "
                    "collective (offload_optimizer.int8_masters / "
                    "offload_param.int8_stream own that transport)")
            elif self._onebit:
                self._qcomm_grads_reason = ("1-bit optimizers already "
                                            "compress their exchange")
            elif self._overlap_want:
                self._qcomm_grads_reason = (
                    "overlap_comm owns the bucketed reduction schedule "
                    "(enable comm_quantization.reduce_scatter there)")
            elif self.fp16_enabled:
                self._qcomm_grads_reason = ("requires bf16/fp32 (no fp16 "
                                            "loss scaling)")
            elif bad:
                self._qcomm_grads_reason = (
                    f"model/expert-parallel axes {bad} are not supported "
                    "on the manual quantized-grad path (ep shards expert "
                    "params; tp/sp/pp shard the program)")
            elif data_world <= 1:
                self._qcomm_grads_reason = ("no data-parallel axis > 1 — "
                                            "there is no all-reduce to "
                                            "quantize")
            else:
                self._qcomm_grads = True
                log_dist(
                    f"comm_quantization: stage {self.zero_stage} gradient "
                    f"all-reduce -> int8 q_all_reduce (block {cq.block}, "
                    f"error_feedback={'on' if cq.error_feedback else 'OFF'})"
                    + ("" if cq.error_feedback else
                       " — compressed grads without the residual "
                       "accumulate quantization bias"), ranks=[0])
        self.gradient_accumulation_steps = lambda: self.config.gradient_accumulation_steps
        self.train_batch_size = lambda: self.config.train_batch_size
        self.train_micro_batch_size_per_gpu = lambda: self.config.train_micro_batch_size_per_gpu
        self._audit_config()
        if self.config.dump_state and comm.get_rank() == 0:
            self.config.print_config()

        self._rng = rng if rng is not None else jax.random.PRNGKey(self.config.seed)
        self._apply_activation_checkpointing_config(model)
        self._apply_pipeline_config(model)
        self._setup_compression(model)
        if self._param_offload:
            mcfg = getattr(model, "config", None)
            if mcfg is not None and hasattr(mcfg, "param_offload"):
                mcfg.param_offload = True
            else:
                logger.warning(
                    "offload_param: model %s does not expose a param_offload "
                    "hook; params stay host-resident but the model will not "
                    "stream them per-layer", type(model).__name__)
        # comm_quantization sites that live inside the MODEL's program
        # (MoE dispatch, sequence ring) are wired through the model
        # config, the param_offload idiom above.  Assigned UNCONDITIONALLY
        # (True or False): a model object reused across engines must not
        # keep a previous engine's quantization flags stuck on.
        _mcfg = getattr(model, "config", None)
        if _mcfg is not None and hasattr(_mcfg, "moe_q_dispatch"):
            _mcfg.comm_quant_block = cq.block
            _moe_q = bool(cq.q_all_to_all
                          and getattr(_mcfg, "num_experts", 0) > 0)
            _mcfg.moe_q_dispatch = _moe_q
            if _moe_q:
                log_dist("comm_quantization: MoE ep dispatch -> int8 "
                         "q_reshard (combine stays dense — replicated "
                         "codes would move MORE bytes than the "
                         "ep-sharded exchange)", ranks=[0])
            elif cq.q_all_to_all:
                logger.warning(
                    "comm_quantization.all_to_all: model has no MoE "
                    "layers — only explicit "
                    "all_to_all_single(quantized=True) callers quantize")
            # attention_core only takes the RING when sp_mode says so or
            # the head count forces it — otherwise ulysses runs and this
            # knob would be a lying log line
            _nsp = self.mesh.shape.get("sp", 1)
            _ntp = self.mesh.shape.get("tp", 1)
            _heads = int(getattr(_mcfg, "num_heads", 0) or 0)
            _local_heads = _heads // max(1, _ntp)
            _ring = (getattr(_mcfg, "sp_mode", "auto") == "ring"
                     or (_local_heads and _local_heads % _nsp))
            _ring_q = bool(cq.q_sequence_ring and _nsp > 1 and _ring)
            _mcfg.seq_ring_q = _ring_q
            if _ring_q:
                log_dist("comm_quantization: sequence-parallel ring KV "
                         "rotation -> int8 codes", ranks=[0])
            elif cq.q_sequence_ring and _nsp > 1:
                logger.warning(
                    "comm_quantization.sequence_ring is set but this "
                    "configuration resolves to ULYSSES attention "
                    "(sp_mode=%s, %d local heads divisible by sp=%d) — "
                    "the knob is inert; set the model's sp_mode='ring' "
                    "to opt the ring in",
                    getattr(_mcfg, "sp_mode", "auto"), _local_heads,
                    _nsp)
        elif cq.q_all_to_all or cq.q_sequence_ring:
            logger.warning(
                "comm_quantization: model %s exposes no comm-quant hooks "
                "(moe_q_dispatch/seq_ring_q); the all_to_all/"
                "sequence_ring sites stay dense", type(model).__name__)
        # pipeline boundary site (runtime/pipe/spmd.py): same unconditional
        # assignment rule — and the trace-time boundary ledger is ALWAYS
        # off under the engine, which commits its analytic per-execution
        # comm plan instead (_merge_pp_comm_plan; the feed-disjointness
        # rule)
        if _mcfg is not None and hasattr(_mcfg, "pp_boundary_q"):
            _npp = self.mesh.shape.get("pp", 1)
            _pp_q = bool(cq.q_pipeline and _npp > 1)
            _mcfg.pp_boundary_q = _pp_q
            _mcfg.comm_quant_block = cq.block
            _mcfg.pp_comm_record = False
            if _pp_q:
                log_dist("comm_quantization: pipeline boundary rings -> "
                         "int8 carry codec (fwd activation + bwd cotangent "
                         f"hops, block {cq.block})", ranks=[0])
        elif cq.q_pipeline:
            logger.warning(
                "comm_quantization.pipeline: model %s exposes no "
                "pp_boundary_q hook; the pipeline boundary stays dense",
                type(model).__name__)
        self._client_loss_fn = loss_fn is not None
        self._loss_fn = loss_fn or self._make_loss_fn(model)
        if param_pspecs is None and hasattr(model, "logical_pspecs"):
            # Built-in models publish their tensor/expert-parallel layout
            # (the AutoTP-equivalent classification, SURVEY.md §2.1).
            param_pspecs = model.logical_pspecs()
        self._client_param_pspecs = param_pspecs  # tensor-parallel logical specs
        self._micro_count = 0
        self._host_steps = 0
        self._pp_plan_pending = True   # pipeline comm-plan merge, 1st batch
        self._boundary_override: Optional[bool] = None
        self._last_loss = None
        self._last_grad_norm = None
        self._last_overflow = None
        self._state: Optional[TrainState] = None
        self._accum_fn = None
        self._apply_fn = None
        self._eval_fn = None
        self.optimizer = None  # optax transformation, set in _build_optimizer
        self._lr_schedule = None
        self.lr_scheduler = None
        self._build_optimizer()

        # Curriculum learning (reference: data_efficiency.data_sampling.
        # curriculum_learning / legacy top-level curriculum_learning):
        # seqlen difficulty applied by truncating batches before dispatch.
        self.curriculum_scheduler = None
        cl = {}
        if self.config.data_efficiency is not None:
            cl = self.config.data_efficiency.data_sampling.get(
                "curriculum_learning", {})
        if not cl.get("enabled"):
            cl = getattr(self.config, "curriculum_learning", {}) or {}
        if cl.get("enabled"):
            from deepspeed_tpu.runtime.data_pipeline import CurriculumScheduler

            self.curriculum_scheduler = CurriculumScheduler(cl)
            log_dist(f"curriculum learning: {cl.get('curriculum_type')} "
                     f"{self.curriculum_scheduler.min_difficulty} -> "
                     f"{self.curriculum_scheduler.max_difficulty}", ranks=[0])

        self.checkpoint_engine = ShardedCheckpointEngine(self.config.checkpoint_config)
        self.monitor = MonitorMaster(self.config)

        # -- training-side telemetry (docs/OBSERVABILITY.md) ------------
        # comms_logger block = the telemetry master switch for training:
        # turns on the registry so ds_comm_*/ds_mem_*/ds_train_* record.
        if self.config.comms_logger.enabled:
            get_registry().enable()
        self._comm_plan = None            # set by _setup_state_telemetry
        self._flops_per_step_fn = None    # (micro, seq) -> train FLOPs
        self._flops_since_boundary = 0.0
        self._flops_meter = TrainFlopsMeter()
        self._mem_telemetry = MemoryTelemetry()
        # training step timeline (docs/OBSERVABILITY.md "Distributed
        # tracing"): shares the telemetry master switch — a process that
        # records ds_* series also retains its step/micro spans for
        # /requestz?kind=train scrapes and trace_report --timeline
        self._timeline = get_step_timeline()
        if self.config.comms_logger.enabled:
            self._timeline.enable()
        self._flight = get_flight_recorder()
        self._flight_dumped = False
        frc = self.config.flight_recorder
        if frc.enabled:
            self._flight.enable(capacity=frc.capacity, dump_dir=frc.dump_dir)
            if frc.on_signal:
                self._flight.install_signal_handler()
        # -- run-level goodput ledger (docs/OBSERVABILITY.md "Goodput
        # ledger"): every second of run wall clock attributed to one
        # category, telescoping to now - run_start.  Config block or the
        # DSTPU_RUNLEDGER env (the supervisors' per-incarnation channel).
        self._goodput = get_goodput_ledger()
        gpc = self.config.goodput
        if gpc.enabled or os.environ.get("DSTPU_RUNLEDGER"):
            self._goodput.enable(
                path=gpc.path, role="train",
                min_tick_interval_s=gpc.min_tick_interval_s,
                slo_rules=self.config.slo.rules() or None)
        self._gp_comm_gbps = gpc.assumed_comm_gbps
        # per-boundary compute seconds (lag ring for the anomaly-skip
        # reattribution: the trip classifies the PREVIOUS boundary)
        self._gp_compute_since_boundary = 0.0
        self._gp_step_compute = [0.0, 0.0]   # [prev boundary, last boundary]

        # -- preemption grace-window handling (docs/RESILIENCE.md): the
        # SIGTERM handler only latches a flag; the next optimizer boundary
        # runs one emergency save (the watchdog/_aux_trace_tick boundary-
        # hook pattern).  Config-driven install here; the explicit API is
        # enable_preemption_save().
        self._preempt = None
        self._preempt_cfg = None
        self._preempt_client_state_fn = None
        ckc = self.config.checkpoint_config
        if ckc.preemption_save:
            if ckc.save_dir:
                self.enable_preemption_save(ckc.save_dir)
            else:
                logger.warning(
                    "checkpoint.preemption_save is set but checkpoint."
                    "save_dir is not: SIGTERM handler NOT installed "
                    "(nowhere to save)")

        # -- device-true profiling (docs/OBSERVABILITY.md "Device truth"):
        # one-shot auxiliary capture slot shared by /profilez requests and
        # watchdog trips ((TraceCapture, trigger, payload) or None), polled
        # at optimizer boundaries
        self._aux_trace = None
        from deepspeed_tpu.profiling.device_trace import get_profile_broker

        self._pz_broker = get_profile_broker()
        # step-time watchdog (ds_config `watchdog` block): rolling-median
        # anomaly detector; a trip dumps the flight recorder and arms a
        # one-shot trace capture of the following steps
        self._watchdog = None
        self._wd_last_t = None
        wdc = self.config.watchdog
        if wdc.enabled:
            from deepspeed_tpu.monitor.watchdog import StepWatchdog

            self._watchdog = StepWatchdog(factor=wdc.factor,
                                          window=wdc.window,
                                          warmup=wdc.warmup)
            if not self._flight.enabled:
                # a trip dump needs a populated ring; the watchdog implies
                # the recorder (documented)
                self._flight.enable(capacity=frc.capacity,
                                    dump_dir=wdc.output_path or frc.dump_dir)
            log_dist(f"watchdog armed: step > {wdc.factor:g}x rolling "
                     f"median (window {wdc.window}) dumps the flight "
                     f"recorder"
                     + (f" + captures {wdc.capture_steps} steps"
                        if wdc.trace and perfetto_supported() else ""),
                     ranks=[0])

        # bf16/fp32 anomaly containment (ds_config `anomaly_detection`;
        # docs/RESILIENCE.md "Elastic training"): rolling-median grad-norm
        # spike + non-finite detector.  Where the standard apply/fused
        # step compiles, the trip is a BRANCHLESS in-program select (the
        # fp16 has_overflow idiom); after `patience` consecutive trips
        # the boundary tick rolls back to the last-good checkpoint.
        self._anomaly = None
        self._anomaly_pending = None   # lag-1 deferred grad-norm fetch
        self._anomaly_select = False   # step programs compiled with the bound arg
        anc = self.config.anomaly_detection
        if anc.enabled:
            if self._zeropp or self._onebit:
                logger.warning(
                    "anomaly_detection: the ZeRO++/1-bit step programs do "
                    "not carry the in-program skip select; detector NOT "
                    "armed (use the standard/offload paths)")
            else:
                from deepspeed_tpu.monitor.anomaly import GradAnomalyDetector

                self._anomaly = GradAnomalyDetector(
                    factor=anc.factor, window=anc.window,
                    warmup=anc.warmup, patience=anc.patience)
                log_dist(
                    f"anomaly detector armed: grad norm non-finite or > "
                    f"{anc.factor:g}x rolling median skips the step; "
                    f"{anc.patience} consecutive trips roll back to the "
                    f"last-good checkpoint", ranks=[0])

        self.flops_profiler = None
        self._profile_probes = {}
        if self.config.flops_profiler.enabled:
            from deepspeed_tpu.profiling import FlopsProfiler

            self.flops_profiler = FlopsProfiler(ds_engine=self)
            self.flops_profiler.start_profile()
        # jax.profiler trace window (SURVEY §5.1; the NVTX/nsys analog):
        # enabled explicitly, or implied by wall_clock_breakdown
        self._trace = None
        ptc = self.config.profile_trace
        trace_on = bool(ptc.enabled or (ptc.enabled is None
                                        and self.config.wall_clock_breakdown))
        self.timers = SynchronizedWallClockTimer(
            synchronize=self.config.wall_clock_breakdown, annotate=trace_on)
        if trace_on:
            from deepspeed_tpu.profiling.trace import TraceCapture

            trace_dir = ptc.output_path or os.path.join(
                self.config.csv_monitor.output_path or "./csv_monitor",
                "ds_trace")
            self._trace = TraceCapture(trace_dir, start_step=ptc.start_step,
                                       num_steps=ptc.num_steps)
        # -- always-on continuous profiler (docs/OBSERVABILITY.md
        # "Continuous profiling"): scheduled low-duty-cycle device
        # captures feeding ds_comm_<op>_device_seconds + ds_prof_* with
        # no operator /profilez.  Disabled = a None slot and one branch
        # per boundary tick (the PR 3 contract); enabling it implies the
        # registry switch — an attribution feed nobody records is dead
        # weight.
        self._cprof = None
        cpc = self.config.continuous_profiler
        if cpc.enabled:
            from deepspeed_tpu.profiling.continuous import ContinuousProfiler
            from deepspeed_tpu.profiling.continuous import ensure_registered

            get_registry().enable()
            ensure_registered(get_registry())
            self._cprof = ContinuousProfiler(
                engine="train",
                every_steps=cpc.every_steps,
                every_seconds=cpc.every_seconds,
                capture_steps=cpc.capture_steps,
                max_duty_cycle=cpc.max_duty_cycle,
                history_dir=cpc.history_dir,
                max_windows=cpc.max_windows,
                max_bytes=cpc.max_bytes,
                regression_tolerance=cpc.regression_tolerance,
                min_scope_seconds=cpc.min_scope_seconds,
                bytes_per_op_fn=self._profile_bytes_per_op,
                flight=self._flight)
            log_dist(
                f"continuous profiler armed: {cpc.capture_steps}-step "
                f"window every {cpc.every_steps} steps or "
                f"{cpc.every_seconds:g}s (duty cycle <= "
                f"{100 * cpc.max_duty_cycle:g}%) -> {cpc.history_dir}",
                ranks=[0])
        self.tput_timer = ThroughputTimer(batch_size=self.config.train_batch_size)
        self.training_dataloader = None
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)
        self._training = True

        # Params supplied eagerly -> materialize state now; else lazy-init on
        # the first batch (zero.Init-equivalent abstract init, SURVEY.md §7.4).
        if model_parameters is not None:
            self._init_state(model_parameters)

    # ------------------------------------------------------------------
    # construction helpers
    # ------------------------------------------------------------------
    def _audit_config(self) -> None:
        """Loud degradation for parsed-but-inert config (VERDICT r3 item 6).

        Two classes of accepted keys exist and only one warns:

        - *by-design no-ops*: knobs whose capability XLA/GSPMD delivers
          structurally (bucket sizes, ``contiguous_gradients``,
          ``prescale_gradients`` — gradient scaling order is numerically
          immaterial inside one XLA program, ``round_robin_gradients`` — a
          CUDA-stream scheduling detail).  These stay silent: the behavior
          the user asked for happens.  ``overlap_comm`` moved OUT of this
          class: true now activates the layer-chunked explicit overlap
          schedule (runtime/zero/overlap.py) and warns when the
          configuration cannot take it.
        - *inert behavior knobs*: sections that would change observable
          behavior and currently change nothing.  Each warns once here so a
          capability gap can never hide behind a successfully-parsed config.
        """
        cfg = self.config
        zc = cfg.zero_config
        inert = []
        if cfg.amp.enabled:
            inert.append(("amp", "torch/apex AMP has no TPU analog; use the "
                                 "bf16 (recommended) or fp16 sections"))
        if cfg.sparse_gradients_enabled:
            inert.append(("sparse_gradients", "sparse gradient compaction is "
                          "not implemented (dense grads are always exchanged)"))
        if cfg.communication_data_type:
            inert.append(("communication_data_type", "collective dtype "
                          "follows the compute dtype under GSPMD"))
        if zc.overlap_comm and not self._overlap_want:
            inert.append(("zero_optimization.overlap_comm",
                          f"{self._overlap_reason}; the GSPMD-placed "
                          "collectives run unchanged"))
        if not self._zeropp_active():
            if zc.zero_quantized_weights:
                inert.append(("zero_optimization.zero_quantized_weights",
                              self._zeropp_inactive_reason()))
            if zc.zero_quantized_gradients:
                inert.append(("zero_optimization.zero_quantized_gradients",
                              self._zeropp_inactive_reason()))
            if zc.zero_hpz_partition_size > 1:
                inert.append(("zero_optimization.zero_hpz_partition_size",
                              self._zeropp_inactive_reason()))
        cq = self.config.comm_quantization
        if cq.q_grad_all_reduce and not self._qcomm_grads:
            inert.append(("comm_quantization.grad_all_reduce",
                          f"{self._qcomm_grads_reason}; the gradient sync "
                          "runs dense"))
        if ((cq.q_all_gather or cq.q_reduce_scatter)
                and not (self._overlap_want or self._zeropp)):
            inert.append(("comm_quantization.all_gather/reduce_scatter",
                          "no explicit gather/scatter seam in this "
                          "configuration (GSPMD places dense collectives) "
                          "— enable zero_optimization.overlap_comm or the "
                          "ZeRO++ stage-3 path"))
        if cq.q_sequence_ring and self.mesh.shape.get("sp", 1) <= 1:
            inert.append(("comm_quantization.sequence_ring",
                          "no sp mesh axis > 1 — there is no ring "
                          "exchange to quantize"))
        if cq.q_pipeline and self.mesh.shape.get("pp", 1) <= 1:
            inert.append(("comm_quantization.pipeline",
                          "no pp mesh axis > 1 — there is no stage "
                          "boundary ring to quantize"))
        import logging as _logging

        for key, why in inert:
            log_dist(f"config key {key!r} is set but INERT: {why}",
                     ranks=[0], level=_logging.WARNING)
        self._inert_config_keys = [k for k, _ in inert]

    def _zeropp_active(self) -> bool:
        """Whether the ZeRO++ quantized-collective path is active;
        _audit_config warns on the ZeRO++ knobs exactly while this is
        False (with the specific reason)."""
        return self._zeropp

    def _zeropp_inactive_reason(self) -> str:
        why = self._zeropp_reason or "ZeRO++ path not applicable"
        return f"{why}; the knob changes nothing"

    def _setup_overlap(self, params, persist: int) -> None:
        """Model-level half of the ``overlap_comm`` gate (config half ran in
        ``__init__``): the bucketed schedule drives the model through its
        streamed per-layer segments, so the model must expose
        ``stream_segments`` and carry the stacked embed/layers/head param
        layout.  On success, replaces ``self._param_specs`` with the
        layer-dim-0-safe variant and marks the overlap path active."""
        from deepspeed_tpu.runtime.zero.overlap import layerwise_pspecs

        reason = None
        seg = None
        if not hasattr(self.module, "stream_segments"):
            reason = (f"model {type(self.module).__name__} exposes no "
                      "stream_segments (the per-layer contract the bucketed "
                      "schedule drives)")
        else:
            seg = self.module.stream_segments()
            if seg is None:
                reason = ("model declined segmenting (e.g. pipeline "
                          "parallelism owns the layer loop)")
        if reason is None:
            keys = set(params) if isinstance(params, dict) else set()
            if not {"embed", "layers", "final_norm"} <= keys or \
                    not keys <= {"embed", "layers", "final_norm", "lm_head",
                                 "lm_head_bias"}:
                reason = ("param tree is not the stacked embed/layers/head "
                          "layout the bucketed schedule slices")
        if reason is not None:
            self._overlap_reason = reason
            logger.warning(
                "zero_optimization.overlap_comm: %s — falling back to the "
                "GSPMD-placed collective schedule", reason)
            return
        self._overlap = True
        self._overlap_segments = seg
        if self.zero_stage == 3:
            self._param_specs = layerwise_pspecs(
                params, self.mesh, shard=True,
                persistence_threshold=persist,
                logical_specs=self._client_param_pspecs)
        log_dist(
            f"overlap_comm active: layer-chunked collective schedule, "
            f"bucket={self.config.zero_config.overlap_bucket_layers} "
            f"layer(s), zero stage {self.zero_stage} "
            f"(runtime/zero/overlap.py)", ranks=[0])

    def _apply_activation_checkpointing_config(self, model) -> None:
        """Push the ds_config ``activation_checkpointing`` section into the
        model (reference: runtime/activation_checkpointing/checkpointing.py
        ``configure()`` — there a global; here the engine owns the remat
        transform applied in the model forward)."""
        ac = self.config.activation_checkpointing
        mcfg = getattr(model, "config", None)
        if mcfg is None or not hasattr(mcfg, "remat"):
            return
        section_active = (ac.enabled is not None or ac.partition_activations
                          or ac.cpu_checkpointing)
        if ac.enabled is not None:
            mcfg.remat = ac.enabled
        elif section_active:
            # reference configs enable the subsystem via these knobs
            mcfg.remat = True
        # Only take over the policy when the config section is actually in
        # play; otherwise a model built with remat_policy="dots" would be
        # silently reset to the section's default.
        if section_active and hasattr(mcfg, "remat_policy"):
            # cpu_checkpointing: saved residuals page to pinned host memory
            # (the offloaded-dots policy) — overrides the plain policy knob
            if ac.cpu_checkpointing and ac.policy not in ("full",
                                                          "offload_dots"):
                logger.warning(
                    "activation_checkpointing: cpu_checkpointing overrides "
                    "policy=%r with 'offload_dots' (host-paged residuals); "
                    "drop cpu_checkpointing to keep the device-resident "
                    "policy", ac.policy)
            mcfg.remat_policy = ("offload_dots" if ac.cpu_checkpointing
                                 else ac.policy)

    def _setup_compression(self, model) -> None:
        """Wire the compression scheduler (reference compression/scheduler.py
        role): when the ds_config ``compression_training`` section enables a
        pruning method — or ``init_compression`` already attached one to the
        model — the engine consults the scheduler after each optimizer step,
        so ``schedule_offset`` activates without the caller threading
        global_step (VERDICT r4 item 8)."""
        from deepspeed_tpu.compression.compress import (CompressedParams,
                                                        CompressionScheduler)

        self._compression_sched = None
        comp = getattr(model, "_compression", None)
        if comp is None:
            sec = self.config.compression_training
            d = {"compression_training": {
                "sparse_pruning": sec.sparse_pruning,
                "row_pruning": sec.row_pruning,
                "head_pruning": sec.head_pruning,
                "channel_pruning": sec.channel_pruning,
                "weight_quantization": sec.weight_quantization,
                "layer_reduction": sec.layer_reduction}}
            probe = CompressedParams(
                d, num_heads=getattr(getattr(model, "config", None),
                                     "num_heads", None))
            if not probe.cfg.any_pruning:
                return
            comp = probe
            model._compression = comp
        if comp.num_heads is None:
            comp.num_heads = getattr(getattr(model, "config", None),
                                     "num_heads", None)
        if comp.cfg.any_pruning:
            self._compression_sched = CompressionScheduler(comp)
            log_dist("compression scheduler active: sparse=%s row=%s head=%s"
                     % (comp.cfg.sp_enabled, comp.cfg.rp_enabled,
                        comp.cfg.hp_enabled), ranks=[0])

    def _maybe_apply_compression(self) -> None:
        if self._compression_sched is None or self._state is None:
            return
        if getattr(self, "_param_offload", False):
            if not getattr(self, "_warned_comp_offload", False):
                self._warned_comp_offload = True
                logger.warning("compression scheduler skipped: params live "
                               "as host masters under param offload (prune "
                               "via redundancy_clean at export instead)")
            return
        new_params = self._compression_sched.after_step(
            self._state.params, self._host_steps)
        if new_params is not None:
            self._state = self._state._replace(params=new_params)

    def _apply_pipeline_config(self, model) -> None:
        """Push the ds_config ``pipeline`` section into the model: reference
        ``PipelineEngine`` knobs mapped to the SPMD pipeline —
        ``micro_batches`` (reference ``train_batch()`` microbatching) and
        ``schedule`` ("gpipe" fill-drain with autodiff, or "1f1b" — the
        reference TrainSchedule's in-flight-bounded fused schedule)."""
        sec = self.config.pipeline or {}
        mcfg = getattr(model, "config", None)
        if mcfg is None or not hasattr(mcfg, "pp_schedule"):
            if sec:
                logger.warning(
                    "ds_config pipeline section %s ignored: the model "
                    "carries no ModelConfig with pipeline knobs", sec)
            return
        if "micro_batches" in sec:
            mcfg.pp_microbatches = int(sec["micro_batches"])
        sched = sec.get("schedule")
        if sched is not None:
            if sched not in ("gpipe", "1f1b"):
                raise ValueError(
                    f"pipeline.schedule must be 'gpipe' or '1f1b', got "
                    f"{sched!r}")
            mcfg.pp_schedule = sched

    @property
    def state(self) -> Optional["TrainState"]:
        """Training state.  In streamed offload mode the pinned-host param
        copy refreshes lazily here — the hot loop trains from the numpy
        masters and never pays the full-model host->pinned copy per step;
        external readers (eval, checkpointing, fragments) always see the
        current weights."""
        if self._pinned_stale:
            self._pinned_stale = False
            # owned put: _np_params are live host masters; an aliased
            # refresh leaf reaching a donated fn is the PR 2/4/10 class
            self._state = self._state._replace(
                params=_owned_device_put_tree(self._np_params,
                                              self._param_shardings))
        return self._state

    @state.setter
    def state(self, value) -> None:
        self._state = value
        self._pinned_stale = False

    def _make_loss_fn(self, model) -> Callable:
        if hasattr(model, "apply"):  # flax module computing loss in __call__
            def loss_fn(params, batch, rng):
                kwargs = {"rngs": {"dropout": rng}}
                if isinstance(batch, (tuple, list)):
                    return model.apply(params, *batch, **kwargs)
                if isinstance(batch, dict):
                    return model.apply(params, **batch, **kwargs)
                return model.apply(params, batch, **kwargs)

            return loss_fn
        if callable(model):
            def loss_fn(params, batch, rng):
                return model(params, batch)

            return loss_fn
        raise TypeError(f"Unsupported model type {type(model)}")

    def _build_optimizer(self) -> None:
        if self.config.scheduler is not None:
            self._lr_schedule = get_lr_schedule(self.config.scheduler.type,
                                                self.config.scheduler.params)
        elif callable(self.client_lr_scheduler):
            self._lr_schedule = self.client_lr_scheduler
        if self._onebit:
            from deepspeed_tpu.runtime.fp16.onebit.adam import onebit_from_config

            waxes = ("dp", "fsdp", "ep")
            world = int(np.prod([self.mesh.shape.get(a, 1) for a in waxes]))
            self.optimizer = onebit_from_config(
                self.config.optimizer.type, dict(self.config.optimizer.params),
                world=world, axis_names=waxes)
            self.lr_scheduler = (LRSchedulerShim(self._lr_schedule)
                                 if self._lr_schedule is not None else None)
            return
        if self._offload:
            # The reference swaps in DeepSpeedCPUAdam when offload is active
            # (SURVEY.md §3.2 _configure_optimizer); the device-side
            # transformation is identity — all update math runs on host.
            import optax

            if self.client_optimizer is not None:
                logger.warning(
                    "offload_optimizer is enabled: the supplied client "
                    "optimizer (%s) is ignored; states will be stepped by "
                    "DeepSpeedCPUAdam on the host",
                    type(self.client_optimizer).__name__)
            opt_type = (self.config.optimizer.type if self.config.optimizer
                        else "AdamW").lower().replace("_", "").replace("-", "")
            if "adagrad" in opt_type:
                self._offload_opt_type = "adagrad"
            elif "lion" in opt_type:
                self._offload_opt_type = "lion"
            else:
                self._offload_opt_type = "adam"
                if "adam" not in opt_type:
                    logger.warning(
                        "offload_optimizer supports the Adam/Adagrad/Lion "
                        "families; %s config will be stepped by "
                        "DeepSpeedCPUAdam", opt_type)
            self.optimizer = optax.identity()
        elif self.client_optimizer is not None:
            self.optimizer = self.client_optimizer
            if self.config.zero_allow_untested_optimizer:
                log_dist("using client optimizer with ZeRO (zero_allow_untested_optimizer)",
                         ranks=[0])
        else:
            self.optimizer = opt_builder.build_from_config(self.config, self._lr_schedule)
        self.lr_scheduler = (LRSchedulerShim(self._lr_schedule)
                             if self._lr_schedule is not None else None)

    def _init_state_zeropp(self, params: Any) -> None:
        """ZeRO++ state: flat per-leaf fp32 shards over ``fsdp`` (+ hpZ
        secondary copy), optimizer state sharded alike.  See
        runtime/zero/zeropp.py for the layout and collectives."""
        from deepspeed_tpu.runtime.zero import zeropp as zpp

        mesh = self.mesh
        zc = self.config.zero_config
        cq = self.config.comm_quantization
        Pfsdp = self.mesh.shape.get("fsdp", 1)
        z = max(1, zc.zero_hpz_partition_size)
        # the comm_quantization sites are the comm-layer spellings of the
        # legacy ZeRO++ flags (same seam, documented precedence): either
        # alone turns the quantized transport on here — otherwise an
        # hpz-only ZeRO++ config would silently ignore the block
        q_weights = zc.zero_quantized_weights or cq.q_all_gather
        q_grads = zc.zero_quantized_gradients or cq.q_reduce_scatter
        if (q_weights != zc.zero_quantized_weights
                or q_grads != zc.zero_quantized_gradients):
            log_dist(f"ZeRO++ transport driven by comm_quantization: "
                     f"qw={q_weights} qg={q_grads}", ranks=[0])
        self._zpp_cfg = zpp.ZeroPPConfig(
            axis="fsdp", world=Pfsdp, hpz=z,
            q_weights=q_weights,
            q_grads=q_grads,
            compute_dtype=self.compute_dtype)
        self._zpp_shapes = jax.tree.map(lambda p: tuple(p.shape), params)
        self._zpp_lens = zpp.flatten_spec(self._zpp_shapes, Pfsdp)
        fsdp_sh = NamedSharding(mesh, P("fsdp"))
        scalar_sh = NamedSharding(mesh, P())
        lens = self._zpp_lens
        if (self.bfloat16_enabled and not self.config.bf16.master_weights) \
                or self.config.data_types.grad_accum_dtype is not None:
            logger.warning(
                "ZeRO++ path keeps fp32 primary shards and fp32 grad "
                "accumulators (ZeRO-3 master semantics); "
                "bf16.master_weights/data_types.grad_accum_dtype are "
                "ignored here")
        from deepspeed_tpu.runtime.zero.zeropp import flat_grads as _flatten

        primary = jax.jit(lambda pr: _flatten(pr, lens),
                          out_shardings=jax.tree.map(
                              lambda _: fsdp_sh, lens))(params)
        prim_spec = jax.tree.map(lambda _: P("fsdp"), lens)
        # non-quantized secondaries carry a scalar scale placeholder, which
        # must stay replicated (P()); quantized scales are per-block arrays
        secs_spec = jax.tree.map(
            lambda _: P("fsdp") if zc.zero_quantized_weights else P(), lens)
        if z > 1:
            import functools

            sec_fn = jax.jit(jax.shard_map(
                functools.partial(zpp.refresh_secondary, cfg=self._zpp_cfg),
                mesh=mesh, in_specs=(prim_spec,),
                out_specs=(prim_spec, secs_spec),
                axis_names={"dp", "fsdp", "ep"}, check_vma=False))
            sec_q, sec_s = sec_fn(primary)
        else:
            sec_q, sec_s = (), ()
        from deepspeed_tpu.runtime.zero.zeropp import ZeroPPParams

        self._zpp_state_param_specs = ZeroPPParams(
            primary=prim_spec,
            secondary_q=jax.tree.map(lambda _: P("fsdp"), lens) if z > 1 else (),
            secondary_s=secs_spec if z > 1 else ())
        zp = ZeroPPParams(primary=primary, secondary_q=sec_q, secondary_s=sec_s)
        self._param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), self._zpp_state_param_specs,
            is_leaf=lambda x: isinstance(x, P))
        # Optimizer state is initialized on the LOCAL shards (inside
        # shard_map) and stored stacked over fsdp: optimizers whose state
        # layout depends on the leaf size (Adam8bit's [nb, block] int8
        # blocks) must see the same shapes at init and at update — a global
        # init would bake in the unsharded layout and crash the in-region
        # update.  For elementwise optimizers (optax Adam et al.) local
        # init + stacking is identical to sharding a global init.
        local_struct = jax.tree.map(
            lambda L: jax.ShapeDtypeStruct((L // Pfsdp,), jnp.float32), lens)
        opt_shapes = jax.eval_shape(self.optimizer.init, local_struct)
        opt_specs = jax.tree.map(
            lambda l: P() if getattr(l, "ndim", 0) == 0 else P("fsdp"),
            opt_shapes)
        self._zpp_opt_specs = opt_specs
        self._opt_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), opt_specs,
            is_leaf=lambda x: isinstance(x, P))
        prim_spec_tree = jax.tree.map(lambda _: P("fsdp"), lens)
        opt_state = jax.jit(jax.shard_map(
            self.optimizer.init, mesh=mesh, in_specs=(prim_spec_tree,),
            out_specs=opt_specs, check_vma=False))(primary)
        grad_acc = jax.jit(
            lambda pr: jax.tree.map(jnp.zeros_like, pr),
            out_shardings=jax.tree.map(lambda _: fsdp_sh, lens))(primary)
        self._acc_shardings = jax.tree.map(lambda _: fsdp_sh, lens)
        self.state = TrainState(params=zp, opt_state=opt_state,
                                grad_acc=grad_acc,
                                global_steps=jnp.zeros((), jnp.int32),
                                scaler=scaler_lib.make_state(self.config.fp16))
        self._compile_steps()
        n = tree_num_params(params)
        log_dist(f"engine ready (ZeRO++): {n/1e6:.2f}M params, "
                 f"qw={self._zpp_cfg.q_weights} qg={self._zpp_cfg.q_grads} "
                 f"hpz={self._zpp_cfg.hpz}, mesh {dict(self.mesh.shape)}",
                 ranks=[0])
        self._setup_state_telemetry(n)

    def _init_state(self, params: Any) -> None:
        """Build shardings for the full state and compile the step functions."""
        if (self._client_param_pspecs is None
                and self.mesh.shape.get("tp", 1) > 1):
            # model without logical_pspecs on a tp>1 mesh: generic AutoTP —
            # classify column/row splits by name analysis (reference
            # auto_tp.py role)
            from deepspeed_tpu.module_inject.auto_tp import autotp_pspecs

            self._client_param_pspecs = autotp_pspecs(params)
            log_dist("AutoTP: derived tp layout from param names "
                     "(no logical_pspecs on the model)", ranks=[0])
        if self._zeropp:
            return self._init_state_zeropp(params)
        mesh = self.mesh
        zcfg = self.config.zero_config
        persist = zcfg.stage3_param_persistence_threshold if self.zero_stage == 3 else 0

        self._param_specs = params_pspecs(params, mesh, shard=self.zero_stage == 3,
                                          persistence_threshold=persist,
                                          logical_specs=self._client_param_pspecs)
        if self._overlap_want:
            # may replace self._param_specs (stacked-layer dim 0 must stay
            # device-local for the bucketed schedule) and set self._overlap
            self._setup_overlap(params, persist)
        self._onebit_stacked = (self._onebit
                                and getattr(self.optimizer, "stacked_params", False))
        if self._onebit_stacked:
            # 0/1 Adam: replicas legitimately diverge between syncs, so
            # params carry an explicit [W] worker axis sharded over the data
            # axes (each device holds exactly its replica — same bytes as
            # replication)
            waxes = ("dp", "fsdp", "ep")
            self._param_specs = jax.tree.map(
                lambda s: P(waxes, *tuple(s)), self._param_specs)
        self._param_shardings = shardings_from_pspecs(self._param_specs, mesh)
        if self._onebit and hasattr(self.optimizer, "state_pspecs"):
            self._opt_specs = self.optimizer.state_pspecs(params,
                                                          ("dp", "fsdp", "ep"))
        elif self._onebit:
            self._opt_specs = self._onebit_opt_specs(params)
        else:
            opt_shapes = jax.eval_shape(self.optimizer.init, params)
            self._opt_specs = opt_state_pspecs(opt_shapes, mesh, shard=self.zero_stage >= 1)
        self._opt_shardings = shardings_from_pspecs(self._opt_specs, mesh)
        # Gradient accumulator: sharded from stage 2 up (reduce-scatter), or
        # like params under stage 3 (grads of sharded params are sharded).
        acc_shard = self.zero_stage >= 2
        if self._onebit or self._qcomm_grads:
            # per-worker LOCAL grad accumulators, stacked on a leading [W]
            # axis sharded over the data axes (each device holds exactly
            # its own running sum).  The 1-bit path needs this because its
            # compression is defined over local grads; the quantized
            # grad-all-reduce path needs it because the whole point is to
            # defer the reduction to the boundary and move int8 there —
            # note the ZeRO-2 sharded-accumulator memory saving is traded
            # away on this path (full-size local sums, like 1-bit).
            waxes = ("dp", "fsdp", "ep")
            self._acc_specs = jax.tree.map(
                lambda p: P(waxes, *([None] * getattr(p, "ndim", 0))), params)
            if self._qcomm_grads and self.zero_stage == 2:
                log_dist("comm_quantization.grad_all_reduce at ZeRO stage "
                         "2: gradients accumulate LOCALLY (full-size) and "
                         "reduce once per boundary — the stage-2 sharded-"
                         "accumulator memory saving is traded for int8 "
                         "boundary bytes", ranks=[0])
        elif self._overlap:
            # overlap schedule: stage 3 accumulates in EXACTLY the param
            # layout (each bucket's reduce-scatter is the gather's
            # transpose — the shard shapes must line up); stage 2 shards
            # with the same layer-dim-0 constraint; stage 1 replicates as
            # before
            from deepspeed_tpu.runtime.zero.overlap import layerwise_pspecs

            if self.zero_stage == 3:
                self._acc_specs = self._param_specs
            elif self.zero_stage == 2:
                self._acc_specs = layerwise_pspecs(
                    params, mesh, shard=True, persistence_threshold=0,
                    logical_specs=self._client_param_pspecs)
            else:
                self._acc_specs = params_pspecs(
                    params, mesh, shard=False,
                    logical_specs=self._client_param_pspecs)
        else:
            self._acc_specs = params_pspecs(params, mesh, shard=acc_shard,
                                            persistence_threshold=0 if acc_shard else persist,
                                            logical_specs=self._client_param_pspecs)
        self._acc_shardings = shardings_from_pspecs(self._acc_specs, mesh)
        if self._param_offload:
            if hasattr(self.module, "set_param_offload_specs"):
                self.module.set_param_offload_specs(self._param_specs)
            # params live in pinned host memory (streamed per-layer by the
            # model); gradients exit the program on device (XLA's SPMD
            # partitioner cannot yet emit host-placed outputs on multi-device
            # meshes) and are copied straight into numpy accumulators — the
            # only transient device-resident [model]-sized buffer is the grad
            # output at the program boundary.
            self._param_dev_shardings = self._param_shardings
            from deepspeed_tpu.accelerator.real_accelerator import \
                host_memory_kind
            hk = host_memory_kind()
            if hk is not None:
                if hk != "pinned_host":
                    # capability gate (ROADMAP): this backend has no pinned
                    # host memory space — commit the "host" masters to its
                    # host-side kind instead (on CPU that IS the default
                    # memory, so the placement is a no-op and the streamed
                    # offload machinery runs unchanged)
                    log_dist(f"ZeRO-Infinity: backend has no pinned_host "
                             f"memory kind; params placed in {hk!r} "
                             f"(gated fallback)", ranks=[0])
                self._param_shardings = jax.tree.map(
                    lambda s: NamedSharding(s.mesh, s.spec, memory_kind=hk),
                    self._param_shardings)
            else:  # pragma: no cover - clients without the memories API
                logger.warning(
                    "ZeRO-Infinity: backend exposes no memory-kind API; "
                    "params keep the default placement (no host tiering)")
            self._acc_specs = ()
            self._acc_shardings = ()
            self._host_grad_acc = None
        scalar_sh = NamedSharding(mesh, P())
        self._state_shardings = TrainState(
            params=self._param_shardings, opt_state=self._opt_shardings,
            grad_acc=self._acc_shardings, global_steps=scalar_sh,
            scaler=scaler_lib.LossScaleState(scalar_sh, scalar_sh, scalar_sh, scalar_sh))

        # Materialize state on-device, already sharded (zero.Init semantics:
        # nothing is ever resident unsharded).
        if self._offload:
            # Host takes the fp32 masters; the device keeps ONE compute-dtype
            # copy (bf16 halves resident param bytes, and no fp32
            # master/moments ever touch HBM — the ZeRO-Offload contract).
            self._build_offload_optimizer(params)
            cdtype = self.compute_dtype

            def to_compute(p):
                return jax.tree.map(
                    lambda x: x.astype(cdtype)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, p)

            if self._param_offload:
                # cast on device, then hop to pinned host outside jit (the
                # SPMD partitioner rejects host-placed jit outputs on
                # multi-device meshes)
                params = jax.jit(to_compute,
                                 out_shardings=self._param_dev_shardings)(params)
                params = jax.device_put(params, self._param_shardings)
            else:
                params = jax.jit(to_compute, out_shardings=self._param_shardings)(params)
        elif self._onebit_stacked:
            # must win over the master-free bf16 branch below: the stacked
            # specs/opt state are built for [W]-leading leaves, so the cast
            # (when bf16.master_weights=false) composes with the stacking
            W = self.optimizer.world
            master_free = (self.bfloat16_enabled
                           and not self.config.bf16.master_weights)
            if master_free:
                logger.warning(
                    "bf16.master_weights=false with optimizer %s: plain "
                    "round-to-nearest bf16 updates lose sub-ulp steps",
                    self.config.optimizer.type if self.config.optimizer else "?")

            def stack(x):
                if master_free and jnp.issubdtype(x.dtype, jnp.floating):
                    x = x.astype(jnp.bfloat16)
                return jnp.broadcast_to(x[None], (W,) + x.shape)

            params = jax.jit(lambda p: jax.tree.map(stack, p),
                             out_shardings=self._param_shardings)(params)
        elif self.bfloat16_enabled and not self.config.bf16.master_weights:
            # Master-free bf16: the persistent training state IS bf16 (no
            # fp32 master, no fp32 grads anywhere in the step program).
            # Requires an optimizer that rounds stochastically (Adam8bit);
            # round-to-nearest would drop sub-ulp updates and stall training.
            if not getattr(self.optimizer, "updates_are_new_params", False):
                logger.warning(
                    "bf16.master_weights=false with optimizer %s: plain "
                    "round-to-nearest bf16 updates lose sub-ulp steps; use "
                    "Adam8bit (stochastic rounding) for master-free training",
                    self.config.optimizer.type if self.config.optimizer else "?")
            params = jax.jit(
                lambda p: jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x, p),
                out_shardings=self._param_shardings)(params)
        else:
            params = jax.jit(lambda p: p, out_shardings=self._param_shardings)(params)
        opt_state = jax.jit(self.optimizer.init, out_shardings=self._opt_shardings)(params)
        if self._param_offload:
            grad_acc = ()
        elif self._onebit or self._qcomm_grads:
            W = (self.optimizer.world if self._onebit
                 else comm.get_data_parallel_world_size(self.mesh))
            strip = 1 if self._onebit_stacked else 0
            grad_acc = jax.jit(
                lambda p: jax.tree.map(
                    lambda x: jnp.zeros((W,) + x.shape[strip:], jnp.float32), p),
                out_shardings=self._acc_shardings)(params)
        else:
            grad_acc = jax.jit(
                lambda p: jax.tree.map(lambda x: jnp.zeros(x.shape, self._acc_dtype(x.dtype)), p),
                out_shardings=self._acc_shardings)(params)
        self.state = TrainState(params=params, opt_state=opt_state, grad_acc=grad_acc,  # dslint: disable=DSL001 -- every leaf here is a jit OUTPUT (runtime-owned); the device_put above only re-homes a compiled cast to the pinned-host space, no host-numpy alias exists
                                global_steps=jnp.zeros((), jnp.int32),
                                scaler=scaler_lib.make_state(self.config.fp16))
        self._compile_steps()
        n = tree_num_params(params)
        log_dist(f"engine ready: {n/1e6:.2f}M params, zero stage {self.zero_stage}, "
                 f"dtype {self.compute_dtype.__name__}, mesh {dict(self.mesh.shape)}", ranks=[0])
        if self.zero_stage == 3:
            logger.info(describe_partitioning(params, self._param_specs))
        self._setup_state_telemetry(n)

    def _acc_dtype(self, param_dtype):
        # data_types.grad_accum_dtype (reference key): bf16 halves the
        # persistent accumulator; fp32 (default) is exact.  The 1-bit path
        # keeps fp32 (error feedback is defined over fp32 local grads).
        if self._onebit:
            return jnp.float32
        return self.config.grad_accum_dtype()

    def _onebit_opt_specs(self, params):
        """PartitionSpecs for OneBitState: moments/count replicated; the
        error-feedback buffers carry a leading per-worker axis."""
        from deepspeed_tpu.runtime.fp16.onebit.adam import OneBitState

        waxes = ("dp", "fsdp", "ep")
        rep = jax.tree.map(lambda p: P(), params)
        stacked = jax.tree.map(
            lambda p: P(waxes, *([None] * getattr(p, "ndim", 0))), params)
        serr = jax.tree.map(lambda p: P(waxes, None), params)
        return OneBitState(exp_avg=rep, exp_avg_sq=jax.tree.map(lambda p: P(), params),
                           error=stacked, server_error=serr, count=P())

    def _build_offload_optimizer(self, params) -> None:
        from deepspeed_tpu.runtime.zero.offload import OffloadedOptimizer
        from deepspeed_tpu.runtime.zero.streaming import RelayMeter

        # one ds_offload_* relay ledger per process; the streamed path's
        # ParamStreamer registers the same instruments (same registry keys)
        self._relay_meter = RelayMeter()

        p = dict(self.config.optimizer.params) if self.config.optimizer else {}
        betas = tuple(p.get("betas", (0.9, 0.999)))
        off = self.config.zero_config.offload_optimizer
        self._offload_opt = OffloadedOptimizer(
            jax.device_get(params),
            backend=self._offload_device,
            lr=p.get("lr", 1e-3), betas=betas, eps=p.get("eps", 1e-8),
            weight_decay=p.get("weight_decay", 0.0),
            adamw_mode=p.get("adam_w_mode", p.get("adamw_mode", True)),
            swap_dir=off.nvme_path, aio_config=self.config.aio,
            pipeline=off.pipeline_read,
            pipeline_write=off.pipeline_write,
            opt_type=getattr(self, "_offload_opt_type", "adam"),
            int8_masters=bool(getattr(off, "int8_masters", False)
                              and self._offload_device == "cpu"),
            quant_block=int(getattr(off, "quant_block", 256)))

    def lazy_init_from_batch(self, batch: Any) -> None:
        """zero.Init-equivalent: abstract-init then shard-on-create
        (reference: ``deepspeed.zero.Init`` module-interception,
        SURVEY.md §2.1 "zero.Init / partitioned params")."""
        if self.state is not None:
            return
        if not hasattr(self.module, "init"):
            raise ValueError("model has no .init(); pass model_parameters to initialize()")
        self._rng, init_rng = jax.random.split(self._rng)

        def init_fn(rng, b):
            if isinstance(b, (tuple, list)):
                return self.module.init(rng, *b)
            if isinstance(b, dict):
                # batch keys init doesn't take (e.g. loss_mask — an apply()
                # arg, irrelevant to param shapes) must not break a
                # first-call dict batch
                import inspect
                try:
                    sig = inspect.signature(self.module.init)
                    if not any(p.kind == p.VAR_KEYWORD
                               for p in sig.parameters.values()):
                        b = {k: v for k, v in b.items()
                             if k in sig.parameters}
                except (TypeError, ValueError):
                    pass
                return self.module.init(rng, **b)
            return self.module.init(rng, b)

        # Master-free bf16: fold the cast into the init program so the fp32
        # init values are per-buffer transients — the full fp32 tree (2x the
        # persistent params) never materializes.  At the 1.34B single-chip
        # rung that transient alone is ~5.4GB of the 15.75GB budget.
        master_free = (self.bfloat16_enabled
                       and not self.config.bf16.master_weights
                       and not self._offload)
        build_fn = init_fn
        if master_free:
            def build_fn(rng, b):
                return jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16)
                    if jnp.issubdtype(x.dtype, jnp.floating) else x,
                    init_fn(rng, b))

        abstract = jax.eval_shape(build_fn, init_rng, batch)
        zcfg = self.config.zero_config
        persist = zcfg.stage3_param_persistence_threshold if self.zero_stage == 3 else 0
        specs = params_pspecs(abstract, self.mesh, shard=self.zero_stage == 3,
                              persistence_threshold=persist,
                              logical_specs=self._client_param_pspecs)
        shardings = shardings_from_pspecs(specs, self.mesh)
        params = jax.jit(build_fn, out_shardings=shardings)(init_rng, batch)
        self._init_state(params)

    # ------------------------------------------------------------------
    # jitted step functions
    # ------------------------------------------------------------------
    def _compile_steps(self) -> None:
        self._flight.record("compile", what="train step functions",
                            zero_stage=self.zero_stage)
        # ledger: step-program (re)builds are `recompile`, not compute —
        # nested pushes (an elastic rescale recompiling mid-run) stack
        self._goodput.push("recompile")
        try:
            self._compile_steps_inner()
        finally:
            self._goodput.pop()

    def _compile_steps_inner(self) -> None:
        self._anomaly_select = False   # set by the paths that compile the bound arg
        cfg = self.config
        gas = cfg.gradient_accumulation_steps
        compute_dtype = self.compute_dtype
        fp16 = self.fp16_enabled
        clip = cfg.gradient_clipping
        loss_fn = self._loss_fn
        fp16_cfg = cfg.fp16

        def cast_params(p):
            if compute_dtype == jnp.float32:
                return p
            return jax.tree.map(
                lambda x: x.astype(compute_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, p)

        def accum(state: TrainState, batch, rng):
            scale = state.scaler.scale if fp16 else jnp.float32(1.0)

            def scaled_loss_fn(params):
                loss = loss_fn(cast_params(params), batch, rng)
                return (loss.astype(jnp.float32) * scale) / gas, loss

            # named_scope: fwd/bwd ops carry this prefix in the xplane trace
            with jax.named_scope("ds_fwd_bwd"):
                grads, loss = jax.grad(scaled_loss_fn, has_aux=True)(state.params)
                new_acc = jax.tree.map(lambda a, g: a + g.astype(a.dtype),
                                       state.grad_acc, grads)
            return state._replace(grad_acc=new_acc), loss

        # bf16/fp32 anomaly containment: compile the step with an extra
        # traced `anomaly_bound` scalar and fold "grad norm non-finite or
        # above the bound" into the SAME branchless skip select fp16
        # overflow uses — the skipped step is a no-op on params/opt state
        # and does not advance global_steps.  Disabled (default): the
        # programs below are exactly the pre-anomaly forms.
        anomaly_on = self._anomaly is not None

        @jax.named_scope("ds_optimizer_step")
        def apply(state: TrainState, anomaly_bound):
            scale = state.scaler.scale if fp16 else jnp.float32(1.0)
            overflow = has_overflow(state.grad_acc) if fp16 else jnp.zeros((), bool)
            # No-op unscale when fp16 is off: dividing a bf16 accumulator by
            # an fp32 scalar would silently promote the whole grad tree to
            # fp32, materializing the O(model) buffer bf16 accumulation
            # exists to avoid.
            grads = (jax.tree.map(lambda g: g / scale, state.grad_acc)
                     if fp16 else state.grad_acc)
            if clip > 0:
                grads, gnorm = clip_grad_norm(grads, clip)
            else:
                gnorm = global_norm(grads)
            if anomaly_on:
                overflow = (overflow | ~jnp.isfinite(gnorm)
                            | (gnorm > anomaly_bound))
            updates, new_opt = self.optimizer.update(grads, state.opt_state, state.params)
            if getattr(self.optimizer, "updates_are_new_params", False):
                # adam8bit-style transformations return new params directly
                # (stochastic rounding cannot round-trip through a delta)
                new_params = updates
            else:
                import optax

                new_params = optax.apply_updates(state.params, updates)
            if fp16 or anomaly_on:
                sel = lambda new, old: jax.tree.map(
                    lambda a, b: jnp.where(overflow, b, a), new, old)
                new_params = sel(new_params, state.params)
                new_opt = sel(new_opt, state.opt_state)
            new_scaler = scaler_lib.update(
                state.scaler, overflow, dynamic=fp16 and fp16_cfg.dynamic_loss_scale,
                loss_scale_window=fp16_cfg.loss_scale_window,
                min_loss_scale=fp16_cfg.min_loss_scale, hysteresis=fp16_cfg.hysteresis)
            zero_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_state = TrainState(
                params=new_params, opt_state=new_opt, grad_acc=zero_acc,
                global_steps=state.global_steps + (1 - overflow.astype(jnp.int32)),
                scaler=new_scaler)
            return new_state, gnorm, overflow

        def evaluate(params, batch, rng):
            return loss_fn(cast_params(params), batch, rng)

        def apply1(state: TrainState):
            # anomaly off: the bound arg is never read, so this compiles
            # to exactly the historical one-arg program
            return apply(state, None)

        def offload_prep(state: TrainState):
            """Device half of the offload step: unscale + clip; grads leave
            the device once, already final — in bf16 when the engine computes
            in bf16 (halves D2H traffic and feeds the csrc bf16g fast path)."""
            scale = state.scaler.scale if fp16 else jnp.float32(1.0)
            overflow = has_overflow(state.grad_acc) if fp16 else jnp.zeros((), bool)
            grads = (jax.tree.map(lambda g: g / scale, state.grad_acc)
                     if fp16 else state.grad_acc)
            if clip > 0:
                grads, gnorm = clip_grad_norm(grads, clip)
            else:
                gnorm = global_norm(grads)
            if compute_dtype == jnp.bfloat16:
                grads = jax.tree.map(
                    lambda g: g.astype(jnp.bfloat16)
                    if jnp.issubdtype(g.dtype, jnp.floating) else g, grads)
            return grads, gnorm, overflow

        def offload_commit(state: TrainState, overflow):
            new_scaler = scaler_lib.update(
                state.scaler, overflow, dynamic=fp16 and fp16_cfg.dynamic_loss_scale,
                loss_scale_window=fp16_cfg.loss_scale_window,
                min_loss_scale=fp16_cfg.min_loss_scale, hysteresis=fp16_cfg.hysteresis)
            return (jax.tree.map(jnp.zeros_like, state.grad_acc),
                    state.global_steps + (1 - overflow.astype(jnp.int32)),
                    new_scaler)

        def fused(state: TrainState, batches, rng, anomaly_bound):
            """Full optimizer step in ONE XLA program: scan the gas
            micro-batches (grad accumulation), then apply the update.  One
            host dispatch instead of gas+1 — the dispatch latency matters on
            remote-device transports, and a single program lets XLA overlap
            the update's collectives with the last microbatch's compute."""
            rngs = jax.random.split(rng, gas)

            def micro(st, xs):
                b, r = xs
                st, loss = accum(st, b, r)
                return st, loss

            state, losses = jax.lax.scan(micro, state, (batches, rngs))
            state, gnorm, overflow = apply(state, anomaly_bound)
            return state, losses.mean(), gnorm, overflow

        def fused1(state: TrainState, batches, rng):
            return fused(state, batches, rng, None)

        if self._zeropp:
            self._compile_zeropp_steps(loss_fn, gas)
            return
        sh = self._state_shardings
        bs = batch_sharding(self.mesh)
        scalar = NamedSharding(self.mesh, P())
        self._fused_fn = None
        if self._param_offload:
            # Params in pinned host memory; grads land host-resident with the
            # same layout (no device [model]-sized buffers).  Accumulation
            # happens in numpy; the host optimizer consumes it directly.
            def fwdbwd(params, batch, rng):
                def f(p):
                    return loss_fn(cast_params(p), batch, rng).astype(jnp.float32) / gas

                loss, grads = jax.value_and_grad(f)(params)
                return loss * gas, grads

            # No explicit in/out shardings: params arrive committed to pinned
            # host; grads/loss default to device.  Forcing placements here
            # makes jax emit sharding-less annotate_device_placement custom
            # calls that the SPMD partitioner rejects on multi-device meshes.
            self._pofwdbwd_fn = jax.jit(fwdbwd)
            self._accum_fn = None
            self._apply_fn = None
            self._eval_fn = jax.jit(evaluate)
            self._build_streamed_fwdbwd(gas)
            return
        if self._onebit:
            self._compile_onebit_steps(loss_fn, cast_params, gas)
            if not self._onebit_stacked:  # stacked eval is set under shard_map
                self._eval_fn = jax.jit(
                    evaluate, in_shardings=(self._param_shardings, None, None),
                    out_shardings=scalar)
            return
        if self._overlap:
            self._compile_overlap_steps(apply if anomaly_on else apply1,
                                        evaluate, gas, anomaly_on)
            return
        if self._qcomm_grads:
            self._compile_qcomm_steps(loss_fn, cast_params, evaluate, gas,
                                      anomaly_on)
            return
        self._accum_fn = jax.jit(accum, donate_argnums=(0,), in_shardings=(sh, None, None),
                                 out_shardings=(sh, NamedSharding(self.mesh, P())))
        self._anomaly_select = anomaly_on and not self._offload
        if not self._offload:
            if anomaly_on:
                self._fused_fn = jax.jit(
                    fused, donate_argnums=(0,),
                    in_shardings=(sh, None, None, None),
                    out_shardings=(sh, scalar, scalar, scalar))
            else:
                self._fused_fn = jax.jit(
                    fused1, donate_argnums=(0,), in_shardings=(sh, None, None),
                    out_shardings=(sh, scalar, scalar, scalar))
        if self._offload:
            self._offload_prep_fn = jax.jit(offload_prep, in_shardings=(sh,))
            self._offload_commit_fn = jax.jit(
                offload_commit, in_shardings=(sh, None),
                out_shardings=(sh.grad_acc, NamedSharding(self.mesh, P()), sh.scaler))
            self._apply_fn = None
        else:
            if anomaly_on:
                self._apply_fn = jax.jit(
                    apply, donate_argnums=(0,), in_shardings=(sh, None),
                    out_shardings=(sh, NamedSharding(self.mesh, P()),
                                   NamedSharding(self.mesh, P())))
            else:
                self._apply_fn = jax.jit(
                    apply1, donate_argnums=(0,), in_shardings=(sh,),
                    out_shardings=(sh, NamedSharding(self.mesh, P()),
                                   NamedSharding(self.mesh, P())))
        self._eval_fn = jax.jit(evaluate, in_shardings=(self._param_shardings, None, None),
                                out_shardings=NamedSharding(self.mesh, P()))

    def _compile_overlap_steps(self, apply, evaluate, gas,
                               anomaly_on: bool = False) -> None:
        """Accum (and the fused step's micro scan) under full-manual
        ``shard_map`` with the layer-bucketed explicit collective schedule
        (runtime/zero/overlap.py).  The boundary ``apply`` and ``evaluate``
        stay on the GSPMD path — the overlap tentpole targets the per-micro
        collectives; state layout differs from the GSPMD path only in the
        stacked-layer dim-0 constraint, so checkpointing/eval reshard
        transparently."""
        import functools

        from deepspeed_tpu.runtime.zero.overlap import (OverlapSchedule,
                                                        QCommOpts)

        mesh = self.mesh
        mcfg = getattr(self.module, "config", None)
        cq = self.config.comm_quantization
        qcomm = QCommOpts(all_gather=cq.q_all_gather and self.zero_stage == 3,
                          reduce_scatter=cq.q_reduce_scatter
                          and self.zero_stage >= 2,
                          block=cq.block)
        if qcomm.all_gather or qcomm.reduce_scatter:
            log_dist(
                f"comm_quantization on the overlap schedule: "
                f"gathers={'int8' if qcomm.all_gather else 'dense'}, "
                f"reduce-scatters="
                f"{'int8' if qcomm.reduce_scatter else 'dense'} "
                f"(block {qcomm.block})", ranks=[0])
        self._overlap_sched = OverlapSchedule(
            segments=self._overlap_segments,
            params=self._state.params,
            param_specs=self._param_specs,
            acc_specs=self._acc_specs,
            mesh=mesh,
            zero_stage=self.zero_stage,
            compute_dtype=self.compute_dtype,
            bucket_layers=self.config.zero_config.overlap_bucket_layers,
            use_dropout=True,
            # stage 3 ALWAYS remats the layer buckets (the backward must
            # re-gather instead of holding gathered params as residuals —
            # the ZeRO-3 memory contract); stages 1/2 follow the model's
            # activation-checkpointing choice
            remat=(self.zero_stage == 3 or bool(getattr(mcfg, "remat",
                                                        False))),
            qcomm=qcomm)
        state_specs = TrainState(
            params=self._param_specs, opt_state=self._opt_specs,
            grad_acc=self._acc_specs, global_steps=P(),
            scaler=scaler_lib.LossScaleState(P(), P(), P(), P()))
        bspec = P(("dp", "fsdp", "ep"))
        accum_local = self._overlap_sched.make_accum(gas, self.fp16_enabled)
        sm = functools.partial(jax.shard_map, mesh=mesh, check_vma=False)
        sm_accum = sm(accum_local, in_specs=(state_specs, bspec, P()),
                      out_specs=(state_specs, P()))
        self._accum_fn = jax.jit(sm_accum, donate_argnums=(0,))
        sh = self._state_shardings
        scalar = NamedSharding(mesh, P())

        self._anomaly_select = anomaly_on

        def fused(state: TrainState, batches, rng, *anomaly_bound):
            # *anomaly_bound: one traced scalar when the anomaly select is
            # compiled in, empty otherwise — `apply` arrives 2-arg or
            # 1-arg to match (see _compile_steps)
            rngs = jax.random.split(rng, gas)

            def micro(st, xs):
                b, r = xs
                st, loss = sm_accum(st, b, r)
                return st, loss

            state, losses = jax.lax.scan(micro, state, (batches, rngs))
            state, gnorm, overflow = apply(state, *anomaly_bound)
            return state, losses.mean(), gnorm, overflow

        extra = (None,) if anomaly_on else ()
        self._fused_fn = jax.jit(
            fused, donate_argnums=(0,),
            in_shardings=(sh, None, None) + extra,
            out_shardings=(sh, scalar, scalar, scalar))
        self._apply_fn = jax.jit(apply, donate_argnums=(0,),
                                 in_shardings=(sh,) + extra,
                                 out_shardings=(sh, scalar, scalar))
        self._eval_fn = jax.jit(
            evaluate, in_shardings=(self._param_shardings, None, None),
            out_shardings=scalar)

    def _compile_zeropp_steps(self, loss_fn, gas) -> None:
        """Accum/apply/fused under full-manual shard_map over the data axes
        with ZeRO++ collectives: params gathered per micro-batch (int8 when
        ``zero_quantized_weights``; subgroup-only under hpZ), grads
        reduce-scattered (int8 qgZ when ``zero_quantized_gradients``), and
        the hpZ secondary refreshed once per boundary."""
        import functools

        from deepspeed_tpu.runtime.zero import zeropp as zpp
        from deepspeed_tpu.runtime.zero.zeropp import ZeroPPParams

        mesh = self.mesh
        cfg = self._zpp_cfg
        shapes = self._zpp_shapes
        lens = self._zpp_lens
        clip = self.config.gradient_clipping
        waxes = ("dp", "fsdp", "ep")
        optimizer = self.optimizer
        new_params_opt = getattr(optimizer, "updates_are_new_params", False)
        prim_spec = jax.tree.map(lambda _: P("fsdp"), lens)
        opt_specs = self._zpp_opt_specs
        state_specs = TrainState(
            params=self._zpp_state_param_specs, opt_state=opt_specs,
            grad_acc=prim_spec, global_steps=P(),
            scaler=scaler_lib.LossScaleState(P(), P(), P(), P()))
        bspec = P(waxes)

        def accum_local(state: TrainState, batch, rng):
            full = zpp.gather_param_tree(state.params, cfg, shapes)

            def f(pt):
                return loss_fn(pt, batch, rng).astype(jnp.float32) / gas

            loss, g_full = jax.value_and_grad(f)(full)
            gflat = zpp.flat_grads(g_full, lens)

            def rs(gl):
                # reduce_scatter SUMS over fsdp; the engine contract is the
                # GLOBAL-batch mean gradient (each worker's loss is a mean
                # over its local shard), so divide by the fsdp extent and
                # pmean the remaining data axes.
                shard = zpp.reduce_scatter_flat(gl, cfg.axis, cfg.q_grads,
                                                cfg.block)
                return jax.lax.pmean(shard / cfg.world, ("dp", "ep"))

            gshard = jax.tree.map(rs, gflat)
            new_acc = jax.tree.map(lambda a, g: a + g, state.grad_acc, gshard)
            return (state._replace(grad_acc=new_acc),
                    jax.lax.pmean(loss * gas, waxes))

        def apply_local(state: TrainState):
            grads = state.grad_acc
            sumsq = sum(jnp.sum(jnp.square(g))
                        for g in jax.tree.leaves(grads))
            gnorm = jnp.sqrt(jax.lax.psum(sumsq, cfg.axis))
            if clip > 0:
                scale = jnp.minimum(1.0, clip / (gnorm + 1e-6))
                grads = jax.tree.map(lambda g: g * scale, grads)
            prim = state.params.primary
            updates, new_opt = optimizer.update(grads, state.opt_state, prim)
            if new_params_opt:
                new_prim = updates
            else:
                import optax

                new_prim = optax.apply_updates(prim, updates)
            if cfg.hpz > 1:
                sec_q, sec_s = zpp.refresh_secondary(new_prim, cfg)
            else:
                sec_q, sec_s = (), ()
            zero_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_state = state._replace(
                params=ZeroPPParams(new_prim, sec_q, sec_s),
                opt_state=new_opt, grad_acc=zero_acc,
                global_steps=state.global_steps + 1)
            return new_state, gnorm, jnp.zeros((), bool)

        def fused_local(state: TrainState, batches, rng):
            rngs = jax.random.split(rng, gas)

            def micro(st, xs):
                b, r = xs
                st, loss = accum_local(st, b, r)
                return st, loss

            state, losses = jax.lax.scan(micro, state, (batches, rngs))
            state, gnorm, overflow = apply_local(state)
            return state, losses.mean(), gnorm, overflow

        def eval_local(zp_params, batch, rng):
            full = zpp.gather_param_tree(zp_params, cfg, shapes)
            return jax.lax.pmean(loss_fn(full, batch, rng), waxes)

        sm = functools.partial(jax.shard_map, mesh=mesh, check_vma=False)
        self._accum_fn = jax.jit(
            sm(accum_local, in_specs=(state_specs, bspec, P()),
               out_specs=(state_specs, P())), donate_argnums=(0,))
        self._apply_fn = jax.jit(
            sm(apply_local, in_specs=(state_specs,),
               out_specs=(state_specs, P(), P())), donate_argnums=(0,))
        self._fused_fn = jax.jit(
            sm(fused_local, in_specs=(state_specs, P(None, waxes), P()),
               out_specs=(state_specs, P(), P(), P())), donate_argnums=(0,))
        self._eval_fn = jax.jit(
            sm(eval_local, in_specs=(self._zpp_state_param_specs, bspec, P()),
               out_specs=P()))

    def _compile_qcomm_steps(self, loss_fn, cast_params, evaluate, gas,
                             anomaly_on: bool) -> None:
        """ZeRO stage 0/1/2 with the comm-layer quantized gradient sync
        (``comm_quantization.grad_all_reduce``; comm/collectives_q.py).

        Accum runs under full-manual ``shard_map`` over the data axes with
        LOCAL gradients (the 1-bit skeleton: every worker keeps its own
        running sum, stacked on the [W] axis) — no implicit GSPMD psum
        ever moves dense grad bytes.  The boundary apply reduces the
        accumulated tree ONCE through :func:`collectives_q.q_all_reduce`
        (int8 codes + fp32 block scales, fp32 reduce after dequant) and
        then runs the standard update under GSPMD.  Quantizing once per
        boundary (not per micro) is both cheaper and kinder to the
        error-feedback residual, which is carried as ENGINE state
        (``self._qcomm_residual``) — donated into and returned from every
        boundary program, reset to zero on (re)compile and on checkpoint
        load (it is transient sync state, not part of the model; a resume
        restarts it at zero, documented in docs/OBSERVABILITY.md).

        The anomaly-detection in-program skip select composes here
        exactly as on the standard path (the ZeRO++/1-bit refuse-to-arm
        list is unchanged — this path is neither)."""
        import functools

        from deepspeed_tpu.comm import collectives_q as cqt

        mesh = self.mesh
        waxes = ("dp", "fsdp", "ep")
        active_axes = tuple(a for a in waxes
                            if mesh.shape.get(a, 1) > 1)
        cq = self.config.comm_quantization
        block = int(cq.block)
        ef = bool(cq.error_feedback)
        clip = self.config.gradient_clipping
        optimizer = self.optimizer
        new_params_opt = getattr(optimizer, "updates_are_new_params", False)
        fp16_cfg = self.config.fp16

        state_specs = TrainState(
            params=jax.tree.map(lambda s: s.spec, self._param_shardings),
            opt_state=self._opt_specs,
            grad_acc=self._acc_specs,
            global_steps=P(),
            scaler=scaler_lib.LossScaleState(P(), P(), P(), P()))
        bspec = P(waxes)

        def accum_local(state: TrainState, batch, rng):
            # twin of _compile_onebit_steps.accum_local (minus the [W]
            # replica stacking): a fix to the local-grad skeleton here
            # almost certainly applies there too
            def f(p):
                return loss_fn(cast_params(p), batch,
                               rng).astype(jnp.float32) / gas

            loss, grads = jax.value_and_grad(f)(state.params)
            new_acc = jax.tree.map(lambda a, g: a + g[None].astype(a.dtype),
                                   state.grad_acc, grads)
            return (state._replace(grad_acc=new_acc),
                    jax.lax.pmean(loss * gas, waxes))

        def qsync_local(acc, res=None):
            """[W]-stacked local sums -> globally-reduced MEAN grads
            (replicated) (+ the new residual when error feedback is on),
            via int8 q_all_reduce."""
            leaves, treedef = jax.tree_util.tree_flatten(acc)
            res_leaves = (jax.tree_util.tree_leaves(res) if ef
                          else [None] * len(leaves))
            outs, new_res = [], []
            for a, r in zip(leaves, res_leaves):
                o, nr = cqt.q_all_reduce(
                    a[0], active_axes, block=block,
                    residual=(r[0] if ef else None), mean=True)
                outs.append(o)
                new_res.append(nr[None] if nr is not None else None)
            reduced = jax.tree_util.tree_unflatten(treedef, outs)
            if not ef:
                return reduced
            return reduced, jax.tree_util.tree_unflatten(treedef, new_res)

        sm = functools.partial(jax.shard_map, mesh=mesh, check_vma=False)
        acc_specs = self._acc_specs
        reduced_specs = jax.tree.map(lambda _: P(), acc_specs)
        if ef:
            qsync = sm(qsync_local, in_specs=(acc_specs, acc_specs),
                       out_specs=(reduced_specs, acc_specs))
        else:
            # no residual program state at all with error feedback off:
            # a full-model fp32 tree donated through every boundary for
            # nothing would be pure wasted HBM + dispatch traffic
            qsync = sm(qsync_local, in_specs=(acc_specs,),
                       out_specs=reduced_specs)

        @jax.named_scope("ds_optimizer_step")
        def apply_q(state: TrainState, residual, *anomaly_bound):
            if ef:
                grads, new_res = qsync(state.grad_acc, residual)
            else:
                grads = qsync(state.grad_acc)
                new_res = None
            if clip > 0:
                grads, gnorm = clip_grad_norm(grads, clip)
            else:
                gnorm = global_norm(grads)
            overflow = jnp.zeros((), bool)
            if anomaly_on:
                overflow = (overflow | ~jnp.isfinite(gnorm)
                            | (gnorm > anomaly_bound[0]))
            updates, new_opt = optimizer.update(grads, state.opt_state,
                                                state.params)
            if new_params_opt:
                new_params = updates
            else:
                import optax

                new_params = optax.apply_updates(state.params, updates)
            if anomaly_on:
                sel = lambda new, old: jax.tree.map(
                    lambda a, b: jnp.where(overflow, b, a), new, old)
                new_params = sel(new_params, state.params)
                new_opt = sel(new_opt, state.opt_state)
                if ef:
                    # the residual must roll back WITH the step: it was
                    # computed from the rejected gradients, so carrying
                    # it would leak ~1/254 of them into the next boundary
                    # — and a non-finite gradient would poison the carry
                    # FOREVER (every later comp = grads + NaN),
                    # defeating the skip
                    new_res = sel(new_res, residual)
            new_scaler = scaler_lib.update(
                state.scaler, overflow, dynamic=False,
                loss_scale_window=fp16_cfg.loss_scale_window,
                min_loss_scale=fp16_cfg.min_loss_scale,
                hysteresis=fp16_cfg.hysteresis)
            zero_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_state = TrainState(
                params=new_params, opt_state=new_opt, grad_acc=zero_acc,
                global_steps=state.global_steps
                + (1 - overflow.astype(jnp.int32)),
                scaler=new_scaler)
            out = (new_state, gnorm, overflow)
            return out + ((new_res,) if ef else ())

        def fused(state: TrainState, residual, batches, rng,
                  *anomaly_bound):
            rngs = jax.random.split(rng, gas)

            def micro(st, xs):
                b, r = xs
                st, loss = sm_accum(st, b, r)
                return st, loss

            state, losses = jax.lax.scan(micro, state, (batches, rngs))
            out = apply_q(state, residual, *anomaly_bound)
            return (out[0], losses.mean()) + out[1:]

        sm_accum = sm(accum_local, in_specs=(state_specs, bspec, P()),
                      out_specs=(state_specs, P()))
        self._accum_fn = jax.jit(sm_accum, donate_argnums=(0,))
        sh = self._state_shardings
        scalar = NamedSharding(mesh, P())
        res_sh = sh.grad_acc
        extra = (None,) if anomaly_on else ()
        res_tail = (res_sh,) if ef else ()
        if ef:
            apply_jit = jax.jit(
                apply_q, donate_argnums=(0, 1),
                in_shardings=(sh, res_sh) + extra,
                out_shardings=(sh, scalar, scalar) + res_tail)
            fused_jit = jax.jit(
                fused, donate_argnums=(0, 1),
                in_shardings=(sh, res_sh, None, None) + extra,
                out_shardings=(sh, scalar, scalar, scalar) + res_tail)
            acc_shapes = jax.tree.map(lambda a: tuple(a.shape),
                                      self.state.grad_acc)
            res_zeros = jax.jit(
                lambda: jax.tree.map(
                    lambda shp: jnp.zeros(shp, jnp.float32), acc_shapes,
                    is_leaf=lambda x: isinstance(x, tuple)),
                out_shardings=res_sh)
        else:
            # ef off: no residual program state at all — the jits take
            # and return only the TrainState tuple
            apply_jit = jax.jit(
                lambda state, *b: apply_q(state, None, *b),
                donate_argnums=(0,), in_shardings=(sh,) + extra,
                out_shardings=(sh, scalar, scalar))
            fused_jit = jax.jit(
                lambda state, batches, rng, *b: fused(state, None,
                                                      batches, rng, *b),
                donate_argnums=(0,), in_shardings=(sh, None, None) + extra,
                out_shardings=(sh, scalar, scalar, scalar))
            res_zeros = None
        self._qcomm_residual = None
        self._qcomm_apply_jit = apply_jit

        def _residual():
            if self._qcomm_residual is None:
                self._qcomm_residual = res_zeros()
            return self._qcomm_residual

        def _apply(state, *bound):
            if ef:
                st, gnorm, overflow, res = apply_jit(state, _residual(),
                                                     *bound)
                self._qcomm_residual = res
            else:
                st, gnorm, overflow = apply_jit(state, *bound)
            return st, gnorm, overflow

        def _fused(state, batches, rng, *bound):
            if ef:
                st, loss, gnorm, overflow, res = fused_jit(
                    state, _residual(), batches, rng, *bound)
                self._qcomm_residual = res
            else:
                st, loss, gnorm, overflow = fused_jit(state, batches,
                                                      rng, *bound)
            return st, loss, gnorm, overflow

        self._apply_fn = _apply
        self._fused_fn = _fused
        self._anomaly_select = anomaly_on
        self._eval_fn = jax.jit(
            evaluate, in_shardings=(self._param_shardings, None, None),
            out_shardings=scalar)

    def _compile_onebit_steps(self, loss_fn, cast_params, gas) -> None:
        """Accum/apply under full-manual shard_map over the data axes: each
        worker keeps LOCAL gradients (no implicit psum), which is what the
        1-bit compression algorithm is defined over (reference:
        fp16/onebit/adam.py + runtime/comm/nccl.py)."""
        import functools

        mesh = self.mesh
        waxes = ("dp", "fsdp", "ep")
        onebit = self.optimizer
        lr_schedule = self._lr_schedule
        base_lr = (self.config.optimizer.params.get("lr", 1e-3)
                   if self.config.optimizer else 1e-3)
        state_specs = TrainState(
            params=jax.tree.map(lambda s: s.spec, self._param_shardings),
            opt_state=self._opt_specs,
            grad_acc=self._acc_specs,
            global_steps=P(),
            scaler=scaler_lib.LossScaleState(P(), P(), P(), P()))
        bspec = P(waxes)
        stacked = self._onebit_stacked

        def local_view(params):
            """This worker's replica (0/1 Adam stacks replicas on [W])."""
            return (jax.tree.map(lambda p: p[0], params) if stacked
                    else params)

        def accum_local(state: TrainState, batch, rng):
            def f(p):
                return loss_fn(cast_params(local_view(p)), batch,
                               rng).astype(jnp.float32) / gas

            loss, grads = jax.value_and_grad(f)(state.params)
            if stacked:  # grads arrive [1, ...]: already the worker slice
                grads = jax.tree.map(lambda g: g[0], grads)
            new_acc = jax.tree.map(lambda a, g: a + g[None].astype(a.dtype),
                                   state.grad_acc, grads)
            return (state._replace(grad_acc=new_acc),
                    jax.lax.pmean(loss * gas, waxes))

        def apply_local(state: TrainState):
            g_local = jax.tree.map(lambda a: a[0], state.grad_acc)
            lr = lr_schedule(state.opt_state.count) if lr_schedule else base_lr
            new_params, new_opt = onebit.update_local(
                g_local, state.opt_state, state.params, lr=lr)
            zero_acc = jax.tree.map(jnp.zeros_like, state.grad_acc)
            new_state = state._replace(params=new_params, opt_state=new_opt,
                                       grad_acc=zero_acc,
                                       global_steps=state.global_steps + 1)
            # grad-norm reporting: norm of the averaged local grads
            gnorm = global_norm(jax.tree.map(
                lambda g: jax.lax.pmean(g.astype(jnp.float32), waxes), g_local))
            return new_state, gnorm, jnp.zeros((), bool)

        sm = functools.partial(jax.shard_map, mesh=mesh, check_vma=False)
        self._accum_fn = jax.jit(
            sm(accum_local, in_specs=(state_specs, bspec, P()),
               out_specs=(state_specs, P())),
            donate_argnums=(0,))
        self._apply_fn = jax.jit(
            sm(apply_local, in_specs=(state_specs,),
               out_specs=(state_specs, P(), P())),
            donate_argnums=(0,))
        self._fused_fn = None
        if stacked:
            # eval must also slice each worker's replica; between syncs the
            # replicas differ, so the per-worker losses are averaged
            def eval_local(params, batch, rng):
                return jax.lax.pmean(
                    loss_fn(cast_params(local_view(params)), batch, rng)
                    .astype(jnp.float32), waxes)

            self._eval_fn = jax.jit(
                sm(eval_local, in_specs=(state_specs.params, bspec, P()),
                   out_specs=P()))

    # ------------------------------------------------------------------
    # training-side telemetry (docs/OBSERVABILITY.md)
    # ------------------------------------------------------------------
    def _setup_state_telemetry(self, n_params: int) -> None:
        """Once per state init: the static FLOPs estimator (model config),
        the analytic GSPMD comm plan, and the measured ZeRO shard-group
        memory breakdown.  Failures here must never break training."""
        mcfg = getattr(self.module, "config", None)
        L = getattr(mcfg, "num_layers", 0) or 0
        D = getattr(mcfg, "hidden_size", 0) or 0
        if L and D and n_params:
            self._flops_per_step_fn = (
                lambda tokens, seq, n=n_params, L=L, D=D:
                tokens * lm_flops_per_token(n, L, D, seq))
        # the qcomm grad path's explicit manual collectives record
        # themselves (trace-time q/dense twins) — an analytic GSPMD plan
        # on top would double-count the sync it replaced
        if not (self._zeropp or self._onebit or self._param_offload
                or self._qcomm_grads):
            try:
                plan = _build_comm_plan(
                    self.state.params, self._param_specs, self._acc_specs,
                    self.mesh, self.zero_stage, self.compute_dtype,
                    self._acc_dtype(jnp.float32),
                    overlap_sched=self._overlap_sched)
                if self._offload:
                    # the host optimizer step replaces the boundary
                    # gather with per-leaf device_puts — not a collective
                    plan["boundary"] = []
                self._comm_plan = plan if (plan["micro"] or plan["boundary"]) \
                    else None
            except Exception as exc:
                logger.warning("telemetry: comm plan unavailable (%s)", exc)
        # overlap-schedule gauges (docs/OBSERVABILITY.md "Overlap"):
        # bucket count is static truth; the hidden-comm estimate starts at
        # zero and is backfilled with the measured comm∩compute time by
        # every device-trace capture (profiling/device_trace.py)
        try:
            from deepspeed_tpu.profiling.device_trace import OVERLAP_GAUGES

            reg = get_registry()
            n_buckets = (len(self._overlap_sched.bucket_infos())
                         if self._overlap_sched is not None else 0)
            for name, help_ in OVERLAP_GAUGES.items():
                reg.gauge(name, help_)
            reg.gauge("ds_overlap_buckets").set(n_buckets)
            reg.gauge("ds_overlap_hidden_comm_seconds_est").set(0.0)
            if self._overlap_sched is not None and get_registry().enabled:
                log_dist(
                    f"overlap_comm: {n_buckets} buckets, analytic hideable "
                    f"comm fraction "
                    f"{self._overlap_sched.hideable_comm_fraction():.2f}",
                    ranks=[0])
        except Exception as exc:
            logger.warning("telemetry: overlap gauges unavailable (%s)", exc)
        if get_registry().enabled:
            try:
                st = self.state
                pb = device_resident_bytes(st.params)
                gb = device_resident_bytes(st.grad_acc)
                ob = device_resident_bytes(st.opt_state)
                self._mem_telemetry.set_state_bytes(pb, gb, ob)
                log_dist(
                    f"ZeRO stage {self.zero_stage} per-device state bytes: "
                    f"params={pb/1e6:.2f}MB grads={gb/1e6:.2f}MB "
                    f"optimizer={ob/1e6:.2f}MB "
                    f"(mesh {dict(self.mesh.shape)})", ranks=[0])
                self._mem_telemetry.sample()
            except Exception as exc:
                logger.warning("telemetry: state-bytes breakdown "
                               "unavailable (%s)", exc)

    def _micro_telemetry(self, batch) -> None:
        """Per-micro-batch accounting: FLOPs accrual for the MFU gauge and
        a flight-recorder breadcrumb.  One branch each while disabled."""
        if self._timeline.enabled:
            self._timeline.micro(self._host_steps + 1,
                                 self._micro_count + 1,
                                 time.perf_counter())
        if self._flight.enabled:
            self._flight.record("micro_end", step=self._host_steps + 1,
                                micro=self._micro_count + 1)
        if self._flops_per_step_fn is not None and get_registry().enabled:
            for leaf in jax.tree_util.tree_leaves(batch):
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 2:
                    self._flops_since_boundary += self._flops_per_step_fn(
                        int(shape[0]) * int(shape[1]), int(shape[1]))
                    break
        if self._goodput.enabled:
            for leaf in jax.tree_util.tree_leaves(batch):
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 2:    # [micro, seq, ...] -> tokens
                    self._goodput.add_tokens(int(shape[0]) * int(shape[1]))
                    break

    def _boundary_telemetry(self) -> None:
        """Optimizer-boundary accounting: MFU/TFLOPS gauges off the
        boundary-to-boundary wall clock (anchored on the step's loss
        output — dispatch is async, so the meter blocks on it before
        reading the clock; telemetry users pay that boundary bubble, the
        ``wall_clock_breakdown`` trade), and an HBM sample."""
        flops = self._flops_since_boundary
        self._flops_since_boundary = 0.0
        if self._timeline.enabled:
            # close the step span BEFORE the registry gate: the timeline
            # has its own switch (enable() keys off the same config, but
            # a bench-hygiene registry.reset() must not truncate it)
            self._timeline.boundary(self._host_steps, time.perf_counter(),
                                    comm_plan=self._comm_plan,
                                    bubble_share=self._pp_bubble_share())
        if self._goodput.enabled:
            # goodput ledger boundary tick (own switch, before the
            # registry gate): price the step's analytic comm plan into
            # `exposed_comm` (ZeRO-Infinity bandwidth-model style — the
            # honest CPU-host estimate; device captures refine the bench
            # series, not this attribution), roll the per-step compute
            # window for the lag-1 anomaly reattribution, and persist.
            step_compute = self._gp_compute_since_boundary
            self._gp_compute_since_boundary = 0.0
            exposed = self._gp_analytic_exposed_comm_s()
            if exposed > 0.0:
                exposed = self._goodput.shift(
                    "compute", "exposed_comm", min(exposed, step_compute))
                step_compute -= exposed
            self._gp_step_compute = [self._gp_step_compute[1], step_compute]
            self._goodput.set_steps(self._host_steps)
            self._goodput.tick()
        if not get_registry().enabled:
            return
        self._flops_meter.observe_boundary(flops or None,
                                           anchor=self._last_loss)
        self._mem_telemetry.sample()
        # training-numerics blind spot: loss + grad norm as gauges, every
        # boundary.  Gated on the registry so the disabled path never pays
        # the float() device sync; enabled, LM-shaped configs already
        # blocked on the loss for the FLOPs clock above (same boundary
        # bubble), while non-LM configs opt into one boundary sync — the
        # price of reading the numbers out.
        reg = get_registry()
        if reg.enabled:
            if self._last_loss is not None:
                reg.gauge("ds_train_loss",
                          TRAIN_STEP_GAUGES["ds_train_loss"]).set(
                    float(self._last_loss))
            if self._last_grad_norm is not None:
                reg.gauge("ds_train_grad_norm",
                          TRAIN_STEP_GAUGES["ds_train_grad_norm"]).set(
                    float(self._last_grad_norm))
        if self._overlap_sched is not None:
            # static truth, republished so a bench-hygiene registry.reset()
            # between passes cannot make a live scrape read "overlap: off"
            get_registry().gauge("ds_overlap_buckets").set(
                len(self._overlap_sched.bucket_infos()))

    def _gp_analytic_exposed_comm_s(self) -> float:
        """Analytic EXPOSED comm seconds for one optimizer boundary: the
        step's comm-plan bytes (gas micro executions + the boundary
        entries) priced at ``goodput.assumed_comm_gbps``, scaled by the
        overlap schedule's non-hideable fraction when bucketed overlap is
        active (T3-style exposed-time accounting; arXiv:2401.16677).
        Zero when no plan exists — nothing is invented."""
        if self._comm_plan is None:
            return 0.0
        gas = self.config.gradient_accumulation_steps
        total = (analytic_comm_seconds(self._comm_plan["micro"],
                                       self._gp_comm_gbps) * gas
                 + analytic_comm_seconds(self._comm_plan["boundary"],
                                         self._gp_comm_gbps))
        if self._overlap_sched is not None:
            total *= max(0.0, 1.0
                         - self._overlap_sched.hideable_comm_fraction())
        return total

    def _pp_bubble_share(self) -> Optional[float]:
        """Analytic pipeline bubble fraction of the step's schedule (the
        bench.py pp-rung formula): ``(pp-1)/(M+2(pp-1))`` under 1F1B,
        ``(pp-1)/(M+pp-1)`` under GPipe; ``None`` when the mesh has no
        pp extent (no bubble to attribute)."""
        pp = self.mesh.shape.get("pp", 1)
        if pp <= 1:
            return None
        mcfg = getattr(self.module, "config", None)
        M = int(getattr(mcfg, "pp_microbatches", 0) or pp)
        if getattr(mcfg, "pp_schedule", "gpipe") == "1f1b":
            return (pp - 1) / (M + 2 * (pp - 1))
        return (pp - 1) / (M + pp - 1)

    # ------------------------------------------------------------------
    # device-true profiling: /profilez capture + step-time watchdog
    # (docs/OBSERVABILITY.md "Device truth")
    # ------------------------------------------------------------------
    def _maybe_start_aux_trace(self) -> None:
        """Open a pending one-shot capture window before this step's first
        dispatch (the analog of ``self._trace.maybe_start``).  A failed
        start (jax has ONE global profiler session — another holder may
        have it) fails the request / logs instead of crashing training."""
        if self._aux_trace is None:
            return
        cap, trigger, payload = self._aux_trace
        try:
            cap.maybe_start(self._host_steps + 1)
        except Exception as exc:
            self._aux_trace = None
            if trigger == "profilez":
                self._pz_broker.resolve(
                    payload, error=f"trace start failed: {exc}")
            else:
                logger.warning("watchdog: trace start failed: %s", exc)

    def _merge_pp_comm_plan(self, batch) -> None:
        """Analytic pipeline boundary entries, merged into the comm plan's
        MICRO list lazily at the first batch (the boundary tensor shape
        needs the batch's sequence length).  One pipelined execution moves
        ``2*T`` ring hops of one microbatch boundary [mb, S, D] in the
        compute dtype — T forward-ring activation hops plus T reverse-ring
        cotangent hops, with T the schedule length in ticks (``M + pp - 1``
        GPipe, ``M + 2(pp-1)`` 1F1B).  The model's trace-time ledger is off
        under the engine (``pp_comm_record=False``), so this plan is the
        only feed — the repo-wide double-count rule."""
        self._pp_plan_pending = False
        try:
            mcfg = getattr(self.module, "config", None)
            pp = self.mesh.shape.get("pp", 1)
            if pp <= 1 or mcfg is None \
                    or not hasattr(mcfg, "pp_boundary_q"):
                return
            unpacked = self._unpack_lm_batch(batch)
            if unpacked is None:
                return
            toks = unpacked[0]
            if getattr(toks, "ndim", 0) < 2:
                return
            B, S = int(toks.shape[0]), int(toks.shape[1])
            M = int(getattr(mcfg, "pp_microbatches", 0) or pp)
            mb = -(-B // M)                 # padded-batch microbatch rows
            D = int(getattr(mcfg, "hidden_size", 0) or 0)
            if not D:
                return
            is_1f1b = getattr(mcfg, "pp_schedule", "gpipe") == "1f1b"
            T = M + (2 * (pp - 1) if is_1f1b else pp - 1)
            hops = 2 * T
            numel = mb * S * D
            c_item = jnp.dtype(self.compute_dtype).itemsize
            cname = jnp.dtype(self.compute_dtype).name
            dense = hops * numel * c_item
            if getattr(mcfg, "pp_boundary_q", False):
                blk = int(getattr(mcfg, "comm_quant_block", 256) or 256)
                qbytes = hops * (numel + 4 * (-(-numel // blk)))
                entry = ("q_ppermute", hops, qbytes, "int8", pp,
                         (dense, cname))
            else:
                entry = ("ppermute", hops, dense, cname, pp)
            if self._comm_plan is None:
                self._comm_plan = {"micro": [entry], "boundary": []}
            else:
                self._comm_plan["micro"] = (
                    list(self._comm_plan["micro"]) + [entry])
        except Exception as exc:
            logger.warning("telemetry: pipeline comm plan unavailable (%s)",
                           exc)

    def _profile_bytes_per_op(self, steps: int):
        """Payload bytes the analytic comm plan says a ``steps``-step
        window moved, per op slug — feeds the recomputed device busbw."""
        if self._comm_plan is None:
            return None
        gas = self.config.gradient_accumulation_steps
        out = {}
        for mult, entries in ((gas, self._comm_plan["micro"]),
                              (1, self._comm_plan["boundary"])):
            for entry in entries:
                # quantized overlap entries carry a 6th (dense-twin) field
                op, _calls, nbytes, _dtype, world = entry[:5]
                b, w = out.get(op, (0, world))
                out[op] = (b + nbytes * mult * steps, max(w, world))
        return out or None

    def _aux_trace_tick(self) -> None:
        """Per-boundary bookkeeping for the one-shot capture slot: close a
        finished window (post-process + deliver), else claim a pending
        ``/profilez`` request.  One attribute load per step when idle."""
        if self._aux_trace is not None:
            cap, trigger, payload = self._aux_trace
            done = cap.after_step(self._host_steps)
            if done is not None:
                self._aux_trace = None
                self._finish_aux_trace(done, cap, trigger, payload)
            return
        if self._pz_broker.pending is None:
            return
        req = self._pz_broker.claim()
        if req is None:      # another engine grabbed it first
            return
        if self._trace is not None and not self._trace.done:
            # pending counts too: an aux window overlapping the configured
            # profile_trace start would collide in jax's single global
            # profiler session
            self._pz_broker.resolve(
                req, error="the configured profile_trace window is "
                           "capturing (or still ahead); retry after it "
                           "closes")
            return
        if self._cprof is not None and self._cprof.active:
            # the operator wins the single global profiler session: the
            # abandoned continuous window simply reschedules at its next
            # cadence tick
            self._cprof.close()
        import tempfile

        trace_dir = req.trace_dir or tempfile.mkdtemp(prefix="ds_profilez_")
        from deepspeed_tpu.profiling.trace import TraceCapture

        cap = TraceCapture(trace_dir, start_step=self._host_steps + 1,
                           num_steps=req.steps, perfetto=True)
        self._aux_trace = (cap, "profilez", req)

    def _finish_aux_trace(self, trace_dir, cap, trigger, payload) -> None:
        """Post-process a closed capture window and deliver the summary:
        registry backfill always; the HTTP waiter (profilez) or a JSON
        file next to the trace (watchdog).  Failures never break the
        training loop — they fail the request / log instead."""
        from deepspeed_tpu.profiling import device_trace as dtr

        try:
            try:
                summary = dtr.analyze_capture(
                    trace_dir, cap.num_steps,
                    bytes_per_op=self._profile_bytes_per_op(cap.num_steps),
                    clock=cap.clock, trigger=trigger)
            except Exception as exc:
                if trigger == "profilez":
                    self._pz_broker.resolve(
                        payload, error=f"trace post-processing failed: {exc}")
                else:
                    logger.warning(
                        "watchdog: trace post-processing failed: %s", exc)
                return
            if trigger == "profilez":
                self._pz_broker.resolve(payload, summary=summary)
                return
            out = os.path.join(trace_dir, "ds_watchdog_summary.json")
            try:
                with open(out, "w") as fh:
                    json.dump(summary, fh, indent=1, default=str)
            except Exception as exc:
                logger.warning("watchdog: summary write failed: %s", exc)
            logger.warning("watchdog: post-anomaly capture analyzed -> %s "
                           "(per-step gap %.4fs)", out,
                           summary.get("per_step", summary["phases"])["gap_s"])
            if self.config.watchdog.rearm and self._watchdog is not None:
                self._watchdog.reset()
        finally:
            if self._watchdog is not None:
                # the gz+JSON parse above ran inside this boundary interval;
                # exclude it from the next step-time sample or a /profilez
                # capture could spuriously trip the watchdog
                self._wd_last_t = time.perf_counter()

    def _cprof_tick(self) -> None:
        """Boundary hook of the continuous profiler: close a finished
        window (the decompose + history commit run inline here, between
        steps), else open the next one when due — never while another
        holder (profile_trace, a pending/claimed /profilez request, a
        watchdog capture) owns or is about to claim jax's single global
        profiler session.  One attribute load + one branch when off."""
        cp = self._cprof
        if cp is None:
            return
        if cp.active:
            if cp.after_step(self._host_steps) is not None \
                    and self._watchdog is not None:
                # the decompose ran inside this boundary interval; exclude
                # it from the next step-time sample (the
                # _finish_aux_trace idiom)
                self._wd_last_t = time.perf_counter()
            return
        if (self._aux_trace is not None
                or self._pz_broker.pending is not None
                or (self._trace is not None and not self._trace.done)):
            return
        cp.maybe_begin(self._host_steps + 1)

    def _watchdog_tick(self) -> None:
        """Feed the boundary-to-boundary wall time to the watchdog; on a
        trip, dump the flight recorder and arm the one-shot capture.  The
        steady-state cost is the watchdog's contract: one deque append +
        one comparison (plus this clock read)."""
        wd = self._watchdog
        if wd is None:
            return
        now = time.perf_counter()
        last, self._wd_last_t = self._wd_last_t, now
        if last is None or not wd.observe(now - last):
            return
        trip = dict(wd.last_trip)
        trip["step"] = self._host_steps
        self._flight.record("watchdog", **trip)
        reason = (f"watchdog: step {self._host_steps} took "
                  f"{trip['seconds']:.3f}s > {wd.factor:g}x median "
                  f"{trip['median']:.3f}s")
        logger.warning("%s", reason)
        try:
            self._flight.dump(reason=reason)
        except Exception as exc:   # a broken disk must not kill the run
            logger.error("watchdog: flight dump failed: %s", exc)
        wdc = self.config.watchdog
        if (wdc.trace and perfetto_supported() and self._aux_trace is None
                and (self._trace is None or self._trace.done)):
            if self._cprof is not None and self._cprof.active:
                # a trip capture diagnoses an anomaly NOW; the abandoned
                # continuous window reschedules at its next cadence tick
                self._cprof.close()
            import tempfile

            trace_dir = (wdc.output_path
                         or tempfile.mkdtemp(prefix="ds_watchdog_"))
            from deepspeed_tpu.profiling.trace import TraceCapture

            cap = TraceCapture(trace_dir, start_step=self._host_steps + 1,
                               num_steps=wdc.capture_steps, perfetto=True)
            self._aux_trace = (cap, "watchdog", None)

    # ------------------------------------------------------------------
    # anomaly containment: skip -> rollback ladder for bf16/fp32 runs
    # (docs/RESILIENCE.md "Elastic training"; the boundary-hook slot the
    # watchdog and preemption ticks share)
    # ------------------------------------------------------------------
    def _anomaly_tick(self) -> None:
        """Classify the PREVIOUS boundary's realized grad norm (lag-1
        deferred fetch — the serving ``_fetch_block`` idiom: the value has
        long materialized, so this never blocks the step just dispatched)
        and escalate: count the skip, and after ``patience`` consecutive
        trips roll back to the last-good checkpoint."""
        a = self._anomaly
        if a is None:
            return
        pending, self._anomaly_pending = (self._anomaly_pending,
                                          (self._last_grad_norm,
                                           self._last_overflow))
        if pending is None:
            return
        gnorm = float(np.asarray(pending[0]))
        # the device's own select decision for that step: for non-fp16
        # engines the overflow output IS the anomaly trip, which keeps
        # the host ledger truthful even when the cached bound drifted
        # from the live median between dispatch and classification (a
        # dropped step must never go uncounted); fp16 conflates it with
        # loss-scale overflow, so fall back to the host rule there
        skipped = (None if self.fp16_enabled or pending[1] is None
                   else bool(np.asarray(pending[1])))
        if not a.observe(gnorm, skipped=skipped):
            return
        get_registry().counter(
            "ds_train_anomaly_skipped_total",
            "training steps skipped by the grad-norm anomaly select "
            "(non-finite or above factor x rolling median)").inc()
        trip = dict(a.last_trip)
        trip["step"] = self._host_steps
        # the recorder's first positional is the EVENT kind; the
        # detector's trip kind rides as "anomaly"
        trip["anomaly"] = trip.pop("kind")
        self._flight.record("anomaly_skip", **trip)
        # ledger: the skipped step's compute produced nothing — move the
        # classified (lag-1) boundary's compute window to `anomaly_skip`
        self._goodput.shift("compute", "anomaly_skip",
                            self._gp_step_compute[0])
        self._gp_step_compute[0] = 0.0
        if self._timeline.enabled:
            self._timeline.event("anomaly_skip", time.perf_counter(),
                                 **trip)
        logger.warning(
            "anomaly: grad norm %.3e flagged %s (median %.3e, consecutive "
            "%d/%d) — step skipped", gnorm, trip["anomaly"], trip["median"],
            a.consecutive, a.patience)
        if a.should_rollback and self.config.anomaly_detection.rollback:
            self._anomaly_rollback()

    def _anomaly_rollback(self) -> None:
        """``patience`` consecutive anomalous steps: the skip select alone
        is not containing the failure (a poisoned accumulator, or params
        already damaged before the detector armed) — dump the flight
        recorder and restore the newest valid checkpoint."""
        a = self._anomaly
        anc = self.config.anomaly_detection
        if a.rollback_streak >= anc.max_rollbacks:
            raise RuntimeError(
                f"anomaly: {a.rollback_streak} rollbacks without a single "
                f"accepted step in between (max_rollbacks="
                f"{anc.max_rollbacks}) — the anomaly persists across "
                "restores; refusing to loop")
        save_dir = (anc.save_dir or self.config.checkpoint_config.save_dir
                    or (self._preempt_cfg[0] if self._preempt_cfg else None))
        reason = (f"anomaly rollback: {a.consecutive} consecutive anomalous "
                  f"steps at step {self._host_steps}")
        self._flight.record("anomaly_rollback", step=self._host_steps,
                            consecutive=a.consecutive,
                            trip=dict(a.last_trip or {}))
        try:
            self._flight.dump(reason=reason)
        except Exception as exc:     # a broken disk must not kill the run
            logger.error("anomaly: flight dump failed: %s", exc)
        if save_dir is None:
            logger.error("anomaly: rollback requested but no save dir is "
                         "configured (anomaly_detection.save_dir / "
                         "checkpoint.save_dir); continuing with per-step "
                         "skips only")
            a.consecutive = 0        # re-arm the ladder, don't re-enter per step
            return
        # ledger: the rollback window (flight dump + restore) is its own
        # category; the nested load_checkpoint region attributes its own
        # time to checkpoint_load, the remainder stays `rollback`
        self._goodput.push("rollback")
        try:
            ckpt_dir, _ = self.load_checkpoint(save_dir)
        finally:
            self._goodput.pop()
        if ckpt_dir is None:
            logger.error("anomaly: nothing loadable in %s; continuing with "
                         "per-step skips only", save_dir)
            a.consecutive = 0
            return
        get_registry().counter(
            "ds_train_anomaly_rollback_total",
            "anomaly-ladder rollbacks to the last-good checkpoint").inc()
        a.note_rollback()
        self._anomaly_pending = None   # the pending norm belongs to the dead timeline
        logger.warning("%s — restored %s (rollback #%d)", reason, ckpt_dir,
                       a.rollbacks)

    # ------------------------------------------------------------------
    # preemption: SIGTERM -> emergency save at the next optimizer boundary
    # (docs/RESILIENCE.md; same boundary-hook slot as the watchdog)
    # ------------------------------------------------------------------
    def enable_preemption_save(self, save_dir: str, *,
                               client_state_fn: Optional[Callable[[], dict]] = None,
                               exit_after: bool = True,
                               exit_code: Optional[int] = None,
                               signum: Optional[int] = None):
        """Arm the TPU grace-window idiom: SIGTERM latches a flag (a
        handler cannot checkpoint — saves run collectives mid-dispatch);
        the next optimizer boundary performs ONE emergency
        ``save_checkpoint(save_dir)`` carrying ``client_state_fn()`` (the
        dataloader position, so resume is step-accurate) and, when
        ``exit_after``, raises ``SystemExit`` with
        :data:`~deepspeed_tpu.runtime.preemption.PREEMPTED_EXIT_CODE` so a
        supervisor (``tools/train_supervisor.py``, elastic agent)
        restarts-and-resumes instead of treating it as a crash."""
        import signal as _signal

        from deepspeed_tpu.runtime.preemption import (PREEMPTED_EXIT_CODE,
                                                      PreemptionHandler)

        if self._preempt is None:
            self._preempt = PreemptionHandler()
        self._preempt.install(signum if signum is not None
                              else _signal.SIGTERM)
        self._preempt_cfg = (save_dir, bool(exit_after),
                             PREEMPTED_EXIT_CODE if exit_code is None
                             else int(exit_code))
        if client_state_fn is not None:
            self._preempt_client_state_fn = client_state_fn
        log_dist(f"preemption handler armed: SIGTERM -> emergency save to "
                 f"{save_dir} at the next optimizer boundary", ranks=[0])
        return self._preempt

    def set_preemption_client_state(self, fn: Callable[[], dict]) -> None:
        """Register the callable whose dict rides the emergency save's
        ``client_state`` (dataloader position etc.)."""
        self._preempt_client_state_fn = fn

    def _preemption_tick(self) -> None:
        """Boundary poll of the SIGTERM latch: emergency-save once, then
        exit (when configured) with the preempted code.  One attribute
        load + branch while nothing is pending (single-process)."""
        if self._preempt is None:
            return
        requested = self._preempt.requested
        if jax.process_count() > 1:
            # Collective agreement: the signal can land while ranks sit on
            # opposite sides of a boundary, and a rank-local decision
            # would have them enter the save's collectives at DIFFERENT
            # boundaries — a mismatch that hangs out the grace window.
            # Any rank's latch preempts everyone, at the same boundary.
            # Cost: one small host allgather per boundary, only while the
            # handler is armed on a multi-process run.
            from jax.experimental import multihost_utils

            flags = multihost_utils.process_allgather(
                np.asarray(requested, np.int32))
            requested = bool(np.asarray(flags).max())
        if not requested:
            return
        save_dir, exit_after, exit_code = self._preempt_cfg
        tag = f"global_step{self.global_steps}"
        client_state = {}
        if self._preempt_client_state_fn is not None:
            try:
                client_state = dict(self._preempt_client_state_fn() or {})
            except Exception as exc:
                logger.error("preemption: client_state_fn failed: %s", exc)
        self._flight.record("ckpt_emergency", tag=tag, step=self._host_steps,
                            signal_time=self._preempt.signal_time)
        get_registry().counter(
            "ds_ckpt_emergency_saves_total",
            "SIGTERM-triggered boundary emergency saves").inc()
        path = self.save_checkpoint(save_dir, tag=tag,
                                    client_state=client_state)
        # cleared only AFTER the save succeeded: a transient save failure
        # (exception propagates to the caller) leaves the latch set, so
        # the next boundary retries instead of dropping the request
        self._preempt.clear()
        log_dist("preemption: emergency checkpoint %s saved; %s"
                 % (path, "exiting for supervisor restart" if exit_after
                    else "continuing (exit_after=False)"), ranks=[0])
        if exit_after:
            raise SystemExit(exit_code)

    def _flight_crash(self, exc: Exception) -> None:
        """Dump the event ring once, before the exception propagates."""
        if not self._flight.enabled or self._flight_dumped:
            return
        self._flight_dumped = True
        self._flight.record("exception", type=type(exc).__name__,
                            message=str(exc)[:300],
                            step=self._host_steps + 1)
        try:
            self._flight.dump(
                reason=f"unhandled {type(exc).__name__} in engine")
        except Exception as dump_exc:
            logger.error("flight recorder: crash dump failed: %s", dump_exc)

    # ------------------------------------------------------------------
    # reference-parity imperative API (SURVEY.md §3.3)
    # ------------------------------------------------------------------
    def train(self, mode: bool = True):
        self._training = mode
        return self

    def eval(self):
        return self.train(False)

    def __call__(self, batch):
        return self.forward(batch)

    def curriculum_difficulty(self) -> Optional[int]:
        if self.curriculum_scheduler is None:
            return None
        return self.curriculum_scheduler.update_difficulty(self._host_steps)

    @_flight_guard
    def forward(self, batch):
        """One micro-batch forward (+backward: gradients are produced in the
        same XLA program and accumulated — see module docstring)."""
        if self.curriculum_scheduler is not None and self._training:
            # curriculum applies to TRAINING data only (reference semantics);
            # eval always sees full sequences
            from deepspeed_tpu.runtime.data_pipeline import truncate_batch

            batch = truncate_batch(batch, self.curriculum_difficulty())
        batch = shard_batch(batch, self.mesh)
        if self._state is None:
            self.lazy_init_from_batch(batch)
        if not self._training:
            self._rng, rng = jax.random.split(self._rng)
            return self._eval_fn(self.state.params, batch, rng)
        if self._pp_plan_pending:
            self._merge_pp_comm_plan(batch)
        if self._trace is not None and self._micro_count == 0:
            self._trace.maybe_start(self._host_steps + 1)
        if self._micro_count == 0:
            self._maybe_start_aux_trace()
        self.timers(SynchronizedWallClockTimer.FORWARD).start()
        self._rng, rng = jax.random.split(self._rng)
        self._goodput.push("compute")
        try:
            if self._param_offload:
                unpacked = (self._unpack_lm_batch(batch)
                            if self._streamed is not None else None)
                if unpacked is not None:
                    toks, labels, mask = unpacked
                    if self._host_grad_acc is None:
                        self._host_grad_acc = jax.tree.map(
                            lambda a: np.zeros(a.shape, np.float32),
                            self._np_params)
                    loss = self._streamed.run(self._np_params, toks, labels,
                                              mask, rng, self._host_grad_acc)
                else:
                    loss, grads = self._pofwdbwd_fn(self.state.params, batch, rng)
                    self._accum_host_grads(grads)
                    if self.flops_profiler is not None:
                        self._profile_probes["fwdbwd"] = (
                            self._pofwdbwd_fn, (self.state.params, batch, rng))
            else:
                self._check_overlap_batch(batch)
                if self.flops_profiler is not None:
                    self._profile_probes["accum"] = (self._accum_fn,
                                                     (self.state, batch, rng))
                t0 = (time.perf_counter()
                      if self._comm_plan is not None and comm_metrics.active
                      else 0.0)
                # host-timeline twin of the in-jit ds_fwd_bwd named scope: on
                # backends whose trace export drops compiled-op scope names
                # (CPU), the post-processor's degraded mode reads this range
                with annotate("ds_fwd_bwd"):
                    self.state, loss = self._accum_fn(self.state, batch, rng)
                if t0:
                    comm_metrics.commit(self._comm_plan["micro"],
                                        time.perf_counter() - t0)
        finally:
            self._gp_compute_since_boundary += self._goodput.pop()
        self.timers(SynchronizedWallClockTimer.FORWARD).stop()
        self._micro_telemetry(batch)
        self._micro_count += 1
        self._last_loss = loss
        return loss

    def _accum_host_grads(self, grads) -> None:
        """Accumulate host-resident micro-batch grads into fp32 numpy buffers
        (ZeRO-Offload semantics: the accumulator never touches the device)."""
        if self._host_grad_acc is None:
            self._host_grad_acc = jax.tree.map(
                lambda g: np.zeros(g.shape, np.float32), grads)
        jax.tree.map(lambda buf, g: buf.__iadd__(np.asarray(g, np.float32)),
                     self._host_grad_acc, grads)

    def _build_streamed_fwdbwd(self, gas: int) -> None:
        """Construct the per-layer streamed fwd/bwd driver when the model
        supports segmenting (ZeRO-Infinity grad streaming; VERDICT r3 item 2).
        Falls back to the whole-program path (``_pofwdbwd_fn``) otherwise."""
        self._streamed = None
        p_off = self.config.zero_config.offload_param
        if p_off is None or not getattr(p_off, "stream_grads", True):
            return
        if self._client_loss_fn:
            # a custom objective can't route through the model's built-in
            # head segment; the whole-program path honors it
            logger.warning("offload_param.stream_grads: client loss_fn "
                           "supplied — falling back to the whole-program "
                           "fwd/bwd (device grad tree is O(model))")
            return
        if not hasattr(self.module, "stream_segments"):
            logger.warning(
                "offload_param.stream_grads: model %s exposes no "
                "stream_segments; falling back to the whole-program fwd/bwd "
                "(device grad tree is O(model))", type(self.module).__name__)
            return
        seg = self.module.stream_segments()
        if seg is None:
            logger.warning(
                "offload_param.stream_grads: model declined segmenting "
                "(e.g. pipeline parallelism owns the layer loop); falling "
                "back to the whole-program fwd/bwd")
            return
        from deepspeed_tpu.runtime.zero.stream_grad import StreamedFwdBwd

        off_opt = self.config.zero_config.offload_optimizer
        self._streamed = StreamedFwdBwd.from_param_specs(
            seg, self._param_specs, self.mesh, gas=gas, use_dropout=True,
            prefetch=bool(getattr(p_off, "prefetch", True)),
            int8=bool(getattr(p_off, "int8_stream", False)),
            staging_slots=int(getattr(p_off, "staging_slots", 2)),
            quant_block=int(getattr(off_opt, "quant_block", 256)
                            if off_opt is not None else 256))
        # numpy compute-dtype copy for the per-layer H2D slices — built only
        # now that streaming is actually active (a second host-resident model
        # copy is wasted memory on the whole-program fallback)
        self._np_params = jax.device_get(self.state.params)
        log_dist("offload_param: streamed per-layer fwd/bwd active "
                 "(device grads bounded to one layer"
                 + (", int8 relay" if self._streamed.streamer.int8 else "")
                 + (", prefetch off" if not
                    self._streamed.streamer.prefetch_enabled else "")
                 + ")", ranks=[0])

    @staticmethod
    def _unpack_lm_batch(batch):
        """(tokens, labels, loss_mask) matching ``model.apply``'s batch
        conventions, or None for forms the whole-program path defines
        differently (the caller falls back so both paths keep one contract).
        A loss mask is only accepted by its explicit dict key — a positional
        third element is ambiguous (position_ids? attention_mask?) and the
        whole-program path rejects it.  Shared with the overlap schedule
        (one contract for every segment-driven path)."""
        from deepspeed_tpu.runtime.zero.overlap import unpack_lm_batch

        return unpack_lm_batch(batch)

    def _check_overlap_batch(self, batch) -> None:
        """The overlap schedule drives the model through its layer segments,
        which need the LM batch forms; unroutable batches fail loudly here
        (before dispatch) instead of deep inside the shard_map trace."""
        if not self._overlap:
            return
        if self._unpack_lm_batch(batch) is None:
            raise ValueError(
                "zero_optimization.overlap_comm requires (tokens, labels) "
                "tuple or {'tokens': ..., 'labels': ...[, 'loss_mask': ...]} "
                f"dict batches (got {type(batch).__name__}); disable "
                "overlap_comm for custom batch forms")

    def backward(self, loss, retain_graph: bool = False):
        """Reference-parity no-op: gradients were already computed and
        accumulated by ``forward`` (fused fwd+bwd in one XLA program)."""
        return loss

    def is_gradient_accumulation_boundary(self) -> bool:
        if self._boundary_override is not None:
            return self._boundary_override
        gas = self.config.gradient_accumulation_steps
        return self._micro_count % gas == 0 and self._micro_count > 0

    def set_gradient_accumulation_boundary(self, is_boundary: bool) -> None:
        """Manual boundary control (reference API, used by HF Accelerate)."""
        self._boundary_override = is_boundary

    @_flight_guard
    def step(self):
        if not self.is_gradient_accumulation_boundary():
            return
        self._flight.record("step_begin", step=self._host_steps + 1)
        self.timers(SynchronizedWallClockTimer.STEP).start()
        t0 = (time.perf_counter()
              if self._comm_plan is not None and comm_metrics.active
              else 0.0)
        self._goodput.push("compute")
        try:
            if self._param_offload:
                gnorm, overflow = self._step_param_offload()
            elif self._offload:
                gnorm, overflow = self._step_offload()
            else:
                with annotate("ds_optimizer_step"):
                    if self._anomaly_select:
                        self.state, gnorm, overflow = self._apply_fn(
                            self.state, self._anomaly.bound)
                    else:
                        self.state, gnorm, overflow = self._apply_fn(self.state)
        except BaseException:
            # leave the timer re-startable: a caller that catches a
            # mid-step failure and resumes from a checkpoint must not hit
            # "timer already started" on the next boundary
            self._goodput.pop()
            self.timers(SynchronizedWallClockTimer.STEP).stop(record=False)
            raise
        self._gp_compute_since_boundary += self._goodput.pop()
        self.timers(SynchronizedWallClockTimer.STEP).stop()
        if t0 and self._comm_plan["boundary"]:
            comm_metrics.commit(self._comm_plan["boundary"],
                                time.perf_counter() - t0)
        self._last_grad_norm = gnorm
        self._last_overflow = overflow
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._micro_count = 0
        # Host-side mirror of state.global_steps: reading the device scalar
        # here would synchronize every step (it ignores fp16 overflow skips,
        # which only matters for print cadence; checkpoint tags still read
        # the authoritative device count).
        self._host_steps += 1
        self._boundary_telemetry()
        self._flight.record("step_end", step=self._host_steps)
        self._maybe_apply_compression()
        if self._host_steps % self.config.steps_per_print == 0:
            self._report(self.global_steps)
        self._maybe_emit_flops_profile()
        if self._trace is not None:
            self._trace.after_step(self._host_steps)
        self._watchdog_tick()
        self._anomaly_tick()
        self._aux_trace_tick()
        self._cprof_tick()
        self._preemption_tick()

    def _maybe_emit_flops_profile(self) -> None:
        if (self.flops_profiler is None
                or self._host_steps != self.config.flops_profiler.profile_step):
            return
        if (self._apply_fn is not None and self._state is not None
                and hasattr(self._apply_fn, "lower")):
            # the qcomm-grad path's apply is a python wrapper carrying the
            # error-feedback residual — no AOT surface to cost-analyze
            self._profile_probes.setdefault("apply", (self._apply_fn, (self._state,)))
        if self._streamed is not None and self._streamed.probes:
            # streamed offload: fwd+bwd is L dispatches of the per-layer
            # programs plus the embed/head segments
            L = self._streamed.L
            parts = [(fn, spec, L if name.startswith("layer") else 1)
                     for name, (fn, spec) in self._streamed.probes.items()]
            self.flops_profiler.collect_scaled("fwdbwd", parts)
        for name, (fn, args) in self._profile_probes.items():
            self.flops_profiler.collect(name, fn, *args)
        fp = self.config.flops_profiler
        self.flops_profiler.print_model_profile(
            profile_step=fp.profile_step, module_depth=fp.module_depth,
            top_modules=fp.top_modules, detailed=fp.detailed)

    def _step_param_offload(self):
        """ZeRO-Infinity step: grads already accumulated on host; clip, step
        the host optimizer, cast masters to compute dtype, and re-place the
        params in pinned host memory for the next streamed forward."""
        import ml_dtypes

        acc = self._host_grad_acc
        if acc is None:
            raise RuntimeError("step() before any forward() in offload_param mode")
        leaves = jax.tree_util.tree_leaves(acc)
        gnorm = float(np.sqrt(sum(float((g.astype(np.float64) ** 2).sum())
                                  for g in leaves)))
        if self._anomaly is not None and (not math.isfinite(gnorm)
                                          or gnorm > self._anomaly.bound):
            # anomaly skip (fp16-overflow semantics for the host-master
            # path): drop the accumulated grads, step nothing
            for g in leaves:
                g[:] = 0.0
            self._last_grad_norm = gnorm
            return gnorm, True
        clip = self.config.gradient_clipping
        if clip and clip > 0 and gnorm > clip:
            scale = clip / (gnorm + 1e-6)
            for g in leaves:
                g *= scale
        lr = self.get_lr()[0]
        masters = self._offload_opt.step([g.reshape(-1) for g in leaves], lr=lr)
        np_dtype = {jnp.bfloat16: ml_dtypes.bfloat16,
                    jnp.float16: np.float16}.get(self.compute_dtype, np.float32)
        master = self._offload_opt.tree_from_masters(masters)
        compute = jax.tree.map(lambda a: a.astype(np_dtype), master)
        if self._streamed is not None:
            # training reads only the numpy masters; the pinned-host
            # state.params refreshes lazily on the next external read
            # (eval/checkpoint) instead of paying a full-model host copy
            # every optimizer step
            self._np_params = compute
            self._state = self._state._replace(
                global_steps=self._state.global_steps + 1)
            self._pinned_stale = True
        else:
            # owned put (dslint DSL001): ``compute`` is host numpy, and on
            # the non-streamed param-offload path these leaves are donated
            # into the accum fn next micro-batch — the exact corruption
            # _step_offload hit in PR 4
            new_params = _owned_device_put_tree(compute,
                                                self._param_shardings)
            self.state = self._state._replace(
                params=new_params, global_steps=self._state.global_steps + 1)
        for g in leaves:
            g[:] = 0.0
        self._last_grad_norm = gnorm
        return gnorm, False

    def _step_offload(self):
        """Optimizer step with host-resident states (ZeRO-Offload path),
        leaf-streamed and overlapped (reference: pipelined_optimizer_swapper):

        - all grad D2H transfers are put in flight up front
          (``copy_to_host_async``), so leaf i+1 streams while leaf i steps;
        - bf16 engines use the csrc ``ds_adam_step_bf16g`` fast path — bf16
          grads in, bf16 params out, no fp32 conversion pass;
        - each leaf's updated params go back with a per-leaf async
          ``device_put``, overlapping H2D with the next leaf's host step.
        """
        import ml_dtypes

        state = self.state
        t_relay = time.perf_counter()
        grads, gnorm, overflow = self._offload_prep_fn(state)
        # The host optimizer step forces a sync anyway; reading the overflow
        # flag here costs nothing extra (reference offload is host-synced too).
        skipped = self.fp16_enabled and bool(overflow)
        if self._anomaly is not None and not skipped:
            # anomaly skip for the host-stepped path: the same sync
            # rationale as the overflow read above (no in-program select
            # exists — the optimizer step is host code)
            g = float(np.asarray(gnorm))
            if not math.isfinite(g) or g > self._anomaly.bound:
                skipped = True
                overflow = np.bool_(True)   # steps/scaler record the skip
        if not skipped:
            # ledger: the host relay (D2H grads -> host optimizer -> H2D
            # params) is `host_stall`, nested inside step()'s compute
            # region — the stack attributes this window out of compute
            self._goodput.push("host_stall")
            flat, treedef = jax.tree_util.tree_flatten(grads)
            for leaf in flat:  # start every D2H now; np.asarray below collects
                try:
                    leaf.copy_to_host_async()
                except Exception:
                    pass
            lr = self.get_lr()[0]
            opt = self._offload_opt
            meter = self._relay_meter
            metered = meter is not None and meter.registry.enabled
            np_dtype = {jnp.bfloat16: ml_dtypes.bfloat16,
                        jnp.float16: np.float16}.get(self.compute_dtype, np.float32)
            use_bf16g = (opt.opt_type == "adam"
                         and self.compute_dtype == jnp.bfloat16
                         and opt.adam is not None
                         and not opt.int8_masters)
            shardings = jax.tree_util.tree_leaves(self._param_shardings)
            opt.begin_step(lr=lr)
            new_leaves = []
            h2d = d2h = 0
            for i, leaf in enumerate(flat):
                g = np.asarray(leaf)
                d2h += g.nbytes
                if use_bf16g and str(g.dtype) == "bfloat16":
                    # fresh buffer per leaf: device_put is async, so a reused
                    # buffer could be overwritten mid-transfer
                    out = opt.step_leaf_bf16(i, g.reshape(-1),
                                             np.empty(opt._sizes[i],
                                                      ml_dtypes.bfloat16))
                elif opt.int8_masters:
                    # int8 relay: the host step requantized the master; only
                    # the blockwise code + scales travel H2D, and a memoized
                    # compiled dequant materializes the compute-dtype param
                    # on device (~2x fewer relay bytes than bf16).  The
                    # dequant OUTPUT is runtime-owned, so donating it into
                    # the accum fn is safe (the _owned_device_put concern).
                    opt.step_leaf(
                        i, np.ascontiguousarray(g, np.float32).reshape(-1),
                        return_master=False)
                    q, s = opt.relay_leaf(i)
                    h2d += q.nbytes + s.nbytes
                    new_leaves.append(_dequant_put(
                        tuple(opt._shapes[i]), np.dtype(np_dtype).name,
                        shardings[i])(q, s))
                    continue
                else:
                    master = opt.step_leaf(
                        i, np.ascontiguousarray(g, np.float32).reshape(-1))
                    out = master.astype(np_dtype)
                h2d += out.nbytes
                # per-leaf async H2D overlaps with the next leaf's host
                # step; the OWNED put matters: these params are donated
                # into the accum fn next micro-batch, and donating a
                # zero-copy numpy-aliased buffer into a cache-deserialized
                # executable corrupts it (see _owned_device_put)
                new_leaves.append(_owned_device_put(
                    out.reshape(opt._shapes[i]), shardings[i]))
            opt.end_step()
            self._goodput.pop()
            if metered:
                meter.h2d_bytes.inc(h2d)
                meter.d2h_bytes.inc(d2h)
                meter.stall.record(time.perf_counter() - t_relay)
            new_params = jax.tree_util.tree_unflatten(treedef, new_leaves)
        else:
            new_params = state.params
        zero_acc, steps, scaler = self._offload_commit_fn(state, overflow)
        self.state = state._replace(params=new_params, grad_acc=zero_acc,
                                    global_steps=steps, scaler=scaler)
        return gnorm, overflow

    @_flight_guard
    def train_step(self, batch):
        """One full optimizer step from a stacked batch in a single dispatch.

        ``batch`` leaves carry a leading ``[gas, micro, ...]`` axis (or
        ``[gas*micro, ...]``, reshaped here).  Falls back to the
        accum-loop + step path when offload is active (the host optimizer
        step cannot live inside the XLA program)."""
        gas = self.config.gradient_accumulation_steps

        tbs = self.config.train_batch_size

        def stack(x):
            if not (isinstance(x, jax.Array) and getattr(x, "ndim", 0)):
                x = np.asarray(x)
            if not x.ndim:
                return x
            # Disambiguate stacked [gas, micro, ...] from flat [batch, ...]
            # even when gas == batch (micro == 1): the stacked form's second
            # dim is the micro size.
            already = (x.shape[0] == gas
                       and (x.shape[0] != tbs
                            or (x.ndim > 1 and x.shape[1] == tbs // gas)))
            if already:
                return x
            if x.shape[0] % gas:
                raise ValueError(f"batch leading dim {x.shape[0]} not "
                                 f"divisible by gradient_accumulation_steps={gas}")
            return x.reshape((gas, x.shape[0] // gas) + x.shape[1:])

        stacked = jax.tree.map(stack, batch)
        if self.curriculum_scheduler is not None:
            from deepspeed_tpu.runtime.data_pipeline import truncate_batch

            # stacked layout is [gas, micro, seq, ...]: seq is axis 2
            stacked = truncate_batch(stacked, self.curriculum_difficulty(),
                                     seq_axis=2)
        if self.state is None:
            first = jax.tree.map(lambda x: x[0], stacked)
            self.lazy_init_from_batch(shard_batch(first, self.mesh))
        if self._fused_fn is None:  # offload path: host step between programs
            losses = [self.forward(jax.tree.map(lambda x: x[i], stacked))
                      for i in range(gas)]
            self.step()
            return jnp.mean(jnp.stack(losses))
        if self._pp_plan_pending:
            # fused path skips forward(): merge off one micro-slice here
            self._merge_pp_comm_plan(jax.tree.map(lambda x: x[0], stacked))
        stacked = shard_batch(stacked, self.mesh, stacked=True)
        self._check_overlap_batch(stacked)
        self._rng, rng = jax.random.split(self._rng)
        if self.flops_profiler is not None:
            self._profile_probes["train_step"] = (self._fused_fn,
                                                  (self.state, stacked, rng))
        if self._trace is not None:
            self._trace.maybe_start(self._host_steps + 1)
        self._maybe_start_aux_trace()
        self._flight.record("step_begin", step=self._host_steps + 1,
                            fused=True)
        self.timers(SynchronizedWallClockTimer.STEP).start()
        t0 = (time.perf_counter()
              if self._comm_plan is not None and comm_metrics.active
              else 0.0)
        # the fused program runs fwd/bwd AND the update in one dispatch:
        # the host range cannot separate them (device scope rows can)
        self._goodput.push("compute")
        try:
            with annotate("ds_fwd_bwd"):
                if self._anomaly_select:
                    self.state, loss, gnorm, overflow = self._fused_fn(
                        self.state, stacked, rng, self._anomaly.bound)
                else:
                    self.state, loss, gnorm, overflow = self._fused_fn(
                        self.state, stacked, rng)
        except BaseException:
            # keep the timer re-startable across a caught mid-step failure
            self._goodput.pop()
            self.timers(SynchronizedWallClockTimer.STEP).stop(record=False)
            raise
        self._gp_compute_since_boundary += self._goodput.pop()
        self.timers(SynchronizedWallClockTimer.STEP).stop()
        if t0:
            # the fused program runs gas micro-batches + the boundary in one
            # dispatch: commit the whole step's plan against its one window
            def scale_entry(e):
                out = e[:1] + (e[1] * gas, e[2] * gas) + e[3:5]
                if len(e) > 5:   # dense twin: bytes or (bytes, dtype)
                    d = e[5]
                    if isinstance(d, (tuple, list)):
                        out += ((d[0] * gas, d[1]),)
                    else:
                        out += (d * gas,)
                return out

            entries = [scale_entry(e) for e in self._comm_plan["micro"]]
            entries += self._comm_plan["boundary"]
            comm_metrics.commit(entries, time.perf_counter() - t0)
        if self._flops_per_step_fn is not None and get_registry().enabled:
            for leaf in jax.tree_util.tree_leaves(stacked):
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 3:
                    self._flops_since_boundary += self._flops_per_step_fn(
                        int(shape[0]) * int(shape[1]) * int(shape[2]),
                        int(shape[2]))
                    break
        if self._goodput.enabled:
            for leaf in jax.tree_util.tree_leaves(stacked):
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 3:    # [gas, micro, seq, ...] -> tokens
                    self._goodput.add_tokens(
                        int(shape[0]) * int(shape[1]) * int(shape[2]))
                    break
        self._last_loss = loss
        self._last_grad_norm = gnorm
        self._last_overflow = overflow
        self._micro_count = 0
        if self.lr_scheduler is not None:
            self.lr_scheduler.step()
        self._host_steps += 1
        self._boundary_telemetry()
        self._flight.record("step_end", step=self._host_steps, fused=True)
        self._maybe_apply_compression()
        if self._host_steps % self.config.steps_per_print == 0:
            self._report(self.global_steps)
        self._maybe_emit_flops_profile()
        if self._trace is not None:
            self._trace.after_step(self._host_steps)
        self._watchdog_tick()
        self._anomaly_tick()
        self._aux_trace_tick()
        self._cprof_tick()
        self._preemption_tick()
        return loss

    def train_batch(self, data_iter=None):
        """Full global-batch step: gas micro-batches + boundary update
        (reference: ``PipelineEngine.train_batch`` shape).  Pulls the gas
        micro-batches eagerly and runs them through the fused single-dispatch
        ``train_step``."""
        if data_iter is None:
            if self.training_dataloader is None:
                raise ValueError("train_batch needs data_iter or training_data")
            data_iter = iter(self.training_dataloader)
        self.tput_timer.start()
        gas = self.config.gradient_accumulation_steps
        # ledger: dataloader wait is `host_stall` — the eager pull below
        # is exactly the window training blocks on host-side input
        self._goodput.push("host_stall")
        try:
            micros = [next(data_iter) for _ in range(gas)]
        finally:
            self._goodput.pop()

        def stack(*xs):
            # keep device-resident batches on device (shard_batch reshards
            # without a host hop); only host data goes through numpy
            if all(isinstance(x, jax.Array) for x in xs):
                return jnp.stack(xs)
            return np.stack([np.asarray(x) for x in xs])

        stacked = jax.tree.map(stack, *micros)
        loss = self.train_step(stacked)
        self.tput_timer.stop()
        return loss

    def eval_batch(self, data_iter):
        was = self._training
        self._training = False
        try:
            return self.forward(next(data_iter))
        finally:
            self._training = was

    # ------------------------------------------------------------------
    # introspection (reference API surface)
    # ------------------------------------------------------------------
    @property
    def global_steps(self) -> int:
        return int(self._state.global_steps) if self._state is not None else 0

    def get_global_grad_norm(self) -> Optional[float]:
        return float(self._last_grad_norm) if self._last_grad_norm is not None else None

    @property
    def loss_scale(self) -> float:
        return float(self._state.scaler.scale) if self._state is not None else 1.0

    @property
    def skipped_steps(self) -> int:
        return int(self._state.scaler.skipped_steps) if self._state is not None else 0

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()
        if self.config.optimizer is not None:
            return [self.config.optimizer.params.get("lr", 0.0)]
        return [0.0]

    def _report(self, steps: int) -> None:
        lr = self.get_lr()[0]
        loss = float(self._last_loss) if self._last_loss is not None else float("nan")  # dslint: disable=DSL002 -- the log line below needs the value; runs once per steps_per_print boundary, not per step
        log_dist(f"step={steps} loss={loss:.4f} lr={lr:.3e} "
                 f"loss_scale={self.loss_scale:.0f} "
                 f"samples/sec={self.tput_timer.avg_samples_per_sec():.2f}", ranks=[0])
        if self.monitor.enabled:
            self.monitor.write_events([("Train/loss", loss, steps),
                                       ("Train/lr", lr, steps),
                                       ("Train/loss_scale", self.loss_scale, steps)])
            # same-schema bridge: the ds_* registry (serving/inference/
            # timer metrics) fans out to the CSV/TensorBoard backends too
            from deepspeed_tpu.monitor.metrics import get_registry

            get_registry().publish(self.monitor, steps)

    def deepspeed_io(self, dataset, batch_size=None, **kwargs):
        gas_batch = batch_size or self.config.train_micro_batch_size_per_gpu * \
            comm.get_data_parallel_world_size(self.mesh)
        return DeepSpeedDataLoader(dataset, batch_size=gas_batch, mesh=self.mesh,
                                   collate_fn=self.collate_fn, **kwargs)

    # ------------------------------------------------------------------
    # checkpointing (reference layout: SURVEY.md §5.4)
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[dict] = None, save_latest: bool = True):
        """Crash-atomic, sharded, multi-host-safe save (docs/RESILIENCE.md).

        Every process writes only its addressable shards (no full gather —
        reference layout role of ``*_zero_pp_rank_*`` files, SURVEY.md
        §5.4), into a ``tmp.<tag>`` staging directory.  Rank 0 then writes
        ``MANIFEST.json`` (per-file size + sha256, world_size, zero_stage,
        format version) with every data file fsynced, the backend
        ``commit`` runs, and ONLY then is the stage atomically renamed
        into place and the ``latest`` pointer updated via tmp +
        ``os.replace`` — a kill at any byte offset during the save leaves
        ``latest`` naming a tag that still loads."""
        if self.state is None:
            raise RuntimeError("nothing to checkpoint: engine state not initialized")
        tag = str(tag or f"global_step{self.global_steps}")
        gp_t0 = time.perf_counter()
        self._goodput.push("checkpoint_save")
        try:
            final_dir = self._save_checkpoint_inner(save_dir, tag,
                                                    client_state, save_latest)
        finally:
            self._goodput.pop()
        # flight `checkpoint` events carry the save wall time + a ledger
        # event id, so the ledger's checkpoint_save seconds and the
        # flight dump reconcile row-by-row (docs/OBSERVABILITY.md)
        dur_s = round(time.perf_counter() - gp_t0, 6)
        event_id = self._goodput.note_event("checkpoint_save", dur_s,
                                            tag=tag)
        self._flight.record("checkpoint", op="save", tag=tag, dir=final_dir,
                            dur_s=dur_s, event_id=event_id)
        log_dist(f"saved checkpoint {final_dir}", ranks=[0])
        return final_dir

    def _save_checkpoint_inner(self, save_dir: str, tag: str,
                               client_state: Optional[dict],
                               save_latest: bool) -> str:
        from deepspeed_tpu.runtime.checkpoint_engine import atomic

        final_dir = os.path.join(save_dir, tag)
        stage_dir = atomic.stage_path(save_dir, tag)
        rank0 = comm.get_rank() == 0
        # deterministic data resume (docs/RESILIENCE.md "Elastic
        # training"): the attached dataloader's stream state (epoch,
        # sample offset, shuffle seed) rides client_state so an elastic
        # restart replays the exact remaining sample stream — an explicit
        # caller-provided "dataloader" key wins
        client_state = dict(client_state or {})
        dl = self.training_dataloader
        if (dl is not None and "dataloader" not in client_state
                and hasattr(dl, "state_dict")):
            try:
                client_state["dataloader"] = dl.state_dict()
            except Exception as exc:
                logger.warning("checkpoint: dataloader state_dict failed: "
                               "%s", exc)
        # every process ensures the dirs exist (a non-shared filesystem
        # would otherwise FileNotFoundError on non-zero ranks); only rank
        # 0 clears crash debris — concurrent rmtrees could delete a
        # freshly-created stage on a shared filesystem
        os.makedirs(save_dir, exist_ok=True)
        if rank0:
            atomic.clear_stage(save_dir, tag)  # debris of a crashed save
        os.makedirs(stage_dir, exist_ok=True)
        comm.barrier()
        self.checkpoint_engine.create(tag)
        self.checkpoint_engine.save(self.state.params,
                                    os.path.join(stage_dir, "model_states"))
        optim_payload = {"opt_state": self.state.opt_state,
                         "grad_acc": self.state.grad_acc,
                         "global_steps": self.state.global_steps,
                         "scaler": tuple(self.state.scaler)}
        self.checkpoint_engine.save(optim_payload,
                                    os.path.join(stage_dir, "optim_states"))
        if self._offload and rank0:
            # host-resident fp32 master + moments, streamed one leaf at a time
            self._offload_opt.write_state(os.path.join(stage_dir, "offload_states"))
        if rank0:
            # the batch triad rides along so a resume at a DIFFERENT
            # device set can rescale grad accumulation to preserve the
            # recorded global batch (_maybe_elastic_rescale)
            meta = {"client_state": client_state,
                    "micro_count": self._micro_count,
                    "lr_scheduler": (self.lr_scheduler.state_dict()
                                     if self.lr_scheduler else None),
                    "zero_stage": self.zero_stage,
                    "world_size": comm.get_world_size(),
                    "data_parallel_size":
                        comm.get_data_parallel_world_size(self.mesh),
                    "gradient_accumulation_steps":
                        self.config.gradient_accumulation_steps,
                    "train_micro_batch_size_per_gpu":
                        self.config.train_micro_batch_size_per_gpu,
                    "train_batch_size": self.config.train_batch_size}
            with open(os.path.join(stage_dir, "client_state.json"), "w") as fh:
                json.dump(meta, fh, default=str)
        comm.barrier()               # every process's shards are on disk
        if rank0:
            atomic.write_manifest(
                stage_dir, tag,
                extra={"world_size": comm.get_world_size(),
                       "zero_stage": self.zero_stage,
                       "global_steps": int(self.global_steps)})
        comm.barrier()
        # The backend commit point.  Publication happens strictly AFTER it
        # (regression-pinned: a crash between the shard writes and here
        # must leave `latest` untouched — the pointer used to be written
        # before commit, a window that published partial checkpoints).
        self.checkpoint_engine.commit(tag)
        if rank0:
            atomic.publish_dir(stage_dir, final_dir)
            if save_latest:
                atomic.write_latest(save_dir, tag)
            self._ckpt_gc(save_dir)
        comm.barrier()
        get_registry().counter("ds_ckpt_saves_total",
                               "committed checkpoint saves").inc()
        return final_dir

    def _ckpt_gc(self, save_dir: str) -> None:
        """Retention GC (``checkpoint.keep_last_n``): after a successful
        commit, delete the oldest VALID tags beyond the budget — never the
        tag ``latest`` points to, and never unverifiable/corrupt dirs
        (kept as post-mortem evidence).  ``ds_ckpt_retained`` publishes
        the surviving tag count either way."""
        from deepspeed_tpu.runtime.checkpoint_engine import atomic

        keep = self.config.checkpoint_config.keep_last_n
        # any .trash.* here is a leak from a publish that crashed between
        # rename-aside and cleanup (checkpoint-sized, invisible to tags)
        for name in atomic.sweep_trash(save_dir):
            log_dist(f"checkpoint GC: removed crashed-publish debris "
                     f"{name}", ranks=[0])
        tags = atomic.list_tags(save_dir)
        if keep and keep > 0:
            import shutil

            latest = atomic.read_latest(save_dir)
            valid = [t for t in tags
                     if atomic.verify_dir(os.path.join(save_dir, t),
                                          level="fast").ok]
            for t in valid[keep:]:
                if t == latest:
                    continue
                shutil.rmtree(os.path.join(save_dir, t), ignore_errors=True)
                self._flight.record("ckpt_gc", tag=t)
                log_dist(f"checkpoint GC: deleted tag {t} "
                         f"(keep_last_n={keep})", ranks=[0])
            tags = atomic.list_tags(save_dir)
        get_registry().gauge(
            "ds_ckpt_retained",
            "checkpoint tags retained in the save dir after GC").set(
            len(tags))

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_module_strict: bool = True, load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True,
                        load_module_only: bool = False):
        """Verified load with walk-back (docs/RESILIENCE.md): the
        requested tag (or the one ``latest`` names) is manifest-verified
        before any bytes are resharded; a corrupt / partial / missing tag
        records ``ds_ckpt_verify_failures_total`` plus a flight-recorder
        event and the loader walks back to the newest valid tag
        (``ds_ckpt_fallbacks_total``) instead of crashing.  Returns
        ``(ckpt_dir, client_state)``, or ``(None, {})`` when nothing
        loadable exists."""
        if self.state is None:
            raise RuntimeError("load_checkpoint requires initialized state "
                               "(pass model_parameters or run one batch first)")
        gp_t0 = time.perf_counter()
        self._goodput.push("checkpoint_load")
        try:
            result = self._load_checkpoint_verified(
                load_dir, tag, load_optimizer_states,
                load_lr_scheduler_states, load_module_only)
        finally:
            self._goodput.pop()
        if result[0] is not None:
            # duration-carrying flight event + ledger event id, the same
            # reconciliation contract as the save path
            dur_s = round(time.perf_counter() - gp_t0, 6)
            event_id = self._goodput.note_event("checkpoint_load", dur_s,
                                                dir=result[0])
            self._flight.record("checkpoint", op="load", dir=result[0],
                                dur_s=dur_s, event_id=event_id)
        return result

    def _load_checkpoint_verified(self, load_dir: str, tag: Optional[str],
                                  load_optimizer_states: bool,
                                  load_lr_scheduler_states: bool,
                                  load_module_only: bool):
        from deepspeed_tpu.runtime.checkpoint_engine import atomic

        requested = (str(tag) if tag is not None
                     else atomic.read_latest(load_dir))
        candidates = [requested] if requested else []
        for t in atomic.list_tags(load_dir):
            if t not in candidates:
                candidates.append(t)
        if not candidates:
            logger.warning("no 'latest' pointer or checkpoint tags in %s; "
                           "cannot load", load_dir)
            return None, {}
        verify = self.config.checkpoint_config.verify_on_load
        deep = self.config.checkpoint_config.deep_verify_on_load
        reg = get_registry()
        for i, t in enumerate(candidates):
            ckpt_dir = os.path.join(load_dir, t)
            if verify:
                st = atomic.verify_dir(ckpt_dir, level="full")
                if st.state == "no_manifest":
                    logger.warning("checkpoint %s has no MANIFEST.json "
                                   "(pre-manifest save): loading "
                                   "unverified", ckpt_dir)
                elif not st.ok:
                    reg.counter(
                        "ds_ckpt_verify_failures_total",
                        "checkpoint tags that failed manifest verification "
                        "at load").inc()
                    self._flight.record("ckpt_verify_fail", tag=t,
                                        state=st.state,
                                        problems=st.problems[:3])
                    logger.warning(
                        "checkpoint %s failed verification (%s): %s — "
                        "walking back", ckpt_dir, st.state,
                        "; ".join(st.problems[:3]) or "?")
                    continue
            if deep:
                # chunk-level pass (checkpoint.deep_verify_on_load),
                # independent of verify_on_load: names the offending
                # shard/leaf and catches index corruption the per-file
                # manifest hashes cannot
                deep_problems = atomic.deep_verify(ckpt_dir)
                if deep_problems:
                    reg.counter(
                        "ds_ckpt_verify_failures_total",
                        "checkpoint tags that failed manifest "
                        "verification at load").inc()
                    self._flight.record("ckpt_verify_fail", tag=t,
                                        state="corrupt_deep",
                                        problems=deep_problems[:3])
                    logger.warning(
                        "checkpoint %s failed DEEP verification: %s — "
                        "walking back", ckpt_dir,
                        "; ".join(deep_problems[:3]))
                    continue
            result = self._load_checkpoint_dir(
                ckpt_dir, load_optimizer_states, load_lr_scheduler_states,
                load_module_only)
            if i > 0:
                reg.counter(
                    "ds_ckpt_fallbacks_total",
                    "loads that fell back to an older valid tag").inc()
                self._flight.record("ckpt_fallback",
                                    requested=candidates[0], loaded=t)
                logger.warning("checkpoint fallback: tag %r was unloadable; "
                               "resumed from %r instead", candidates[0], t)
            reg.counter("ds_resume_total",
                        "successful checkpoint loads (resumes)").inc()
            return result
        logger.warning("no valid checkpoint in %s (tried %s)", load_dir,
                       candidates)
        return None, {}

    def _load_checkpoint_dir(self, ckpt_dir: str, load_optimizer_states: bool,
                             load_lr_scheduler_states: bool,
                             load_module_only: bool):
        from deepspeed_tpu.runtime.checkpoint_engine import is_sharded_checkpoint

        if not is_sharded_checkpoint(os.path.join(ckpt_dir, "model_states")):
            return self._load_legacy_checkpoint(ckpt_dir, load_optimizer_states,
                                                load_lr_scheduler_states,
                                                load_module_only)
        # Resharding load: each device reads only the byte ranges backing its
        # slice of the target sharding — a checkpoint saved at any ZeRO
        # stage/mesh loads at any other without a host-side full gather.
        params = self.checkpoint_engine.load(
            os.path.join(ckpt_dir, "model_states"),
            shardings=self._param_shardings)
        params = self._cast_like(params, self.state.params)
        new_state = self.state._replace(params=params)
        meta = {}
        meta_path = os.path.join(ckpt_dir, "client_state.json")
        if os.path.exists(meta_path):
            with open(meta_path) as fh:
                meta = json.load(fh)
        if not load_module_only and load_optimizer_states:
            scalar_sh = NamedSharding(self.mesh, P())
            opt_shardings = {"opt_state": self._opt_shardings,
                             "grad_acc": self._acc_shardings,
                             "global_steps": scalar_sh,
                             "scaler": tuple([scalar_sh] * len(self.state.scaler))}
            opt = self.checkpoint_engine.load(
                os.path.join(ckpt_dir, "optim_states"), shardings=opt_shardings)
            offload_dir = os.path.join(ckpt_dir, "offload_states")
            if self._offload and os.path.isdir(offload_dir):
                self._offload_opt.read_state(offload_dir)
            new_state = new_state._replace(
                opt_state=self._cast_like(opt["opt_state"], self.state.opt_state),
                grad_acc=self._cast_like(opt["grad_acc"], self.state.grad_acc),
                global_steps=jnp.asarray(opt["global_steps"], jnp.int32),
                scaler=scaler_lib.LossScaleState(*[jnp.asarray(x) for x in opt["scaler"]]))
            self._host_steps = int(jax.device_get(opt["global_steps"]))
            self._micro_count = int(meta.get("micro_count", 0) or 0)
        if load_lr_scheduler_states and self.lr_scheduler is not None and meta.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        self.state = new_state
        # the error-feedback residual is transient sync state, not part of
        # the checkpoint: a resume restarts it at zero (documented)
        self._qcomm_residual = None
        if self._param_offload and getattr(self, "_streamed", None) is not None:
            self._np_params = jax.device_get(self.state.params)
        self._restore_client_runtime(meta)
        log_dist(f"loaded checkpoint {ckpt_dir}", ranks=[0])
        return ckpt_dir, meta.get("client_state", {})

    def _load_legacy_checkpoint(self, ckpt_dir: str, load_optimizer_states: bool,
                                load_lr_scheduler_states: bool,
                                load_module_only: bool):
        """Read the pre-sharded single-file msgpack layout (checkpoints saved
        by earlier releases remain resumable)."""
        legacy = MsgpackCheckpointEngine()
        params_host = legacy.load(
            os.path.join(ckpt_dir, "model_states.msgpack"),
            target=jax.device_get(self.state.params))
        # owned puts (dslint DSL001): msgpack-loaded host arrays become
        # state leaves that the donated accum/apply fns consume on the
        # first post-resume step — an aliased leaf meeting a
        # cache-DESERIALIZED executable is the PR 2/4 corruption
        new_state = self.state._replace(
            params=_owned_device_put_tree(params_host,
                                          self._param_shardings))
        meta = {}
        meta_path = os.path.join(ckpt_dir, "client_state.json")
        if os.path.exists(meta_path):
            with open(meta_path) as fh:
                meta = json.load(fh)
        if not load_module_only and load_optimizer_states:
            target = {"opt_state": jax.device_get(self.state.opt_state),
                      "grad_acc": jax.device_get(self.state.grad_acc),
                      "global_steps": np.zeros((), np.int32),
                      "scaler": tuple(np.asarray(x) for x in self.state.scaler)}
            if self._offload:
                target["offload"] = self._offload_opt.state_dict()
            opt_host = legacy.load(
                os.path.join(ckpt_dir, "optim_states.msgpack"), target=target)
            if self._offload and "offload" in opt_host:
                self._offload_opt.load_state_dict(opt_host["offload"])
            new_state = new_state._replace(
                opt_state=_owned_device_put_tree(opt_host["opt_state"],
                                                 self._opt_shardings),
                grad_acc=_owned_device_put_tree(opt_host["grad_acc"],
                                                self._acc_shardings),
                global_steps=jnp.asarray(opt_host["global_steps"], jnp.int32),
                scaler=scaler_lib.LossScaleState(
                    *[jnp.asarray(x) for x in opt_host["scaler"]]))
            self._host_steps = int(opt_host["global_steps"])
        if load_lr_scheduler_states and self.lr_scheduler is not None and meta.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(meta["lr_scheduler"])
        self.state = new_state
        self._qcomm_residual = None   # transient sync state, never loaded
        if self._param_offload and getattr(self, "_streamed", None) is not None:
            self._np_params = jax.device_get(self.state.params)
        self._restore_client_runtime(meta)
        log_dist(f"loaded legacy checkpoint {ckpt_dir}", ranks=[0])
        return ckpt_dir, meta.get("client_state", {})

    def _restore_client_runtime(self, meta: dict) -> None:
        """Elastic-resume hooks shared by both load paths: rescale grad
        accumulation against the recorded batch triad when the device set
        changed, then restore the attached dataloader's stream state."""
        self._maybe_elastic_rescale(meta)
        dl_state = (meta.get("client_state") or {}).get("dataloader")
        dl = self.training_dataloader
        if dl_state and dl is not None and hasattr(dl, "load_state_dict"):
            try:
                dl.load_state_dict(dl_state)
            except Exception as exc:
                logger.warning("checkpoint: dataloader state restore "
                               "failed: %s", exc)

    def _maybe_elastic_rescale(self, meta: dict) -> None:
        """World-size-change resume (docs/RESILIENCE.md "Elastic
        training"): the checkpoint records the batch triad it was trained
        with; when the data-parallel extent changed across the restart,
        rescale ``gradient_accumulation_steps`` (keeping the per-device
        micro batch) so the GLOBAL batch — and therefore the loss
        trajectory — is preserved, and recompile the step programs with
        the new accumulation count.  The divisibility rule: the recorded
        global batch must be an exact multiple of ``micro x new_dp``;
        anything else raises instead of silently training at a different
        batch size."""
        saved_dp = int(meta.get("data_parallel_size") or 0)
        saved_gas = int(meta.get("gradient_accumulation_steps") or 0)
        saved_micro = int(meta.get("train_micro_batch_size_per_gpu") or 0)
        if not (saved_dp and saved_gas and saved_micro):
            return          # pre-elastic checkpoint: no triad recorded
        cfg = self.config
        cur_dp = comm.get_data_parallel_world_size(self.mesh)
        saved_tbs = int(meta.get("train_batch_size")
                        or saved_micro * saved_gas * saved_dp)
        cur_tbs = (cfg.train_micro_batch_size_per_gpu
                   * cfg.gradient_accumulation_steps * cur_dp)
        if cur_tbs == saved_tbs:
            return          # triad already consistent (same world, or the
                            # config pre-resolved gas for the new world)
        if not cfg.checkpoint_config.elastic_resume:
            logger.warning(
                "checkpoint was trained at global batch %d (dp=%d, gas=%d) "
                "but this run computes %d (dp=%d): checkpoint."
                "elastic_resume is OFF — keeping the current triad; the "
                "loss trajectory will NOT match the original run",
                saved_tbs, saved_dp, saved_gas, cur_tbs, cur_dp)
            return
        den = cfg.train_micro_batch_size_per_gpu * cur_dp
        if saved_tbs % den:
            from deepspeed_tpu.elasticity import \
                ElasticityIncompatibleWorldSize

            raise ElasticityIncompatibleWorldSize(
                f"cannot resume the recorded global batch {saved_tbs} at "
                f"data-parallel world {cur_dp} with micro batch "
                f"{cfg.train_micro_batch_size_per_gpu}: {saved_tbs} is not "
                f"a multiple of micro x dp = {den} — resume at a world "
                f"size dividing global_batch/micro "
                f"(docs/RESILIENCE.md 'Elastic training')")
        new_gas = saved_tbs // den
        old_gas = cfg.gradient_accumulation_steps
        cfg.gradient_accumulation_steps = new_gas
        cfg.train_batch_size = saved_tbs
        if self._micro_count:
            logger.warning("elastic resume inside an accumulation window: "
                           "dropping %d partial micro-batches",
                           self._micro_count)
            self._micro_count = 0
        if new_gas != old_gas:
            self._compile_steps()   # gas is baked into the step programs
        self.tput_timer.batch_size = saved_tbs
        get_registry().counter(
            "ds_elastic_resumes_total",
            "checkpoint loads that rescaled grad accumulation to preserve "
            "the global batch across a world-size change").inc()
        self._flight.record("elastic_resume", saved_dp=saved_dp, dp=cur_dp,
                            saved_gas=saved_gas, gas=new_gas,
                            global_batch=saved_tbs)
        if self._timeline.enabled:
            self._timeline.event("elastic_resume", time.perf_counter(),
                                 saved_dp=saved_dp, dp=cur_dp,
                                 saved_gas=saved_gas, gas=new_gas,
                                 global_batch=saved_tbs)
        log_dist(f"elastic resume: dp {saved_dp} -> {cur_dp}; "
                 f"gradient_accumulation_steps {saved_gas} -> {new_gas} "
                 f"preserves global batch {saved_tbs}", ranks=[0])

    def _cast_like(self, tree, like):
        """Cast loaded leaves to the live state's dtypes (cheap jitted map;
        checkpoints may hold a different precision than the running config).
        Shape mismatches get a clear error — e.g. optimizer-state layouts
        that changed between releases cannot be silently coerced."""
        def cast(a, b):
            if tuple(getattr(a, "shape", ())) != tuple(getattr(b, "shape", ())):
                raise ValueError(
                    f"checkpoint leaf shape {getattr(a, 'shape', ())} does "
                    f"not match the live state's {getattr(b, 'shape', ())} — "
                    "the state layout changed (e.g. Adam8bit block layout); "
                    "restart without load or export/import via the "
                    "universal checkpoint")
            return a.astype(b.dtype) if a.dtype != b.dtype else a

        return jax.tree.map(cast, tree, like)

    def module_params(self):
        """Model-shaped param view: strips 0/1 Adam's leading [W] replica
        axis (worker-0's replica, the reference's rank-0 save convention).
        Export/introspection consumers must use this, not ``state.params``."""
        params = self.state.params
        if self._onebit_stacked:
            params = jax.tree.map(lambda x: x[0], params)
        return params

    def save_16bit_model(self, save_dir: str, save_filename: str = "model_states_16bit"):
        """Save compute-dtype weights (reference:
        ``stage3_gather_16bit_weights_on_model_save``) — sharded layout, cast
        on device, written shard-streamed: no rank-0 full gather."""
        os.makedirs(save_dir, exist_ok=True)
        cdtype = self.compute_dtype
        if self._zeropp:
            # flat shards -> full model-shaped tree (explicit export API;
            # the gather here is the point of the call)
            import functools

            from deepspeed_tpu.runtime.zero import zeropp as zpp

            out_specs = jax.tree.map(lambda _: P(), self._zpp_shapes,
                                     is_leaf=lambda x: isinstance(x, tuple))
            gfn = jax.jit(jax.shard_map(
                functools.partial(zpp.gather_param_tree, cfg=self._zpp_cfg,
                                  shapes=self._zpp_shapes),
                mesh=self.mesh, in_specs=(self._zpp_state_param_specs,),
                out_specs=out_specs, check_vma=False))
            full = gfn(self.state.params)
            out = os.path.join(save_dir, save_filename)
            self.checkpoint_engine.save(full, out)
            comm.barrier()
            return out
        # In param_offload mode the live shardings are pinned_host — cast with
        # device outputs (the partitioner rejects host-placed jit outputs on
        # multi-device meshes); the sharded writer streams either way.
        if self._onebit_stacked:
            out_sh = None  # model-shaped view; default placement
        else:
            out_sh = (self._param_dev_shardings if self._param_offload
                      else self._param_shardings)
        cast_fn = (lambda p: jax.tree.map(
            lambda x: x.astype(cdtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, p))
        jit_kw = {} if out_sh is None else {"out_shardings": out_sh}
        cast = jax.jit(cast_fn, **jit_kw)(self.module_params())
        out = os.path.join(save_dir, save_filename)
        self.checkpoint_engine.save(cast, out)
        comm.barrier()
        return out
