"""DSL006 — flight/trace shared-structure mutation discipline.

Originating incident: PR 7's scrape-race class — the metrics HTTP thread
iterating the request tracer's ring/heap while the engine thread mutated
them, and the perfetto clock anchor being patched field-by-field under a
reader.  The repaired contract, per structure kind:

- ``swap``   — the published object is immutable; writers REBIND the
  whole attribute (``self.f = new``), never mutate in place.  The clock
  anchor and any snapshot-published dict use this.
- ``atomic`` — single-writer structures read by snapshot-copy
  (``list(self._ring)``): each mutation must be ONE GIL-atomic operation
  (method call like ``append``/``heappush``, whole rebind, or a
  single-level slot store ``self.f[i] = rec``).  Mutating a PUBLISHED
  element in place (``self.f[i]["k"] = v``, ``self.f[i].x = v``,
  augmented assigns) races every reader that copied the container.
- ``lock:<attr>`` — every write happens inside ``with self.<attr>:``.

Structures opt in via annotations the analyzer evaluates literally:

    class RequestTracer:
        _dslint_shared = {"_ring": "atomic", "_slowest": "atomic"}

    _DSLINT_SHARED_GLOBALS = {"_ANCHOR": "swap"}        # module level

The attribute-write-site analysis then audits every method of the class
(and every module function for globals).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .astutil import FUNC_NODES, tail_name, walk_no_nested
from .engine import FileContext, Finding, Project, Rule, register_rule

CLASS_TAG = "_dslint_shared"
GLOBAL_TAG = "_DSLINT_SHARED_GLOBALS"
HEAPQ_MUTATORS = {"heappush", "heappop", "heapreplace", "heapify",
                  "heappushpop"}


def _literal_str_dict(node: ast.AST) -> Optional[Dict[str, str]]:
    if not isinstance(node, ast.Dict):
        return None
    out: Dict[str, str] = {}
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant)
                and isinstance(v.value, str)):
            return None
        out[k.value] = v.value
    return out


def _field_ref(node: ast.AST, owner: str, field: str) -> bool:
    """``self.field`` (owner='self') or bare ``field`` (owner='')."""
    if owner:
        return (isinstance(node, ast.Attribute) and node.attr == field
                and isinstance(node.value, ast.Name)
                and node.value.id == owner)
    return isinstance(node, ast.Name) and node.id == field


class SharedMutationRule(Rule):
    id = "DSL006"
    title = "tagged shared structures: swap-whole / atomic op / under lock"
    incident = ("PR 7 — /statz scrape thread racing the engine thread on "
                "the tracer ring/heap; the clock anchor must swap whole")

    def check_file(self, ctx: FileContext,
                   project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        # module-level globals
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == GLOBAL_TAG:
                tags = _literal_str_dict(stmt.value)
                if tags:
                    self._check_scope(ctx, ctx.tree, "", tags, findings)
        # classes
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and stmt.targets[0].id == CLASS_TAG:
                    tags = _literal_str_dict(stmt.value)
                    if tags:
                        self._check_scope(ctx, node, "self", tags,
                                          findings)
        return findings

    # ------------------------------------------------------------------
    def _check_scope(self, ctx: FileContext, scope_node, owner: str,
                     tags: Dict[str, str], findings: List[Finding]) -> None:
        init_name = "__init__" if owner else None

        for fn in ast.walk(scope_node):
            if not isinstance(fn, FUNC_NODES):
                continue
            in_init = fn.name == init_name
            fn_tags = tags
            if not owner:
                # module globals: a bare Name is only THE global inside a
                # function that declares ``global <name>`` or never binds
                # it locally — a same-named local temp is out of scope
                fn_tags = {f: p for f, p in tags.items()
                           if self._names_global(fn, f)}
                if not fn_tags:
                    continue
            self._check_fn(ctx, fn, owner, fn_tags, in_init, findings)

    @staticmethod
    def _names_global(fn, name: str) -> bool:
        declared = any(isinstance(s, ast.Global) and name in s.names
                       for s in walk_no_nested(fn))
        if declared:
            return True
        bound_locally = any(
            isinstance(n, ast.Name) and n.id == name
            and isinstance(n.ctx, (ast.Store, ast.Del))
            for n in walk_no_nested(fn))
        return not bound_locally

    def _check_fn(self, ctx, fn, owner, tags, in_init, findings) -> None:

        def report(node, field, policy, what) -> None:
            hint = {
                "swap": "rebind the whole object instead "
                        "(readers hold the old snapshot)",
                "atomic": "use one GIL-atomic op (append/heappush/whole "
                          "slot store) or swap the whole object",
            }.get(policy.split(":")[0],
                  f"wrap the write in 'with {owner}.{policy.split(':', 1)[-1]}:'")
            findings.append(Finding(
                self.id, ctx.rel, node.lineno, node.col_offset,
                f"shared structure {field!r} (policy {policy!r}) mutated "
                f"via {what} — {hint} (scrape-race class, PR 7)",
                end_line=getattr(node, "end_lineno", None) or node.lineno))

        def walk(stmts: Sequence[ast.stmt],
                 held_locks: Tuple[str, ...]) -> None:
            for stmt in stmts:
                if isinstance(stmt, FUNC_NODES):
                    continue
                if isinstance(stmt, ast.With):
                    locks = []
                    for item in stmt.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Attribute) \
                                and isinstance(ce.value, ast.Name) \
                                and (not owner or ce.value.id == owner):
                            locks.append(ce.attr)
                        elif isinstance(ce, ast.Name):
                            locks.append(ce.id)
                    walk(stmt.body, held_locks + tuple(locks))
                    continue
                self._check_stmt(ctx, stmt, owner, tags, in_init,
                                 held_locks, report)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and sub \
                            and isinstance(sub[0], ast.stmt):
                        walk(sub, held_locks)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        walk(h.body, held_locks)

        walk(fn.body, ())

    # ------------------------------------------------------------------
    def _check_stmt(self, ctx, stmt, owner, tags, in_init, held_locks,
                    report) -> None:

        def policy_violation(field: str, policy: str, node, what: str,
                             atomic_ok: bool) -> None:
            kind = policy.split(":")[0]
            if kind == "lock":
                lock_attr = policy.split(":", 1)[1]
                if lock_attr not in held_locks:
                    report(node, field, policy, what)
            elif kind == "swap":
                if what != "whole rebind":
                    report(node, field, policy, what)
            elif kind == "atomic":
                if not atomic_ok and what != "whole rebind":
                    report(node, field, policy, what)

        def match_field(node) -> Optional[str]:
            for f in tags:
                if _field_ref(node, owner, f):
                    return f
            return None

        def unwind(t) -> Tuple[Optional[str], int]:
            """(tagged field, store depth) when ``t`` writes into one:
            depth 0 = whole rebind, 1 = slot store, >1 = nested."""
            node, depth = t, 0
            while True:
                f = match_field(node)
                if f is not None:
                    return f, depth
                if isinstance(node, (ast.Subscript, ast.Attribute)):
                    node = node.value
                    depth += 1
                else:
                    return None, 0

        # assignment targets
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            aug = isinstance(stmt, ast.AugAssign)
            for t in targets:
                f, depth = unwind(t)
                if f is None:
                    continue
                if depth == 0 and not aug:
                    if not in_init:
                        policy_violation(f, tags[f], stmt, "whole rebind",
                                         True)
                    continue
                what = ("augmented assign" if aug else
                        "single-level slot store" if depth == 1 else
                        "nested element mutation")
                policy_violation(f, tags[f], stmt, what,
                                 atomic_ok=(depth == 1 and not aug))
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                base = t
                while isinstance(base, ast.Subscript):
                    base = base.value
                f = match_field(base)
                if f is not None:
                    policy_violation(f, tags[f], stmt, "del", False)
        elif isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            func = call.func
            # self.field.method(...)
            if isinstance(func, ast.Attribute):
                f = match_field(func.value)
                if f is not None:
                    policy_violation(f, tags[f], call,
                                     f"method call .{func.attr}()",
                                     atomic_ok=True)
                    return
            # heapq.heappush(self.field, ...)
            if tail_name(func) in HEAPQ_MUTATORS:
                for arg in call.args[:1]:
                    f = match_field(arg)
                    if f is not None:
                        policy_violation(f, tags[f], call,
                                         f"{tail_name(func)}()",
                                         atomic_ok=True)


register_rule(SharedMutationRule())


# --- selftest fixtures -----------------------------------------------------
SELFTEST_BAD = '''\
import heapq


class Tracer:
    _dslint_shared = {"_ring": "atomic", "_anchor": "swap",
                      "_pending": "lock:_lock"}

    def __init__(self):
        self._ring = []
        self._anchor = {"perf": 0.0}
        self._pending = None

    def record(self, rec):
        self._ring.append(rec)              # atomic op: fine
        self._ring[0]["t"] = 1.0            # <- nested element mutation
        self._anchor["perf"] = 2.0          # <- swap policy: no in-place
        self._pending = rec                 # <- lock policy: not held
'''

SELFTEST_GOOD = '''\
import heapq


class Tracer:
    _dslint_shared = {"_ring": "atomic", "_anchor": "swap",
                      "_pending": "lock:_lock"}

    def __init__(self):
        self._ring = []
        self._anchor = {"perf": 0.0}
        self._pending = None

    def record(self, rec):
        self._ring.append(rec)
        heapq.heappush(self._ring, rec)
        self._ring[3] = rec                 # whole-slot swap: atomic
        self._anchor = {"perf": 2.0}        # whole rebind
        with self._lock:
            self._pending = rec
'''
