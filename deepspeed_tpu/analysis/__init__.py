"""dslint: AST-level invariant checker for this repo's incident-derived
correctness rules (see docs/LINT.md for the catalogue):

- DSL001 donation safety (raw device_put vs donate_argnums callees)
- DSL002 sync-free hot paths (no hidden device syncs in step/decode/drain
  loops or disabled-telemetry branches)
- DSL003 jax-free operator tools (whole import-graph closure)
- DSL004 metric-namespace literals + the bench summary-block ledger
- DSL005 unconditional ds_comm_<op> named_scope on collective wrappers
- DSL006 flight/trace shared-structure mutation discipline

This package is stdlib-only and uses RELATIVE imports exclusively:
``tools/dslint.py`` loads it by file path on boxes with no jax (and the
package's own DSL003 closure check keeps it that way).  Run via::

    python tools/dslint.py deepspeed_tpu tools bench.py
    python tools/dslint.py --selftest
    make lint
"""

from .engine import (Finding, META_RULE, Project, RULES, Rule,  # noqa: F401
                     rule_ids, run_paths)
from . import dsl001_donation  # noqa: F401  (registration side effect)
from . import dsl002_sync  # noqa: F401
from . import dsl003_jaxfree  # noqa: F401
from . import dsl004_metrics  # noqa: F401
from . import dsl005_scope  # noqa: F401
from . import dsl006_shared  # noqa: F401
from .selftest import run_selftest  # noqa: F401

__all__ = ["Finding", "META_RULE", "Project", "RULES", "Rule", "rule_ids",
           "run_paths", "run_selftest"]
