"""Shared AST helpers for the dslint rules (stdlib only)."""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Tuple

FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)
SCOPE_NODES = FUNC_NODES + (ast.Lambda,)


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def tail_name(node: ast.AST) -> Optional[str]:
    """Last attribute segment of a callee (``device_put`` for any
    ``*.device_put``), or the bare Name."""
    d = dotted(node)
    return d.rsplit(".", 1)[-1] if d else None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """Literal int / tuple-of-ints (``donate_argnums`` values)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                vals.append(el.value)
            else:
                return None
        return tuple(vals)
    return None


def keyword(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def walk_no_nested(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` that does NOT descend into nested function/lambda
    bodies — their code runs in a different regime (usually inside jit,
    where host-sync heuristics don't apply)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, SCOPE_NODES):
            continue
        stack.extend(ast.iter_child_nodes(n))


def contains(node: ast.AST, pred) -> bool:
    return any(pred(n) for n in ast.walk(node))


def functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    """Every function/method def in the module, at any nesting."""
    for node in ast.walk(tree):
        if isinstance(node, FUNC_NODES):
            yield node


def terminates(stmts: Sequence[ast.stmt]) -> bool:
    """Whether a block always leaves the enclosing suite (return / raise /
    continue / break as its last statement)."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))
