"""DSL003 — jax-free operator tools.

Originating incidents: PR 7 (fleet_dump quietly imported the
``deepspeed_tpu`` package — whose ``__init__`` pulls jax — until its
loader was rewritten to go by file path) and PR 9 (tools/router.py's
no-jax contract pinned with a fresh-interpreter subprocess).  The
operator tools must run on boxes with no jax install; one careless
``import`` anywhere in their closure breaks every one of them.

This rule replaces N per-tool subprocess asserts with ONE whole-graph
check: for each tool entry point it computes the static import closure —

- plain ``import`` / ``from ... import`` at any nesting (a lazy jax
  import still violates the operator-box contract; ``if TYPE_CHECKING:``
  blocks are skipped);
- ``importlib.import_module("<literal>")``;
- the file-path loader idiom (``spec_from_file_location``): ``*.py``
  string literals in the call (including constant ``os.path.join``
  parts) resolve to repo files WITHOUT triggering package ``__init__``s
  — that is the idiom's whole point;
- importing ``deepspeed_tpu.a.b`` the normal way adds every package
  ``__init__`` on the chain, which is how jax usually sneaks in —

and reports the full chain when the closure reaches a banned root
(``jax``/``jaxlib``/``flax``/``optax``) at the import that introduces it.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .astutil import const_str, tail_name
from .engine import FileContext, Finding, Project, Rule, register_rule

# the operator-tool entry points under tools/ that carry the no-jax
# contract (each states it in its docstring; dslint itself is one)
JAXFREE_TOOLS = ("router.py", "fleet_dump.py", "ckpt_verify.py",
                 "train_supervisor.py", "serve_supervisor.py",
                 "trace_report.py", "metrics_dump.py", "perf_ledger.py",
                 "goodput_report.py", "dslint.py")
BANNED_ROOTS = {"jax", "jaxlib", "flax", "optax"}
PACKAGE = "deepspeed_tpu"


def _guard_polarity(test: ast.AST):
    """Whether ``test`` being TRUE means "cannot newly import at runtime":

    - ``TYPE_CHECKING`` → True (the body never executes);
    - ``"pkg" in sys.modules`` / ``sys.modules.get(x) is not None`` →
      True (the PR 9 package-or-file-path loader idiom: the body only
      runs when the package is ALREADY imported, so it cannot newly drag
      jax onto an operator box);
    - negations flip; anything else → None (both branches are live).
    """
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = _guard_polarity(test.operand)
        return None if inner is None else (not inner)
    if tail_name(test) in ("TYPE_CHECKING", "typing.TYPE_CHECKING"):
        return True
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        mentions = any(isinstance(s, ast.Attribute) and s.attr == "modules"
                       and tail_name(s.value) == "sys"
                       for s in ast.walk(test))
        if mentions:
            op = test.ops[0]
            if isinstance(op, ast.In):
                return True
            if isinstance(op, ast.NotIn):
                return False
            if isinstance(op, ast.IsNot):   # sys.modules.get(x) is not None
                return True
            if isinstance(op, ast.Is):      # sys.modules.get(x) is None
                return False
    return None


def _skipped_imports(tree: ast.AST) -> Set[ast.AST]:
    """Import nodes that cannot pull new modules at runtime — ONLY the
    dead side of a recognized guard is skipped: the body of a positive
    guard (``if TYPE_CHECKING:`` / ``if "pkg" in sys.modules:``), or the
    ``else`` of a negated one.  ``if "pkg" not in sys.modules: import
    jax`` runs exactly on the operator box and stays checked."""
    skip: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.If):
            continue
        polarity = _guard_polarity(node.test)
        if polarity is None:
            continue
        dead = node.body if polarity else node.orelse
        for stmt in dead:
            for sub in ast.walk(stmt):
                skip.add(sub)
    return skip


def _module_to_rel(name: str, importer_rel: str, level: int,
                   root: str) -> List[str]:
    """Repo-relative candidate files a module name resolves to.

    Returns [] for stdlib/third-party.  Package imports include every
    ``__init__.py`` on the chain (they execute)."""
    out: List[str] = []
    if level:
        # relative import: resolve against the importer's directory
        base = os.path.dirname(importer_rel)
        for _ in range(level - 1):
            base = os.path.dirname(base)
        parts = [p for p in name.split(".") if p] if name else []
        target = "/".join([base] + parts) if base else "/".join(parts)
        for cand in (target + ".py", target + "/__init__.py"):
            if os.path.isfile(os.path.join(root, cand)):
                out.append(cand)
        return out
    parts = name.split(".")
    if parts[0] == PACKAGE:
        # executing a package import runs every __init__ on the chain
        for i in range(1, len(parts)):
            init = "/".join(parts[:i]) + "/__init__.py"
            if os.path.isfile(os.path.join(root, init)):
                out.append(init)
        leaf = "/".join(parts)
        for cand in (leaf + ".py", leaf + "/__init__.py"):
            if os.path.isfile(os.path.join(root, cand)):
                out.append(cand)
        return out
    # tools import their siblings bare (tools/ is put on sys.path)
    if importer_rel.startswith("tools/"):
        cand = "tools/" + parts[0] + ".py"
        if os.path.isfile(os.path.join(root, cand)):
            out.append(cand)
            return out
    # a bare module that happens to live at repo root (bench etc.)
    cand = parts[0] + ".py"
    if os.path.isfile(os.path.join(root, cand)):
        out.append(cand)
    return out


def _py_consts_in(node: ast.AST) -> List[str]:
    """``*.py`` path literals in one expression subtree: constant-tailed
    ``os.path.join`` calls, plus bare constants that are not join
    components (a lone ``"__init__.py"`` join part is not a path)."""
    consts: List[str] = []
    join_parts: set = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and tail_name(sub.func) == "join":
            parts = [const_str(a) for a in sub.args]
            for a in sub.args:
                join_parts.add(id(a))
            if parts and parts[-1] and parts[-1].endswith(".py") \
                    and all(p is not None for p in parts[1:]):
                consts.append("/".join(p for p in parts if p is not None))
    for sub in ast.walk(node):
        s = const_str(sub)
        if s and s.endswith(".py") and id(sub) not in join_parts:
            consts.append(s)
    return consts


def _literal_py_paths(scope: ast.AST, importer_rel: str,
                      root: str) -> List[str]:
    """Repo files loaded via the file-path idiom
    (``spec_from_file_location``): literals inside the loader calls,
    plus — because the path is often built a few lines away — literals
    in assignments to any name that (transitively) feeds a loader call.
    A ``.py`` constant elsewhere in the file (an argv default, say) is
    NOT treated as loaded."""
    spec_calls = [n for n in ast.walk(scope)
                  if isinstance(n, ast.Call)
                  and tail_name(n.func) == "spec_from_file_location"]
    consts: List[str] = []
    relevant: set = set()
    for call in spec_calls:
        consts.extend(_py_consts_in(call))
        for sub in ast.walk(call):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                relevant.add(sub.id)
    assigns = [n for n in ast.walk(scope)
               if isinstance(n, ast.Assign) and len(n.targets) == 1
               and isinstance(n.targets[0], ast.Name)]
    changed = True
    while changed:
        changed = False
        for a in assigns:
            if a.targets[0].id in relevant:
                for sub in ast.walk(a.value):
                    if isinstance(sub, ast.Name) \
                            and isinstance(sub.ctx, ast.Load) \
                            and sub.id not in relevant:
                        relevant.add(sub.id)
                        changed = True
    for a in assigns:
        if a.targets[0].id in relevant:
            consts.extend(_py_consts_in(a.value))
    out: List[str] = []
    importer_dir = os.path.dirname(importer_rel)
    for c in consts:
        c = c.replace(os.sep, "/").lstrip("./")
        for base in ("", importer_dir, "tools", PACKAGE):
            cand = "/".join([p for p in (base, c) if p])
            if os.path.isfile(os.path.join(root, cand)):
                out.append(cand)
                break
        else:
            # suffix match anywhere under the package tree
            suffix = "/" + c
            for dirpath, dirnames, filenames in os.walk(
                    os.path.join(root, PACKAGE)):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in filenames:
                    full = os.path.join(dirpath, fn)
                    relc = os.path.relpath(full, root).replace(os.sep, "/")
                    if relc.endswith(suffix):
                        out.append(relc)
    return out


class _Edge:
    __slots__ = ("dest", "line", "end_line", "banned")

    def __init__(self, dest: Optional[str], line: int, end_line: int = 0,
                 banned: Optional[str] = None):
        self.dest = dest        # repo-relative file, or None for banned
        self.line = line
        self.end_line = end_line or line   # imports can span lines
        self.banned = banned    # banned root name when dest is None


def _edges(ctx: FileContext, root: str) -> List[_Edge]:
    """Outgoing import edges of one file."""
    skip = _skipped_imports(ctx.tree)
    edges: List[_Edge] = []

    def add_module(name: str, level: int, line: int,
                   end_line: int = 0) -> None:
        if not level and name.split(".")[0] in BANNED_ROOTS:
            edges.append(_Edge(None, line, end_line,
                               banned=name.split(".")[0]))
            return
        for rel in _module_to_rel(name, ctx.rel, level, root):
            edges.append(_Edge(rel, line, end_line))

    for node in ast.walk(ctx.tree):
        if node in skip:
            continue
        if isinstance(node, ast.Import):
            for alias in node.names:
                add_module(alias.name, 0, node.lineno,
                           node.end_lineno or node.lineno)
        elif isinstance(node, ast.ImportFrom):
            end = node.end_lineno or node.lineno
            add_module(node.module or "", node.level, node.lineno, end)
            if node.level:
                # ``from . import engine`` binds submodules by name
                base = (node.module + "." if node.module else "")
                for alias in node.names:
                    for rel in _module_to_rel(base + alias.name, ctx.rel,
                                              node.level, root):
                        edges.append(_Edge(rel, node.lineno, end))
            # ``from pkg import submodule`` may bind a module, not an
            # attribute; resolve those too (conservative: only when the
            # name is a file next to the package)
            if node.level == 0 and node.module \
                    and node.module.split(".")[0] == PACKAGE:
                for alias in node.names:
                    sub = node.module + "." + alias.name
                    for rel in _module_to_rel(sub, ctx.rel, 0, root):
                        if rel.endswith(alias.name + ".py") \
                                or rel.endswith(alias.name + "/__init__.py"):
                            edges.append(_Edge(rel, node.lineno, end))
        elif isinstance(node, ast.Call):
            t = tail_name(node.func)
            if t == "import_module" and node.args:
                name = const_str(node.args[0])
                if name:
                    add_module(name, 0, node.lineno,
                               node.end_lineno or node.lineno)
            elif t == "spec_from_file_location":
                for rel in _literal_py_paths(ctx.tree, ctx.rel, root):
                    edges.append(_Edge(rel, node.lineno,
                                       node.end_lineno or node.lineno))
    return edges


class JaxFreeToolsRule(Rule):
    id = "DSL003"
    title = "operator tools must not reach jax in their import closure"
    incident = ("PR 7/9 — fleet_dump imported the jax-pulling package "
                "__init__; per-tool subprocess asserts replaced by one "
                "whole-graph closure check")

    def check_project(self, project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        for tool in JAXFREE_TOOLS:
            rel = "tools/" + tool
            ctx = project.context_for(rel)
            if ctx is None:
                continue
            findings.extend(self._check_tool(project, rel))
        return findings

    def _check_tool(self, project: Project, entry: str) -> List[Finding]:
        root = project.root
        # BFS with parent pointers; report once per (entry, banned edge)
        visited: Set[str] = {entry}
        parent: Dict[str, Tuple[str, int]] = {}
        queue: List[str] = [entry]
        findings: List[Finding] = []
        reported: Set[Tuple[str, int]] = set()

        def chain(rel: str) -> str:
            hops = [rel]
            while hops[-1] in parent:
                hops.append(parent[hops[-1]][0])
            return " <- ".join(hops)

        while queue:
            rel = queue.pop(0)
            ctx = project.context_for(rel)
            if ctx is None:
                continue
            for edge in _edges(ctx, root):
                # a line-level ``# dslint: disable=DSL003 -- reason`` on an
                # import PRUNES that edge: the annotation documents why the
                # import cannot run on the jax-less path (e.g. a lazy
                # import only reached from live-capture code)
                if ctx.suppressed(Finding(self.id, ctx.rel, edge.line, 0,
                                          "", end_line=edge.end_line)):
                    continue
                if edge.banned is not None:
                    # one finding per (tool, banned root): BFS order makes
                    # this the SHORTEST offending chain — fixing it either
                    # clears the tool or surfaces the next chain
                    key = (entry, edge.banned)
                    if key in reported:
                        continue
                    reported.add(key)
                    f = Finding(
                        self.id, ctx.rel, edge.line, 0,
                        f"jax-free tool {entry!r} reaches {edge.banned!r} "
                        f"via: {chain(rel)} — load repo modules by file "
                        f"path (the fleet_dump idiom) or make the import "
                        f"lazy behind the jax-needing call",
                        end_line=edge.end_line)
                    if not ctx.suppressed(f):
                        findings.append(f)
                elif edge.dest not in visited:
                    visited.add(edge.dest)
                    parent[edge.dest] = (rel, edge.line)
                    queue.append(edge.dest)
        return findings


register_rule(JaxFreeToolsRule())


# --- selftest fixtures (project trees, built by the selftest) --------------
SELFTEST_BAD_TREE = {
    "tools/router.py": "import helper\n",
    "tools/helper.py": "from deepspeed_tpu.monitor import metrics\n",
    "deepspeed_tpu/__init__.py": "import jax\n",
    "deepspeed_tpu/monitor/__init__.py": "",
    "deepspeed_tpu/monitor/metrics.py": "import json\n",
}

# the inverted loader guard: the import runs EXACTLY on the jax-less
# path — only the dead side of a guard may be skipped
SELFTEST_BAD_NEGATED_GUARD_TREE = {
    "tools/router.py": (
        "import sys\n"
        "if 'deepspeed_tpu' not in sys.modules:\n"
        "    import jax  # runs precisely on the operator box\n"
    ),
}

SELFTEST_GOOD_TREE = {
    "tools/router.py": (
        "import importlib.util, os\n"
        "spec = importlib.util.spec_from_file_location(\n"
        "    '_m', os.path.join(_R, 'deepspeed_tpu', 'monitor',"
        " 'metrics.py'))\n"
    ),
    "deepspeed_tpu/__init__.py": "import jax\n",
    "deepspeed_tpu/monitor/__init__.py": "",
    "deepspeed_tpu/monitor/metrics.py": "import json\n",
}
