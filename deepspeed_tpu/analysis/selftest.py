"""dslint selftest: every rule must fire on its seeded fixture and stay
quiet on its clean twin, and the suppression machinery must enforce the
reason requirement.  Pure stdlib + temp files, so ``tools/dslint.py
--selftest`` runs on an operator box and is wired tier-1 (the
fleet_dump/ckpt_verify idiom: the offline tool cannot silently rot).
"""

from __future__ import annotations

import os
import tempfile
from typing import Dict, List, Tuple

from . import (dsl001_donation, dsl002_sync, dsl003_jaxfree, dsl004_metrics,
               dsl005_scope, dsl006_shared)
from .engine import META_RULE, run_paths

# (rule id, bad source, good source, in-tree filename) — file-level rules
# (DSL005 is scoped to comm/ directories, so its fixture lives there)
_FILE_CASES = [
    ("DSL001", dsl001_donation.SELFTEST_BAD, dsl001_donation.SELFTEST_GOOD,
     "case.py"),
    ("DSL002", dsl002_sync.SELFTEST_BAD, dsl002_sync.SELFTEST_GOOD,
     "case.py"),
    ("DSL004", dsl004_metrics.SELFTEST_BAD, dsl004_metrics.SELFTEST_GOOD,
     "case.py"),
    ("DSL005", dsl005_scope.SELFTEST_BAD, dsl005_scope.SELFTEST_GOOD,
     "deepspeed_tpu/comm/case.py"),
    ("DSL006", dsl006_shared.SELFTEST_BAD, dsl006_shared.SELFTEST_GOOD,
     "case.py"),
]


def _lint_source(source: str, root: str, name: str = "case.py"):
    path = os.path.join(root, *name.split("/"))
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(source)
    findings, _ = run_paths([path], root=root)
    return findings


def _write_tree(root: str, tree: Dict[str, str]) -> None:
    for rel, src in tree.items():
        path = os.path.join(root, *rel.split("/"))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(src)


def run_selftest(verbose: bool = False) -> List[str]:
    """Returns a list of failure strings (empty = OK)."""
    failures: List[str] = []

    def check(cond: bool, msg: str) -> None:
        if not cond:
            failures.append(msg)
        elif verbose:
            print(f"  ok: {msg}")

    with tempfile.TemporaryDirectory(prefix="dslint_selftest_") as td:
        for rule_id, bad, good, fname in _FILE_CASES:
            sub = os.path.join(td, rule_id.lower())
            os.makedirs(sub, exist_ok=True)
            hits = [f for f in _lint_source(bad, sub, fname)
                    if f.rule == rule_id]
            check(bool(hits), f"{rule_id} fires on its seeded fixture")
            clean = [f for f in _lint_source(good, sub, fname)
                     if f.rule == rule_id]
            check(not clean,
                  f"{rule_id} stays quiet on the clean fixture "
                  f"(got {[f.render() for f in clean]})")

        # DSL004 bench summary-block ledger (needs the bench.py filename)
        sub = os.path.join(td, "dsl004_bench")
        os.makedirs(sub, exist_ok=True)
        hits = [f for f in _lint_source(dsl004_metrics.SELFTEST_BAD_BENCH,
                                        sub, "bench.py")
                if f.rule == "DSL004"]
        check(bool(hits), "DSL004 flags a summary block outside the "
                          "cap victim list")

        # DSL004 documented-name check over the ds_prof_* continuous-
        # profiler family: a fixture docs file documents two names (one
        # labeled); an undocumented ds_prof_ literal must be flagged, the
        # documented pair (labels stripped by the normalizer) must pass
        sub = os.path.join(td, "dsl004_prof")
        _write_tree(sub, {"docs/OBSERVABILITY.md":
                          dsl004_metrics.SELFTEST_PROF_DOCS})
        hits = [f for f in _lint_source(dsl004_metrics.SELFTEST_BAD_PROF,
                                        sub) if f.rule == "DSL004"]
        check(bool(hits), "DSL004 flags an undocumented ds_prof_* name")
        clean = [f for f in _lint_source(dsl004_metrics.SELFTEST_GOOD_PROF,
                                         sub) if f.rule == "DSL004"]
        check(not clean, "DSL004 accepts documented ds_prof_* names "
                         f"(got {[f.render() for f in clean]})")

        # DSL003 import-graph closure (project trees)
        for name, tree, expect in (
                ("bad", dsl003_jaxfree.SELFTEST_BAD_TREE, True),
                ("bad_negated_guard",
                 dsl003_jaxfree.SELFTEST_BAD_NEGATED_GUARD_TREE, True),
                ("good", dsl003_jaxfree.SELFTEST_GOOD_TREE, False)):
            sub = os.path.join(td, f"dsl003_{name}")
            _write_tree(sub, tree)
            findings, _ = run_paths(["tools"], root=sub)
            hits = [f for f in findings if f.rule == "DSL003"]
            if expect:
                check(bool(hits), f"DSL003 fires on the {name} tree")
                if name == "bad":
                    check(any("deepspeed_tpu/__init__.py" in f.message
                              for f in hits),
                          "DSL003 reports the full import chain")
            else:
                check(not hits, "DSL003 accepts the file-path loader "
                                f"idiom (got {[f.render() for f in hits]})")

        # suppression machinery (DSL005 fixture, in its comm/ home)
        sub = os.path.join(td, "suppress")
        os.makedirs(sub, exist_ok=True)
        comm = "deepspeed_tpu/comm/"
        bad_line = dsl005_scope.SELFTEST_BAD
        suppressed = bad_line.replace(
            "return lax.psum(x, axis)          # <- no ds_comm_ scope",
            "return lax.psum(x, axis)  "
            "# dslint: disable=DSL005 -- eager debug helper, never traced")
        hits = [f for f in _lint_source(suppressed, sub, comm + "s1.py")]
        check(not any(f.rule == "DSL005" and f.line == 7 for f in hits),
              "a disable with a reason suppresses its line")
        no_reason = bad_line.replace(
            "return lax.psum(x, axis)          # <- no ds_comm_ scope",
            "return lax.psum(x, axis)  # dslint: disable=DSL005")
        hits = _lint_source(no_reason, sub, comm + "s2.py")
        check(any(f.rule == META_RULE for f in hits),
              "a disable WITHOUT a reason is itself a finding (DSL000)")
        check(any(f.rule == "DSL005" for f in hits),
              "a reasonless disable does not suppress the finding")
        unknown = "x = 1  # dslint: disable=DSL999 -- no such rule\n"
        hits = _lint_source(unknown, sub, "s3.py")
        check(any(f.rule == META_RULE for f in hits),
              "naming an unknown rule is a DSL000 finding")

    return failures
