"""DSL005 — unconditional ``ds_comm_<op>`` named_scope on collective wrappers.

Originating incident: PR 3's compiled-program-stability contract — every
collective wrapper emits its ``ds_comm_<op>`` ``jax.named_scope``
UNCONDITIONALLY, so toggling telemetry never changes the compiled
program (a scope behind an ``if registry.enabled`` would recompile every
cached executable on toggle, and the device-trace matcher
(profiling/device_trace.py) would lose its rows exactly when you turn
profiling on).

Scope of the rule: files under a ``comm/`` directory (the wrapper
layers: ``deepspeed_tpu/comm/``, ``deepspeed_tpu/runtime/comm/``) plus
``deepspeed_tpu/runtime/pipe/`` — the pipeline schedules dispatch their
stage-boundary ``ppermute`` rings directly (ISSUE 16) and are held to
the same contract.  A function there that calls a ``lax`` collective
must wrap it in a ``with``-scope (``named_scope``/``scope``/``_scope``)
whose literal starts with ``ds_comm_``, and neither the collective nor
its scope may sit inside an ``if`` that tests a telemetry-enabled flag.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence

from .astutil import FUNC_NODES, const_str, tail_name
from .engine import FileContext, Finding, Project, Rule, register_rule

COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
               "psum_scatter", "all_to_all", "ppermute"}
SCOPE_FUNCS = {"named_scope", "scope", "_scope"}
SCOPE_PREFIX = "ds_comm_"
COMM_DIRS = ("deepspeed_tpu/comm/", "deepspeed_tpu/runtime/comm/",
             "deepspeed_tpu/runtime/pipe/")


def _is_collective(node: ast.Call) -> bool:
    func = node.func
    if not isinstance(func, ast.Attribute) or func.attr not in COLLECTIVES:
        return False
    recv = tail_name(func.value)
    return recv in ("lax", "jax.lax")


def _scope_of(withitem: ast.withitem) -> Optional[str]:
    ce = withitem.context_expr
    if isinstance(ce, ast.Call) and tail_name(ce.func) in SCOPE_FUNCS \
            and ce.args:
        return const_str(ce.args[0])
    return None


def _enabled_test(node: ast.AST) -> bool:
    return any(isinstance(s, ast.Attribute) and s.attr == "enabled"
               for s in ast.walk(node))


class UnconditionalScopeRule(Rule):
    id = "DSL005"
    title = "comm wrappers: ds_comm_<op> named_scope, outside telemetry ifs"
    incident = ("PR 3 — toggling telemetry must never change the compiled "
                "program; the device-trace matcher keys on the scope name")

    def check_file(self, ctx: FileContext,
                   project: Project) -> Iterable[Finding]:
        if not any(d in ctx.rel for d in COMM_DIRS):
            return ()
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, FUNC_NODES):
                self._check_fn(ctx, node, findings)
        return findings

    def _check_fn(self, ctx: FileContext, fn, findings) -> None:

        def walk(stmts: Sequence[ast.stmt], scopes: List[str],
                 in_enabled_if: bool) -> None:
            for stmt in stmts:
                if isinstance(stmt, FUNC_NODES):
                    continue   # nested defs get their own visit
                if isinstance(stmt, ast.With):
                    names = [s for s in (_scope_of(i) for i in stmt.items)
                             if s]
                    ds = [s for s in names if s.startswith(SCOPE_PREFIX)]
                    if ds and in_enabled_if:
                        findings.append(Finding(
                            self.id, ctx.rel, stmt.lineno, stmt.col_offset,
                            f"named_scope {ds[0]!r} emitted inside a "
                            f"telemetry-enabled conditional — the scope "
                            f"must be unconditional (compiled-program "
                            f"stability, PR 3)"))
                    walk(stmt.body, scopes + ds, in_enabled_if)
                    continue
                if isinstance(stmt, ast.If):
                    enab = _enabled_test(stmt.test)
                    walk(stmt.body, scopes, in_enabled_if or enab)
                    walk(stmt.orelse, scopes, in_enabled_if or enab)
                    continue
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and sub \
                            and isinstance(sub[0], ast.stmt):
                        walk(sub, scopes, in_enabled_if)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        walk(h.body, scopes, in_enabled_if)
                # expression scan for collectives (skip nested defs)
                stack = [stmt]
                while stack:
                    n = stack.pop()
                    if isinstance(n, FUNC_NODES + (ast.Lambda, ast.With,
                                                   ast.If)) \
                            and n is not stmt:
                        continue
                    if isinstance(n, ast.Call) and _is_collective(n):
                        if not scopes:
                            findings.append(Finding(
                                self.id, ctx.rel, n.lineno, n.col_offset,
                                f"lax.{n.func.attr} without an enclosing "
                                f"'with {SCOPE_PREFIX}<op>' named_scope — "
                                f"the device-trace matcher and xplane "
                                f"rows key on the scope name (PR 3)",
                                end_line=n.end_lineno or n.lineno))
                        elif in_enabled_if:
                            findings.append(Finding(
                                self.id, ctx.rel, n.lineno, n.col_offset,
                                f"lax.{n.func.attr} dispatched inside a "
                                f"telemetry-enabled conditional — the "
                                f"compiled program must not change when "
                                f"telemetry toggles (PR 3)",
                                end_line=n.end_lineno or n.lineno))
                    stack.extend(ast.iter_child_nodes(n))

        walk(fn.body, [], False)


register_rule(UnconditionalScopeRule())


# --- selftest fixtures -----------------------------------------------------
SELFTEST_BAD = '''\
from jax import lax

from deepspeed_tpu.profiling.trace import scope as _scope


def all_reduce(x, axis):
    return lax.psum(x, axis)          # <- no ds_comm_ scope


def all_gather(x, axis, registry):
    if registry.enabled:
        with _scope("ds_comm_all_gather"):    # <- conditional scope
            return lax.all_gather(x, axis, axis=0, tiled=True)
    return lax.all_gather(x, axis, axis=0, tiled=True)


def q_all_reduce(q, s, axis):
    # quantized wrapper shipping codes with a BARE exchange — the new
    # collectives_q surface is held to the same contract
    qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=False)
    return qt, st


def q_all_gather(q, s, axis, comm_metrics):
    if comm_metrics.enabled:          # <- codes exchanged under a
        with _scope("ds_comm_q_all_gather"):  # telemetry-enabled if
            return lax.all_gather(q, axis, axis=0, tiled=False)
    return lax.all_gather(q, axis, axis=0, tiled=False)
'''

SELFTEST_GOOD = '''\
from jax import lax

from deepspeed_tpu.profiling.trace import scope as _scope


def all_reduce(x, axis):
    with _scope("ds_comm_all_reduce"):
        return lax.psum(x, axis)


def q_all_reduce(q, s, axis, comm_metrics):
    # recording may be conditional; the exchange and its scope are not
    if comm_metrics.enabled:
        comm_metrics.record_q("q_all_reduce", axis, (q, s), q)
    with _scope("ds_comm_q_all_reduce"):
        qt = lax.all_to_all(q, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        st = lax.all_to_all(s, axis, split_axis=0, concat_axis=0,
                            tiled=False)
    return qt, st
'''
