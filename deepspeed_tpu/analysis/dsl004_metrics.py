"""DSL004 — metric-namespace literals + the bench summary-block ledger.

Originating incidents: PR 2 established the runtime namespace guard
(every REGISTERED metric must be ``ds_``-prefixed and documented in
docs/OBSERVABILITY.md) — but the runtime guard only sees a name when its
registration branch executes; a metric born behind a rarely-taken branch
escapes until production takes that branch.  This rule extracts every
``Counter``/``Gauge``/``Histogram`` name LITERAL (and every f-string
prefix) statically and applies the same two checks at parse time.

Second half (PR 10's bench handshake): the runner parses — and truncates
around ~2k chars — the LAST stdout line of bench.py, so
``summary_lines`` caps the final line at ``BENCH_SUMMARY_MAX_CHARS`` by
dropping optional blocks from an explicit victim list.  A NEW dict-valued
summary block that is not in that list silently re-opens the BENCH_r05
``"parsed": null`` bug the first time it pushes the line over budget.
This rule cross-checks every ``summary["<key>"] = <dict-ish>`` in
``summary_lines`` against the victim tuple of the cap loop.

Third half (PR 17's perf ledger): ``tools/perf_ledger.py`` builds
per-metric trajectories over the committed BENCH_*.json blocks and
attributes regressions to environment drift — which only works when
every block stamps its provenance.  When ``summary_lines`` emits blocks
at all, it must also stamp a ``summary["run_meta"]`` block built by a
``run_metadata()`` helper whose dict carries a ``schema_version`` key;
a bench block without the stamp is a trajectory point that can never be
attributed, so this rule requires it statically.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterable, List, Optional, Set, Tuple

from .astutil import const_str, tail_name
from .engine import FileContext, Finding, Project, Rule, register_rule

FAMILY_METHODS = {"counter", "gauge", "histogram"}
FAMILY_CLASSES = {"Counter", "Gauge", "Histogram"}
DOCS_REL = "docs/OBSERVABILITY.md"
PREFIX = "ds_"

# files that mint names from caller input rather than literals (the
# registry itself, and the dump/render tools)
EXEMPT_SUFFIXES = ("deepspeed_tpu/monitor/metrics.py",)

_WILD = "\x00"  # internal wildcard marker for f-string segments


def _extract_name(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """(name_pattern, is_literal) for a family-creating call; the pattern
    uses a wildcard marker for formatted f-string fields."""
    func = call.func
    is_family = False
    if isinstance(func, ast.Attribute) and func.attr in FAMILY_METHODS:
        is_family = True
    elif isinstance(func, ast.Name) and func.id in FAMILY_CLASSES:
        is_family = True
    if not is_family or not call.args:
        return None
    arg = call.args[0]
    s = const_str(arg)
    if s is not None:
        return s, True
    if isinstance(arg, ast.JoinedStr):
        parts = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append(_WILD)
        return "".join(parts), False
    return None   # dynamic name: the runtime guard owns it


def _docs_patterns(text: str) -> Set[str]:
    """Normalized metric tokens from the docs: backtick tokens starting
    with ds_, label blocks stripped, ``<op>``-style holes -> wildcard."""
    out: Set[str] = set()
    for tok in re.findall(r"`([^`]+)`", text):
        tok = tok.strip()
        if not tok.startswith(PREFIX):
            continue
        tok = re.sub(r"\{[^}]*\}", "", tok)          # label blocks
        tok = re.sub(r"<[^>]*>", _WILD, tok)         # <op> holes
        tok = tok.strip()
        if tok:
            out.add(tok)
    return out


def _pattern_matches(name: str, patterns: Set[str], raw_text: str) -> bool:
    if _WILD not in name:
        if name in patterns or name in raw_text:
            return True
        # a literal name may be documented as a <hole> pattern row
        for p in patterns:
            if _WILD in p and re.fullmatch(
                    re.escape(p).replace(re.escape(_WILD), r"[A-Za-z0-9_]+"),
                    name):
                return True
        return False
    # f-string: compare skeletons (wildcards collapse)
    skel = re.sub(_WILD + "+", _WILD, name)
    for p in patterns:
        if re.sub(_WILD + "+", _WILD, p) == skel:
            return True
    # fall back: the static prefix must at least appear in the docs
    prefix = name.split(_WILD, 1)[0]
    return bool(prefix) and prefix in raw_text


class MetricNamespaceRule(Rule):
    id = "DSL004"
    title = "metric name literals: ds_ prefix + documented; bench summary ledger"
    incident = ("PR 2's runtime namespace guard only fires when the "
                "registration branch executes; PR 10's BENCH_r05 record "
                "was lost to an uncapped final-line summary block")

    def check_file(self, ctx: FileContext,
                   project: Project) -> Iterable[Finding]:
        findings: List[Finding] = []
        if not ctx.rel.endswith(EXEMPT_SUFFIXES):
            findings.extend(self._check_names(ctx, project))
        if ctx.rel.endswith("bench.py"):
            findings.extend(self._check_bench_summary(ctx))
        return findings

    @staticmethod
    def _docs(project: Project):
        """(docs text, normalized pattern set), cached per Project — the
        docs depend only on the root, not on the file being checked."""
        cached = getattr(project, "_dsl004_docs", None)
        if cached is not None:
            return cached
        docs_text = ""
        docs_path = os.path.join(project.root, DOCS_REL)
        if os.path.isfile(docs_path):
            with open(docs_path, encoding="utf-8") as fh:
                docs_text = fh.read()
        patterns = _docs_patterns(docs_text) if docs_text else set()
        project._dsl004_docs = (docs_text, patterns)
        return project._dsl004_docs

    # -- metric name literals ------------------------------------------
    def _check_names(self, ctx: FileContext,
                     project: Project) -> List[Finding]:
        findings: List[Finding] = []
        docs_text, patterns = self._docs(project)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            got = _extract_name(node)
            if got is None:
                continue
            name, literal = got
            display = name.replace(_WILD, "{...}")
            lead = name.split(_WILD, 1)[0]
            if not lead.startswith(PREFIX):
                findings.append(Finding(
                    self.id, ctx.rel, node.lineno, node.col_offset,
                    f"metric name {display!r} outside the ds_ namespace "
                    f"(docs/OBSERVABILITY.md contract; the runtime guard "
                    f"only sees executed branches)",
                    end_line=node.end_lineno or node.lineno))
                continue
            if docs_text and not _pattern_matches(name, patterns,
                                                  docs_text):
                findings.append(Finding(
                    self.id, ctx.rel, node.lineno, node.col_offset,
                    f"metric name {display!r} not documented in "
                    f"{DOCS_REL} — add its schema row",
                    end_line=node.end_lineno or node.lineno))
        return findings

    # -- bench summary-block ledger ------------------------------------
    def _check_bench_summary(self, ctx: FileContext) -> List[Finding]:
        fn = None
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "summary_lines":
                fn = node
                break
        if fn is None:
            return []
        block_assigns: List[Tuple[str, ast.Assign]] = []
        victims: Set[str] = set()
        victim_node = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "summary":
                    key = const_str(t.slice)
                    # a "block" is a dict-valued entry: dict literal /
                    # comprehension / a call to a known dict builder
                    # (_strip_bulky).  Attribute calls (``ov.get(...)``)
                    # and scalar builtins (``len(...)``) are cap-exempt.
                    dictish = isinstance(node.value,
                                         (ast.Dict, ast.DictComp)) or (
                        isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Name)
                        and node.value.func.id in ("dict", "_strip_bulky",
                                                   "run_metadata"))
                    if key is not None and dictish:
                        block_assigns.append((key, node))
            elif isinstance(node, ast.For):
                if isinstance(node.target, ast.Name) \
                        and node.target.id == "victim" \
                        and isinstance(node.iter, (ast.Tuple, ast.List)):
                    victim_node = node
                    for el in node.iter.elts:
                        s = const_str(el)
                        if s:
                            victims.add(s)
        findings: List[Finding] = []
        if block_assigns and victim_node is None:
            a = block_assigns[0][1]
            return [Finding(
                self.id, ctx.rel, a.lineno, a.col_offset,
                "summary_lines writes summary blocks but has no "
                "'for victim in (...)' cap loop — the final-line byte "
                "budget (BENCH_SUMMARY_MAX_CHARS) is unenforced")]
        for key, node in block_assigns:
            if key not in victims:
                findings.append(Finding(
                    self.id, ctx.rel, node.lineno, node.col_offset,
                    f"BENCH_JSON summary block {key!r} is not in the "
                    f"final-line cap's victim list — an oversized line "
                    f"truncates to non-JSON and the whole record is lost "
                    f"(the BENCH_r05 'parsed: null' bug)",
                    end_line=node.end_lineno or node.lineno))
        if block_assigns:
            findings.extend(self._check_run_meta_stamp(ctx, fn,
                                                       block_assigns))
        return findings

    def _check_run_meta_stamp(self, ctx: FileContext, fn: ast.FunctionDef,
                              block_assigns: List[Tuple[str, ast.Assign]],
                              ) -> List[Finding]:
        """Blocks exist → a ``run_meta`` stamp with schema_version must too."""
        has_run_meta = any(k == "run_meta" for k, _ in block_assigns)
        schema_ok = False
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.FunctionDef) \
                    and node.name == "run_metadata":
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Dict) and any(
                            const_str(k) == "schema_version"
                            for k in sub.keys if k is not None):
                        schema_ok = True
                    elif isinstance(sub, ast.Assign) \
                            and len(sub.targets) == 1 \
                            and isinstance(sub.targets[0], ast.Subscript) \
                            and const_str(sub.targets[0].slice) \
                            == "schema_version":
                        schema_ok = True
        if has_run_meta and schema_ok:
            return []
        return [Finding(
            self.id, ctx.rel, fn.lineno, fn.col_offset,
            "BENCH_JSON blocks carry no run-metadata stamp — add "
            "summary['run_meta'] = run_metadata() with a "
            "'schema_version' key so tools/perf_ledger.py can attribute "
            "a regression to environment drift (git sha / jax version) "
            "instead of the code under test")]


register_rule(MetricNamespaceRule())


# --- selftest fixtures -----------------------------------------------------
SELFTEST_BAD = '''\
from deepspeed_tpu.monitor.metrics import get_registry

reg = get_registry()
bad = reg.counter("serve_requests_total", "missing ds_ prefix")  # <- BAD
'''

SELFTEST_GOOD = '''\
from deepspeed_tpu.monitor.metrics import get_registry

reg = get_registry()
ok = reg.counter("ds_serve_requests_total", "documented name")
dyn = reg.counter(name_variable)          # dynamic: runtime guard owns it
'''

# the ds_prof_* continuous-profiler family (docs/OBSERVABILITY.md
# "Continuous profiling"): the documented-name check must cover it like
# any other ds_ family — including the labeled {scope=} rows, whose docs
# tokens carry a label block the normalizer strips
SELFTEST_PROF_DOCS = '''\
# Observability
| `ds_prof_windows_total` | counter | completed windows |
| `ds_prof_scope_device_seconds{scope=}` | gauge | per-scope seconds |
'''

SELFTEST_BAD_PROF = '''\
from deepspeed_tpu.monitor.metrics import get_registry

reg = get_registry()
bad = reg.counter("ds_prof_bogus_total", "undocumented ds_prof name")
'''

SELFTEST_GOOD_PROF = '''\
from deepspeed_tpu.monitor.metrics import get_registry

reg = get_registry()
ok = reg.counter("ds_prof_windows_total", "documented")
lab = reg.gauge("ds_prof_scope_device_seconds", labels={"scope": "comm"})
'''

SELFTEST_BAD_BENCH = '''\
import json


def summary_lines(record, rung_serving):
    summary = {"metric": record["metric"]}
    summary["big_new_block"] = {"a": 1, "b": 2}      # <- not a victim
    line = json.dumps(summary)
    for victim in ("train_metrics",):
        if len(line) <= 1800:
            break
        summary.pop(victim, None)
        line = json.dumps(summary)
    return [line]
'''
