"""dslint engine: file walking, suppression parsing, rule running.

Stdlib-``ast`` only — this module (and every rule module) must be
importable WITHOUT jax, because ``tools/dslint.py`` loads the package by
file path on operator boxes and in pre-commit hooks (the
fleet_dump/ckpt_verify idiom).  Do not add package-absolute imports here:
``deepspeed_tpu/__init__`` pulls jax, which is exactly the class of
regression rule DSL003 exists to catch.

Suppression syntax (checked, not free-form):

    x = risky()  # dslint: disable=DSL001 -- <why this site is safe>
    # dslint: disable-file=DSL004 -- <why this whole file is exempt>

A ``disable`` without the `` -- reason`` tail, or naming an unknown rule,
is itself a finding (DSL000): the incident log is the point — a
suppression that doesn't say WHY rots into cargo cult.  ``disable``
applies to the physical lines its statement spans; ``disable-file``
applies to the whole file.  ``# dslint: hot`` on a ``def`` line opts that
function into the DSL002 hot-zone set without touching the rule config.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "FileContext", "Project", "Rule", "run_paths",
           "iter_python_files", "RULES", "register_rule", "rule_ids",
           "META_RULE"]

META_RULE = "DSL000"   # suppression hygiene (always on)

# populated by the rule modules at import time (see __init__.py)
RULES: List["Rule"] = []


def register_rule(rule: "Rule") -> "Rule":
    RULES.append(rule)
    return rule


def rule_ids() -> Set[str]:
    return {r.id for r in RULES} | {META_RULE}


@dataclass
class Finding:
    rule: str
    path: str            # as scanned (repo-relative when run from root)
    line: int
    col: int
    message: str
    end_line: int = 0    # last physical line of the flagged node

    def __post_init__(self):
        if not self.end_line:
            self.end_line = self.line

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)


_DIRECTIVE = "dslint:"


@dataclass
class _Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: str
    file_level: bool


class FileContext:
    """One parsed source file plus its dslint comment directives."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.line_suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        self.hot_lines: Set[int] = set()
        self.directive_findings: List[Finding] = []
        self._parse_directives()

    # -- comment directives --------------------------------------------
    def _parse_directives(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except tokenize.TokenError:  # pragma: no cover - ast parsed already
            comments = []
        known = rule_ids()
        for line, text in comments:
            body = text.lstrip("#").strip()
            if not body.startswith(_DIRECTIVE):
                continue
            directive = body[len(_DIRECTIVE):].strip()
            if directive == "hot":
                self.hot_lines.add(line)
                continue
            kind, _, rest = directive.partition("=")
            kind = kind.strip()
            if kind not in ("disable", "disable-file"):
                self.directive_findings.append(Finding(
                    META_RULE, self.rel, line, 0,
                    f"unknown dslint directive {kind!r} (expected "
                    f"disable / disable-file / hot)"))
                continue
            spec, sep, reason = rest.partition("--")
            rules = tuple(r.strip() for r in spec.split(",") if r.strip())
            reason = reason.strip()
            if not rules:
                self.directive_findings.append(Finding(
                    META_RULE, self.rel, line, 0,
                    "dslint disable names no rules"))
                continue
            bad = [r for r in rules if r not in known]
            if bad:
                self.directive_findings.append(Finding(
                    META_RULE, self.rel, line, 0,
                    f"dslint disable names unknown rule(s) {', '.join(bad)}"))
                continue
            if not sep or not reason:
                self.directive_findings.append(Finding(
                    META_RULE, self.rel, line, 0,
                    "dslint disable without a justification: write "
                    "'# dslint: disable=RULE -- <reason>'"))
                continue
            if kind == "disable-file":
                self.file_suppressions.update(rules)
            else:
                self.line_suppressions.setdefault(line, set()).update(rules)

    # -- suppression check ---------------------------------------------
    def suppressed(self, finding: Finding) -> bool:
        if finding.rule in self.file_suppressions:
            return True
        for line in range(finding.line, max(finding.line,
                                            finding.end_line) + 1):
            if finding.rule in self.line_suppressions.get(line, ()):
                return True
        return False


class Project:
    """The full scanned file set plus the repo root (for whole-project
    rules: DSL003's import closure, DSL004's docs cross-check)."""

    def __init__(self, root: str, files: Sequence[FileContext]):
        self.root = os.path.abspath(root)
        self.files = list(files)
        self.by_rel: Dict[str, FileContext] = {f.rel: f for f in self.files}

    def context_for(self, rel: str) -> Optional[FileContext]:
        """The scanned context for a repo-relative path; parses the file
        fresh when it exists on disk but was outside the scan set (an
        import-closure node still gets local suppressions honored)."""
        rel = rel.replace(os.sep, "/")
        ctx = self.by_rel.get(rel)
        if ctx is not None:
            return ctx
        path = os.path.join(self.root, rel)
        if os.path.isfile(path):
            try:
                with open(path, encoding="utf-8") as fh:
                    ctx = FileContext(path, rel, fh.read())
            except (SyntaxError, UnicodeDecodeError, ValueError):
                return None
            self.by_rel[rel] = ctx
            return ctx
        return None


class Rule:
    """Base rule.  Subclasses set ``id``/``title``/``incident`` and
    implement ``check_file`` and/or ``check_project``."""

    id = "DSL???"
    title = ""
    incident = ""      # the originating failure (docs/LINT.md pulls this)

    def check_file(self, ctx: FileContext,
                   project: "Project") -> Iterable[Finding]:
        return ()

    def check_project(self, project: "Project") -> Iterable[Finding]:
        return ()


def iter_python_files(paths: Sequence[str], root: str) -> List[Tuple[str, str]]:
    """Expand files/dirs into (abspath, relpath) pairs, skipping caches
    and build output."""
    out: List[Tuple[str, str]] = []
    seen: Set[str] = set()
    skip_dirs = {"__pycache__", ".git", "build", ".eggs", "node_modules"}
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        ap = os.path.abspath(ap)
        if os.path.isfile(ap):
            candidates = [ap]
        elif os.path.isdir(ap):
            candidates = []
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in skip_dirs)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        candidates.append(os.path.join(dirpath, fn))
        else:
            raise FileNotFoundError(f"dslint: no such path: {p}")
        for c in candidates:
            if c in seen:
                continue
            seen.add(c)
            rel = os.path.relpath(c, root)
            out.append((c, rel))
    return out


def load_context(path: str, rel: str) -> Optional[FileContext]:
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    return FileContext(path, rel, source)


def run_paths(paths: Sequence[str], root: Optional[str] = None,
              rules: Optional[Sequence[Rule]] = None,
              ) -> Tuple[List[Finding], Project]:
    """Lint ``paths`` (files or directories).  Returns the surviving
    (non-suppressed) findings sorted by location, plus the Project for
    callers that want the file census."""
    root = os.path.abspath(root or os.getcwd())
    active = list(rules if rules is not None else RULES)
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for path, rel in iter_python_files(paths, root):
        try:
            ctx = load_context(path, rel)
        except SyntaxError as exc:
            findings.append(Finding(META_RULE, rel.replace(os.sep, "/"),
                                    exc.lineno or 1, 0,
                                    f"syntax error: {exc.msg}"))
            continue
        except (UnicodeDecodeError, ValueError) as exc:
            # non-UTF-8 bytes / embedded NULs: a finding, not a crash
            findings.append(Finding(META_RULE, rel.replace(os.sep, "/"),
                                    1, 0, f"unparseable source: {exc}"))
            continue
        contexts.append(ctx)
    project = Project(root, contexts)
    for ctx in contexts:
        findings.extend(ctx.directive_findings)   # never suppressible
        for rule in active:
            for f in rule.check_file(ctx, project):
                if not ctx.suppressed(f):
                    findings.append(f)
    for rule in active:
        for f in rule.check_project(project):
            ctx = project.context_for(f.path)
            if ctx is None or not ctx.suppressed(f):
                findings.append(f)
    # dedupe: one finding per (rule, site, message) — nested AST walks may
    # visit a call from more than one enclosing statement
    seen: Set[Tuple] = set()
    unique: List[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    unique.sort(key=Finding.sort_key)
    return unique, project
