"""DSL002 — sync-free hot paths.

Originating incidents: PR 3 (``ds_train_loss`` publication paid a
``float()`` device sync even with telemetry disabled) and PR 7 (the
request tracer's disabled path had to be pinned to one branch / zero
alloc).  The serving decode/drain loops and the training step boundary
are dispatch pipelines: a stray ``float()`` / ``.item()`` /
``np.asarray`` / ``jax.device_get`` / ``block_until_ready`` on a
device value stalls the pipeline for a full device round-trip — and the
cheapest place to hide one is a telemetry branch that only executes when
metrics are OFF, where no test ever times it.

Checked regions:

- functions named in ``HOT_ZONES`` (per-file allowlists of the engine
  step / decode / drain loops), plus any function whose ``def`` line
  carries a ``# dslint: hot`` tag;
- within those, statements are EXEMPT when they can only run with
  telemetry enabled: the body of ``if <x>.enabled:`` (or of a local
  flag assigned from an ``.enabled`` expression), and everything after
  an ``if not <x>.enabled: return`` early-out;
- the body of ``if not <x>.enabled:`` itself is the DISABLED path — it
  is checked extra strictly (that's the never-executed-branch class).

Nested ``def``/``lambda`` bodies are skipped: inside ``jit`` those calls
are trace-time ops, not host syncs.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Sequence, Set

from .astutil import FUNC_NODES, tail_name, terminates
from .engine import FileContext, Finding, Project, Rule, register_rule

# function-name allowlists per path suffix: the engine step/decode/drain
# loops and their telemetry helpers (reachable every iteration)
HOT_ZONES = {
    "deepspeed_tpu/serving/engine.py": {
        "step", "_decode_block", "_drain_one", "_flush_outstanding",
        "_fetch_block", "_materialize", "_prefill_one_chunk",
        "_admit_prefix", "_release",
    },
    "deepspeed_tpu/runtime/engine.py": {
        "step", "train_step", "train_batch", "forward",
        "_micro_telemetry", "_boundary_telemetry", "_report",
    },
    "deepspeed_tpu/runtime/zero/streaming.py": {
        "prefetch", "_dispatch", "take", "_put", "_restage_into_slot",
        "record_d2h",
    },
}

# calls that force a device->host round-trip on a device value
SYNC_NAME_CALLS = {"float"}
SYNC_TAIL_CALLS = {"asarray", "array", "device_get", "block_until_ready"}
SYNC_METHODS = {"item"}
# receivers whose asarray/array is jnp (dispatch, not a host sync)
_DEVICE_NS = {"jnp", "jax.numpy"}
# benign argument shapes for float(...): literals and wall-clock reads
_TIME_CALLS = {"perf_counter", "time", "monotonic"}


def _enabled_expr(node: ast.AST, enabled_locals: Set[str]) -> bool:
    """Whether ``node`` mentions a telemetry-enabled flag."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "enabled":
            return True
        if isinstance(sub, ast.Name) and sub.id in enabled_locals:
            return True
    return False


def _not_enabled_test(test: ast.AST, enabled_locals: Set[str]) -> bool:
    return (isinstance(test, ast.UnaryOp)
            and isinstance(test.op, ast.Not)
            and _enabled_expr(test.operand, enabled_locals))


def _benign_float_arg(arg: ast.AST) -> bool:
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Call) and tail_name(arg.func) in _TIME_CALLS:
        return True
    return False


def _sync_call(node: ast.Call) -> Optional[str]:
    """A short description when ``node`` is a suspected sync, else None."""
    func = node.func
    if isinstance(func, ast.Name) and func.id in SYNC_NAME_CALLS:
        if node.args and not _benign_float_arg(node.args[0]):
            return f"{func.id}(...)"
        return None
    tail = tail_name(func)
    if tail in SYNC_METHODS and not node.args and not node.keywords:
        return ".item()"
    if tail in SYNC_TAIL_CALLS and isinstance(func, ast.Attribute):
        recv = func.value
        recv_name = tail_name(recv) if not isinstance(recv, ast.Name) \
            else recv.id
        # np.asarray / numpy.array sync; jnp.asarray is device dispatch
        if tail in ("asarray", "array"):
            if recv_name in ("np", "numpy"):
                return f"{recv_name}.{tail}(...)"
            return None
        return f"{tail}(...)"
    return None


class SyncFreeHotPathRule(Rule):
    id = "DSL002"
    title = "no hidden device syncs in hot loops / disabled-telemetry paths"
    incident = ("PR 3/7 — float()/np.asarray device syncs hiding in "
                "telemetry branches that only run with metrics disabled, "
                "stalling the async dispatch pipeline")

    def check_file(self, ctx: FileContext,
                   project: Project) -> Iterable[Finding]:
        zone = None
        for suffix, names in HOT_ZONES.items():
            if ctx.rel.endswith(suffix):
                zone = names
                break
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, FUNC_NODES):
                continue
            tagged = any(ln in ctx.hot_lines for ln in
                         range(min(d.lineno for d in
                                   node.decorator_list + [node]),
                               node.lineno + 1))
            if tagged or (zone is not None and node.name in zone):
                self._check_hot_function(ctx, node, findings)
        return findings

    # ------------------------------------------------------------------
    def _check_hot_function(self, ctx: FileContext, fn, findings) -> None:
        enabled_locals: Set[str] = set()

        def scan_expr(node: ast.AST) -> None:
            stack = [node]
            while stack:
                n = stack.pop()
                if isinstance(n, FUNC_NODES + (ast.Lambda,)):
                    continue
                if isinstance(n, ast.Call):
                    desc = _sync_call(n)
                    if desc:
                        findings.append(Finding(
                            self.id, ctx.rel, n.lineno, n.col_offset,
                            f"suspected device sync {desc} in hot path "
                            f"{fn.name!r} (reachable with telemetry "
                            f"disabled) — defer the fetch or gate it on "
                            f"registry.enabled (PR 3/7)",
                            end_line=n.end_lineno or n.lineno))
                stack.extend(ast.iter_child_nodes(n))

        def walk(stmts: Sequence[ast.stmt], exempt: bool) -> None:
            rest_exempt = exempt
            for stmt in stmts:
                if isinstance(stmt, FUNC_NODES):
                    continue
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and _enabled_expr(stmt.value, enabled_locals):
                    enabled_locals.add(stmt.targets[0].id)
                if isinstance(stmt, ast.If):
                    if not rest_exempt:
                        scan_expr(stmt.test)
                    if _not_enabled_test(stmt.test, enabled_locals):
                        # body = the telemetry-DISABLED path: checked
                        walk(stmt.body, rest_exempt)
                        walk(stmt.orelse, True)
                        if terminates(stmt.body):
                            rest_exempt = True   # early-out guard
                    elif _enabled_expr(stmt.test, enabled_locals):
                        walk(stmt.body, True)    # enabled-only branch
                        walk(stmt.orelse, rest_exempt)
                    else:
                        walk(stmt.body, rest_exempt)
                        walk(stmt.orelse, rest_exempt)
                    continue
                # non-If compound statements: scan headers, recurse bodies
                if not rest_exempt:
                    for field in ("value", "test", "iter", "items",
                                  "exc", "cause", "targets", "target"):
                        sub = getattr(stmt, field, None)
                        if isinstance(sub, ast.AST):
                            scan_expr(sub)
                        elif isinstance(sub, list):
                            for s in sub:
                                if isinstance(s, ast.AST):
                                    scan_expr(s)
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if isinstance(sub, list) and sub \
                            and isinstance(sub[0], ast.stmt):
                        walk(sub, rest_exempt)
                if isinstance(stmt, ast.Try):
                    for h in stmt.handlers:
                        walk(h.body, rest_exempt)

        walk(fn.body, False)


register_rule(SyncFreeHotPathRule())


# --- selftest fixtures -----------------------------------------------------
SELFTEST_BAD = '''\
import numpy as np


class Engine:
    def _decode_block(self):   # dslint: hot
        toks = self._dispatch()
        if not self.registry.enabled:
            # disabled-telemetry branch paying a device sync  <- BAD
            self._last = float(toks.sum())
        vals = np.asarray(toks)                              # <- BAD
        return vals
'''

SELFTEST_GOOD = '''\
import time


class Engine:
    def _decode_block(self):   # dslint: hot
        toks = self._dispatch()
        if self.registry.enabled:
            self._m.record(float(toks.sum()))   # enabled-only: exempt
        t0 = float(time.perf_counter())         # wall clock: benign
        metered = self.registry.enabled
        if metered:
            self._m.record(float(toks[0]))      # enabled local: exempt
        if not self.registry.enabled:
            return toks
        return float(toks.sum())                # post-guard: enabled-only
'''
