"""DSL001 — donation safety.

Originating incidents: PR 2 (make_array_from_callback shim), PR 4
(test_offload NaN'd with a warm /tmp/dstpu_xla_cache), PR 10 (offload
relay).  On the CPU runtime ``jax.device_put`` zero-copies aligned host
numpy arrays, so the returned Array ALIASES the caller's buffer — and
donating that alias into a persistent-cache-DESERIALIZED executable
corrupts it.  Every device_put whose result can reach a
``donate_argnums`` callee must route through an owned-copy seam
(``_owned_device_put`` / a compiled producer whose output is
runtime-owned).

Static approximation (per function scope):

- *donated callables*: names/attributes assigned from ``jax.jit(...,
  donate_argnums=...)`` and functions decorated with
  ``functools.partial(jax.jit, donate_argnums=...)`` — the donated
  argument positions are recorded;
- *tainted values*: results of raw ``device_put`` /
  ``make_array_from_callback`` calls (owned seams exempt), propagated
  through simple assignment and ``list.append``;
- *sinks*: a tainted value (or inline raw put) passed at a donated
  position, or — in any file that compiles donated callables — fed into a
  ``params=`` keyword of a ``_replace``/``TrainState`` call, because the
  engine's train states are what the donated accum/apply fns consume next
  dispatch.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .astutil import (FUNC_NODES, contains, dotted, functions, int_tuple,
                      keyword, tail_name)
from .engine import FileContext, Finding, Project, Rule, register_rule

RAW_PUTS = {"device_put", "make_array_from_callback"}
# seams whose OUTPUT is runtime-owned (compiled copy / compiled dequant):
# a call whose dotted name mentions one of these is never a raw put, even
# if a segment collides with RAW_PUTS (e.g. ``seams.device_put`` renamed)
OWNED_SEAMS = {"_owned_device_put", "_owned_device_put_tree", "_owned_copy",
               "_dequant_put"}
STATE_SINK_CALLEES = {"_replace", "TrainState"}
STATE_SINK_KEYWORDS = {"params", "opt_state", "grad_acc"}


def _is_jit_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and tail_name(node.func) in ("jit", "pjit"))


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    kw = keyword(call, "donate_argnums")
    if kw is None:
        return None
    return int_tuple(kw)


def _jit_with_donate(node: ast.AST) -> Optional[Tuple[int, ...]]:
    """donate positions when ``node`` is ``jax.jit(..., donate_argnums=)``
    or ``functools.partial(jax.jit, donate_argnums=)``."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_call(node):
        return _donated_positions(node)
    if tail_name(node.func) == "partial" and node.args \
            and tail_name(node.args[0]) in ("jit", "pjit"):
        return _donated_positions(node)
    return None


def _collect_donated(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """callee key (bare name or attribute name) -> donated positions."""
    donated: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            pos = _jit_with_donate(node.value)
            if pos:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    donated[t.id] = pos
                elif isinstance(t, ast.Attribute):
                    donated[t.attr] = pos
        elif isinstance(node, FUNC_NODES):
            for dec in node.decorator_list:
                pos = _jit_with_donate(dec)
                if pos:
                    donated[node.name] = pos
    return donated


def _raw_put_call(node: ast.AST) -> Optional[ast.Call]:
    """The node itself, when it is a raw (un-owned) put call."""
    if isinstance(node, ast.Call) and tail_name(node.func) in RAW_PUTS:
        name = dotted(node.func) or ""
        if any(seam in name.split(".") for seam in OWNED_SEAMS):
            return None
        return node
    return None


def _expr_taints(node: ast.AST, tainted: Set[str]) -> bool:
    """Whether evaluating ``node`` can yield a raw-put-aliased value."""
    for sub in ast.walk(node):
        if _raw_put_call(sub) is not None:
            return True
        if isinstance(sub, ast.Name) and sub.id in tainted \
                and isinstance(sub.ctx, ast.Load):
            return True
    return False


class DonationSafetyRule(Rule):
    id = "DSL001"
    title = "donation safety: raw device_put must not reach donated callees"
    incident = ("PR 2/4/10 — donating a zero-copy numpy-aliased device_put "
                "result into a cache-deserialized executable corrupts it "
                "(offload train went NaN with a warm XLA cache)")

    def check_file(self, ctx: FileContext,
                   project: Project) -> Iterable[Finding]:
        donated = _collect_donated(ctx.tree)
        findings: List[Finding] = []
        has_donated = bool(donated) or contains(
            ctx.tree, lambda n: isinstance(n, ast.keyword)
            and n.arg == "donate_argnums")
        for fn in list(functions(ctx.tree)) + [ctx.tree]:
            body = fn.body if hasattr(fn, "body") else []
            if fn is ctx.tree:
                body = ctx.tree.body
            findings.extend(self._check_scope(ctx, body, donated,
                                              has_donated))
        return findings

    # ------------------------------------------------------------------
    def _check_scope(self, ctx: FileContext, body, donated,
                     has_donated) -> List[Finding]:
        tainted: Set[str] = set()
        findings: List[Finding] = []

        def visit_stmt(stmt: ast.stmt) -> None:
            # taint bookkeeping first (flow order within the scope)
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                if _expr_taints(stmt.value, tainted):
                    tainted.add(stmt.targets[0].id)
                else:
                    tainted.discard(stmt.targets[0].id)
            elif isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                           ast.Call):
                call = stmt.value
                # list.append(tainted) taints the list
                if tail_name(call.func) == "append" \
                        and isinstance(call.func, ast.Attribute) \
                        and isinstance(call.func.value, ast.Name) \
                        and call.args \
                        and _expr_taints(call.args[0], tainted):
                    tainted.add(call.func.value.id)
            # sink scan on every expression in the statement
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    self._check_call(ctx, node, donated, has_donated,
                                     tainted, findings)
            # recurse into compound statements (NOT nested defs: their
            # scope is checked separately, without this scope's taints)
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.stmt) \
                        and not isinstance(child, FUNC_NODES):
                    visit_stmt(child)

        for stmt in body:
            if not isinstance(stmt, FUNC_NODES):
                visit_stmt(stmt)
        return findings

    def _check_call(self, ctx, call, donated, has_donated, tainted,
                    findings) -> None:
        key = tail_name(call.func)
        pos = donated.get(key)
        if pos:
            for p in pos:
                if p < len(call.args) and _expr_taints(call.args[p],
                                                       tainted):
                    findings.append(Finding(
                        self.id, ctx.rel, call.lineno, call.col_offset,
                        f"raw device_put result reaches donated arg {p} of "
                        f"{key!r} — route through _owned_device_put (or a "
                        f"compiled producer); donating a numpy-aliased "
                        f"buffer into a cache-deserialized executable "
                        f"corrupts it (PR 2/4/10)",
                        end_line=call.end_lineno or call.lineno))
        if has_donated and key in STATE_SINK_CALLEES:
            for kw in call.keywords:
                if kw.arg in STATE_SINK_KEYWORDS \
                        and _expr_taints(kw.value, tainted):
                    findings.append(Finding(
                        self.id, ctx.rel, call.lineno, call.col_offset,
                        f"raw device_put result stored into "
                        f"{key}({kw.arg}=...) — this state is donated into "
                        f"the compiled accum/apply path next dispatch; "
                        f"route through _owned_device_put (PR 2/4/10)",
                        end_line=call.end_lineno or call.lineno))


register_rule(DonationSafetyRule())


# --- selftest fixtures -----------------------------------------------------
SELFTEST_BAD = '''\
import functools
import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def accum(state, batch):
    return state + batch


def step(state, host_grads, shardings):
    g = jax.device_put(host_grads, shardings)      # numpy-aliased on CPU
    return accum(g, 1.0)                           # donated arg 0  <- BAD
'''

SELFTEST_GOOD = '''\
import functools
import jax

from engine_seams import _owned_device_put


@functools.partial(jax.jit, donate_argnums=(0,))
def accum(state, batch):
    return state + batch


def step(state, host_grads, shardings):
    g = _owned_device_put(host_grads, shardings)   # runtime-owned copy
    extra = jax.device_put(host_grads, shardings)  # non-donated position
    return accum(g, extra)
'''
