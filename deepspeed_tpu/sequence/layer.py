"""Sequence parallelism: Ulysses all-to-all attention and ring attention.

Reference: ``deepspeed/sequence/layer.py`` ``DistributedAttention`` (SURVEY.md
§2.1, §5.7) — input sharded on the sequence dim across the SP group,
all-to-all re-shards seq↔head around the core attention so each rank computes
full-sequence attention for ``H/P`` heads.  Here that is a ``shard_map`` over
the mesh's ``sp`` axis with ``jax.lax.all_to_all`` (which rides ICI directly).

**Ring attention** (``ring_attention``) is the TPU-idiomatic extension beyond
the reference's capability (SURVEY.md §5.7 plan): KV chunks rotate around the
``sp`` axis via ``ppermute`` while each rank accumulates blockwise-softmax
partial results for its resident Q chunk — memory O(S/P), comm overlapped
with compute, no head-count divisibility requirement.  Implemented as a
``lax.scan`` over ring steps (differentiable; the backward re-runs the ring).

Both entry points take globally-shaped [B, H, S, D] arrays and shard
internally, so they drop into any attention call site.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm import collectives_q as cq
from deepspeed_tpu.comm.mesh import axis_size, data_axes

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Ulysses
# ---------------------------------------------------------------------------

def ulysses_attention(q, k, v, mesh: Mesh, attn_fn: Optional[Callable] = None,
                      causal: bool = True, axis: str = "sp"):
    """All-to-all seq↔head reshard around full-sequence attention.

    q: [B, H, S, D]; k/v: [B, Hkv, S, D] with Hkv == H (repeat GQA heads
    before calling).  Requires H % sp == 0 and S % sp == 0.
    """
    if attn_fn is None:
        # flash kernel on TPU for lane-aligned sequences (mirrors
        # attention_core's s % 128 gate — unaligned tiles stay on the jnp
        # reference); resolve_impl falls back to the reference on CPU anyway
        from deepspeed_tpu.ops.pallas import flash_attention, mha_reference
        if q.shape[2] % 128 == 0:
            attn_fn = functools.partial(flash_attention, causal=causal)
        else:
            attn_fn = functools.partial(mha_reference, causal=causal)
    sp = axis_size(mesh, axis)
    if sp == 1:
        return attn_fn(q, k, v)
    batch_ax = data_axes(mesh)
    spec = P(batch_ax, "tp", axis, None)   # seq-sharded on entry/exit

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _inner(ql, kl, vl):
        # [B, h, S/P, D] -> all-to-all -> [B, h/P, S, D]   (h = H/tp)
        def scatter_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def gather_heads(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        o = attn_fn(scatter_heads(ql), scatter_heads(kl), scatter_heads(vl))
        return gather_heads(o)

    return _inner(q, k, v)


class DistributedAttention:
    """Reference-parity wrapper (``deepspeed.sequence.layer.DistributedAttention``).

    ``local_attention(q, k, v) -> out`` computes attention on full sequences;
    this class re-shards seq↔head around it over the sequence-parallel axis.
    scatter_idx/gather_idx are accepted for signature parity (the jax
    implementation always scatters heads / gathers sequence).
    """

    def __init__(self, local_attention: Callable, mesh: Mesh,
                 scatter_idx: int = 2, gather_idx: int = 0, axis: str = "sp"):
        self.local_attn = local_attention
        self.mesh = mesh
        self.axis = axis

    def __call__(self, query, key, value, *args, **kwargs):
        return ulysses_attention(query, key, value, self.mesh,
                                 attn_fn=self.local_attn, axis=self.axis)


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------

def _block_attend(q, k, v, q_pos, k_pos, scale, causal):
    """Blockwise attention partials for online-softmax accumulation.

    q: [B, H, Sq, D]; k/v: [B, H, Sk, D].  Returns (m [B,H,Sq], l [B,H,Sq],
    acc [B,H,Sq,D]) — fp32 running max / sum / weighted values.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    # fully-masked rows have m == NEG_INF and s - m == 0; zero them explicitly
    p = jnp.where(s <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q, k, v, mesh: Mesh, causal: bool = True,
                   sm_scale: Optional[float] = None, axis: str = "sp",
                   quantized: bool = False, quant_block: int = 256):
    """Blockwise ring attention over the ``sp`` axis (ppermute KV rotation).

    q/k/v: [B, H, S, D] globally; sharded on S internally.  Each ring step
    attends the resident Q chunk to the visiting KV chunk and folds the
    result into an online-softmax accumulator; KV then rotates to the next
    neighbor.  Memory is O(S/P) per chip **including backward**: a custom
    VJP re-runs the ring instead of letting scan save every visiting KV
    chunk (which would be O(S) again — VERDICT r2 weak #8).  Comm is
    nearest-neighbor on the ICI torus in both passes.

    ``quantized`` (``comm_quantization.sequence_ring``): the KV chunk is
    quantized ONCE into blockwise int8 + fp32 scales before the ring and
    the *codes* rotate (``collectives_q.q_ppermute``) — every hop moves
    ~1/4 the fp32 bytes, with ONE quantization error total (not one per
    hop; the carried codes never re-quantize).  Compute dequantizes the
    visiting chunk per step.  The backward's dK/dV partial sums stay
    dense: they are running accumulations, and requantizing a running sum
    per hop WOULD compound error.
    """
    B, H, S, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (D ** 0.5)
    sp = axis_size(mesh, axis)
    if sp == 1:
        from deepspeed_tpu.ops.pallas import mha_reference
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    batch_ax = data_axes(mesh)
    spec = P(batch_ax, "tp", axis, None)
    chunk = S // sp

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    def _inner(ql, kl, vl):
        return _ring_local(ql, kl, vl, axis, sp, chunk, scale, causal,
                           bool(quantized), int(quant_block))

    return _inner(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_local(ql, kl, vl, axis, sp, chunk, scale, causal, quantized,
                block):
    out, _ = _ring_fwd(ql, kl, vl, axis, sp, chunk, scale, causal,
                       quantized, block)
    return out


def _kv_carry(kl, vl, quantized, block):
    """(carry, dequant) pair: the scan-carried transport form of the
    visiting KV chunk and the per-step stage recovering compute values."""
    if not quantized:
        return (kl, vl), lambda c: (c[0], c[1])
    kc = cq.quantize_carry(kl, block)
    vc = cq.quantize_carry(vl, block)

    def deq(c):
        return (cq.dequantize_carry(c[0], kl.shape, kl.dtype),
                cq.dequantize_carry(c[1], vl.shape, vl.dtype))

    return (kc, vc), deq


def _rotate_kv(carry_kv, axis, perm, quantized, kl, vl):
    if quantized:
        kc = cq.q_ppermute(carry_kv[0], axis, perm, dense_like=kl)
        vc = cq.q_ppermute(carry_kv[1], axis, perm, dense_like=vl)
        return (kc, vc)
    return (jax.lax.ppermute(carry_kv[0], axis, perm),
            jax.lax.ppermute(carry_kv[1], axis, perm))


def _ring_fwd(ql, kl, vl, axis, sp, chunk, scale, causal, quantized, block):
    my = jax.lax.axis_index(axis)
    q_pos = my * chunk + jnp.arange(chunk)
    m0 = jnp.full(ql.shape[:3], NEG_INF, jnp.float32)
    l0 = jnp.zeros(ql.shape[:3], jnp.float32)
    a0 = jnp.zeros(ql.shape, jnp.float32)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    kv0, deq = _kv_carry(kl, vl, quantized, block)

    def step(carry, t):
        kv, m, l, acc = carry
        kc, vc = deq(kv)
        # KV chunk visiting at step t started at rank (my - t) mod sp
        src = jnp.mod(my - t, sp)
        k_pos = src * chunk + jnp.arange(chunk)
        bm, bl, bacc = _block_attend(ql, kc, vc, q_pos, k_pos, scale, causal)
        mn = jnp.maximum(m, bm)
        c_old = jnp.exp(m - mn)
        c_new = jnp.exp(bm - mn)
        l = l * c_old + bl * c_new
        acc = acc * c_old[..., None] + bacc * c_new[..., None]
        kv = _rotate_kv(kv, axis, perm, quantized, kl, vl)
        return (kv, mn, l, acc), None

    (_, m, l, acc), _ = jax.lax.scan(step, (kv0, m0, l0, a0),
                                     jnp.arange(sp))
    safe_l = jnp.maximum(l, 1e-30)
    out = (acc / safe_l[..., None]).astype(ql.dtype)
    lse = m + jnp.log(safe_l)                       # [B, H, Sq]
    return out, (ql, kl, vl, out, lse)


def _ring_local_fwd(ql, kl, vl, axis, sp, chunk, scale, causal, quantized,
                    block):
    out, res = _ring_fwd(ql, kl, vl, axis, sp, chunk, scale, causal,
                         quantized, block)
    return out, res


def _ring_local_bwd(axis, sp, chunk, scale, causal, quantized, block, res,
                    g):
    """Second ring pass: dK/dV partials travel with their KV chunk and are
    complete when the chunk arrives back home after sp rotations.  Under
    ``quantized`` the visiting KV chunk rotates as codes (matching the
    forward's bytes AND its numerics — the backward must see the same
    dequantized values the forward attended to); the dK/dV running sums
    rotate dense on purpose (requantizing an accumulation per hop would
    compound error)."""
    ql, kl, vl, out, lse = res
    my = jax.lax.axis_index(axis)
    q_pos = my * chunk + jnp.arange(chunk)
    g32 = g.astype(jnp.float32)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)  # [B, H, Sq]
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    dq0 = jnp.zeros(ql.shape, jnp.float32)
    dk0 = jnp.zeros(kl.shape, jnp.float32)
    dv0 = jnp.zeros(vl.shape, jnp.float32)
    kv0, deq = _kv_carry(kl, vl, quantized, block)

    def step(carry, t):
        kv, dkc, dvc, dq = carry
        kc, vc = deq(kv)
        src = jnp.mod(my - t, sp)
        k_pos = src * chunk + jnp.arange(chunk)
        s = jnp.einsum("bhqd,bhkd->bhqk", ql.astype(jnp.float32),
                       kc.astype(jnp.float32)) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        p = jnp.exp(s - lse[..., None])             # [B, H, Sq, Sk]
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        dvc = dvc + jnp.einsum("bhqk,bhqd->bhkd", p, g32)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, vc.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kc.astype(jnp.float32))
        dkc = dkc + jnp.einsum("bhqk,bhqd->bhkd", ds, ql.astype(jnp.float32))
        kv = _rotate_kv(kv, axis, perm, quantized, kl, vl)
        dkc = jax.lax.ppermute(dkc, axis, perm)
        dvc = jax.lax.ppermute(dvc, axis, perm)
        return (kv, dkc, dvc, dq), None

    (_, dk, dv, dq), _ = jax.lax.scan(step, (kv0, dk0, dv0, dq0),
                                      jnp.arange(sp))
    return dq.astype(ql.dtype), dk.astype(kl.dtype), dv.astype(vl.dtype)


_ring_local.defvjp(_ring_local_fwd, _ring_local_bwd)
