"""Sequence parallelism (reference: ``deepspeed/sequence/``) + ring attention."""

from deepspeed_tpu.sequence.layer import (DistributedAttention, ring_attention,
                                          ulysses_attention)

__all__ = ["DistributedAttention", "ring_attention", "ulysses_attention"]
