"""Headline benchmark: GPT-2 125M-class causal-LM training throughput on one
chip (BASELINE.json configs[1] rung; north star = tokens/sec/chip, BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec", "vs_baseline": N}

``vs_baseline`` is achieved MFU / 0.40 — the north-star target is matching
A100 ZeRO-3 MFU (~40%) on the same workload class (BASELINE.md).

Timing note: the device is reached through a tunnel where
``jax.block_until_ready`` can return before remote execution completes, so the
loop is timed against a host fetch of a scalar (forces completion) and the
measured fixed fetch round-trip is subtracted.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm

PEAK_FLOPS = {  # bf16 peak per chip
    "tpu v5 lite": 197e12, "tpu v5e": 197e12, "tpu v5": 459e12,
    "tpu v4": 275e12, "tpu v6 lite": 918e12, "cpu": 1e12,
}


def peak_flops() -> float:
    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu").lower()
    for k, v in PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return 197e12


def sync(x) -> None:
    """Barrier that provably waits: fetch a scalar derived from x."""
    float(jax.tree.leaves(x)[0].sum())


def main():
    on_tpu = jax.default_backend() != "cpu"
    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)

    if on_tpu:
        # micro-batch 16 saturates the chip; accumulation to 128 amortizes the
        # optimizer step.  Vocab padded 50257 -> 50304 (multiple of 128) for
        # MXU tiling — standard practice (Megatron/DeepSpeed GPT-2 runs pad
        # the same way).
        micro, accum, seq, steps, warmup = 16, 8, 1024, 12, 3
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304)
    else:  # dev smoke path
        micro, accum, seq, steps, warmup = 2, 1, 256, 3, 1
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2, hidden_size=128,
                          intermediate_size=512, num_heads=4, vocab_size=2048)
    batch = micro * accum
    cfg = model.config

    ds_config = {
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": accum,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "AdamW", "params": {"lr": 6e-4, "weight_decay": 0.1}},
        "gradient_clipping": 1.0,
        # "mlp_dots": attention residuals persist (the flash kernel never
        # re-runs in backward) while the MLP half remats with matmul outputs
        # saved — measured the fastest policy on v5e at this size.
        "activation_checkpointing": {"enabled": True, "policy": "mlp_dots"},
        # model profile printed once during warmup (XLA cost analysis)
        "flops_profiler": {"enabled": True, "profile_step": 2},
        "steps_per_print": 10**9,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config, mesh=mesh)

    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (accum, micro, seq), 0, cfg.vocab_size)
    batch_data = (tokens, tokens)  # stacked [gas, micro, seq] for train_step

    # measure the fixed host-fetch round-trip to subtract from the loop
    tiny = jax.jit(lambda a: a + 1)
    z = jnp.ones((8, 8))
    sync(tiny(z))
    t0 = time.perf_counter()
    sync(tiny(z))
    overhead = time.perf_counter() - t0

    def one_step():
        # fused path: ONE dispatch for the whole step (scan over microbatches
        # + update in a single XLA program)
        engine.train_step(batch_data)

    for _ in range(warmup):
        one_step()
    sync(engine.state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        one_step()
    sync(engine.state.params)
    # Raw wall time (conservative); the measured fetch round-trip is reported
    # separately in detail for comparison.
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tps = steps * tokens_per_step / dt
    n_params = sum(x.size for x in jax.tree.leaves(engine.state.params))
    # fwd+bwd FLOPs/token: 6N matmul + 12*L*D*S attention (causal halves it).
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq
    mfu = tps * flops_per_token / peak_flops()
    print(json.dumps({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
        "detail": {"mfu": round(mfu, 4), "params_m": round(n_params / 1e6, 2),
                   "batch": batch, "micro_batch": micro, "grad_accum": accum,
                   "seq": seq, "steps": steps,
                   "step_ms": round(1e3 * dt / steps, 2),
                   "fetch_overhead_ms": round(1e3 * overhead, 2),
                   "flops_model": "6N + 6*L*D*S per token (dense causal; "
                                  "remat recompute not counted)",
                   "backend": jax.default_backend(),
                   "device": getattr(jax.devices()[0], "device_kind", "?")},
    }))


if __name__ == "__main__":
    main()
