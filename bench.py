"""Headline benchmark: GPT-2 125M-class causal-LM training throughput on one
chip (BASELINE.json configs[1] rung; north star = tokens/sec/chip, BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec", "vs_baseline": N}

``vs_baseline`` is achieved MFU / 0.40 — the north-star target is matching
A100 ZeRO-3 MFU (~40%) on the same workload class (BASELINE.md).

Timing note: the device is reached through a tunnel where
``jax.block_until_ready`` can return before remote execution completes, so the
loop is timed against a host fetch of a scalar (forces completion) and the
measured fixed fetch round-trip is subtracted.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import build_mesh, set_global_mesh
from deepspeed_tpu.models import causal_lm
# one table for the bench headline and the live ds_train_mfu gauge
from deepspeed_tpu.profiling.flops import PEAK_FLOPS, peak_flops  # noqa: F401


def collect_train_metrics(registry) -> dict:
    """Training-health sub-object for the BENCH_JSON record (the serving
    record's ``metrics`` analog): achieved tflops/mfu gauges, peak HBM, and
    the top-3 collectives by attributed time from the ``ds_comm_*`` series."""
    snap = registry.snapshot()
    out = {}
    if snap.get("ds_train_tflops"):
        out["tflops"] = snap["ds_train_tflops"]
    if snap.get("ds_train_mfu"):
        out["mfu"] = snap["ds_train_mfu"]
    if snap.get("ds_mem_peak_bytes"):
        out["peak_hbm_gb"] = round(snap["ds_mem_peak_bytes"] / 1e9, 3)
    colls = []
    for name, v in snap.items():
        if not (name.startswith("ds_comm_") and name.endswith("_seconds")):
            continue
        if name.endswith("_device_seconds"):
            continue        # device truth rides in the device_profile record
        if not isinstance(v, dict) or not v.get("count"):
            continue
        op = name[len("ds_comm_"): -len("_seconds")]
        byt = snap.get(f"ds_comm_{op}_bytes_total", 0)
        if isinstance(byt, dict):               # {dtype=} labeled family
            byt = sum(b for b in byt.values() if isinstance(b, (int, float)))
        colls.append({"op": op, "time_s": round(v["sum"], 4),
                      "calls": v["count"], "bytes": int(byt)})
    colls.sort(key=lambda c: -c["time_s"])
    if colls:
        out["top_collectives"] = colls[:3]
    return out


def sync(x) -> None:
    """Barrier that provably waits: fetch a scalar derived from x."""
    float(jax.tree.leaves(x)[0].sum())


def capture_device_profile(step_fn, steps: int = 2, tag: str = "train"):
    """Windowed perfetto capture around ``steps`` calls of ``step_fn``,
    post-processed into the compact device-profile record the bench
    attaches to its ``metrics`` sub-object (PR 3/4 pattern): per-step
    phase breakdown (``ds_profile_*`` semantics), gap share, top device
    collectives, serving dispatch slack.  Returns None when this jax
    cannot write the perfetto export; a failed analysis returns a status
    record instead of killing the bench."""
    from deepspeed_tpu.profiling.trace import TraceCapture, perfetto_supported

    if not perfetto_supported():
        return None
    import tempfile

    from deepspeed_tpu.profiling import device_trace as dtr

    d = tempfile.mkdtemp(prefix=f"ds_bench_trace_{tag}_")
    cap = TraceCapture(d, start_step=1, num_steps=steps, perfetto=True)
    try:
        cap.maybe_start(1)
        for i in range(1, steps + 1):
            step_fn()
            cap.after_step(i)
        cap.close()
        s = dtr.summarize_trace(d, steps=steps)
    except Exception as exc:
        return {"status": f"failed: {type(exc).__name__}: {str(exc)[:120]}"}
    finally:
        cap.close()   # a mid-window raise must release the one global
                      # profiler session or every later capture 409s
    per = s.get("per_step") or s["phases"]
    out = {"steps": steps, "window_s": round(s["window_s"], 6),
           "degraded": s["degraded"],
           "per_step": {k: round(v, 6) for k, v in per.items()},
           "trace_dir": d}
    if s["window_s"] > 0:
        out["gap_share"] = round(s["phases"]["gap_s"] / s["window_s"], 4)
    top = sorted(s.get("comm_device", {}).items(),
                 key=lambda kv: -kv[1]["seconds"])[:3]
    if top:
        out["top_device_collectives"] = [
            {"op": op, "device_s": round(rec["seconds"], 6),
             "spans": rec["count"]} for op, rec in top]
    if s.get("serve"):
        out["serve"] = {k: (round(v, 6) if isinstance(v, float) else v)
                        for k, v in s["serve"].items()}
    return out


def goodput_window(before: dict, after: dict, loop_s: float,
                   tokens_expected: int) -> dict:
    """Delta of two goodput-ledger snapshots bracketing a measured loop
    -> the BENCH_JSON ``goodput`` block.  The ledger wall includes the
    snapshot + final device-sync bookends around the timed loop, so the
    ledger tokens/s agrees with the headline within ~10% (documented
    tolerance) while the token COUNT reconciles exactly — both sides
    count gas*micro*seq per fused step."""
    from deepspeed_tpu.monitor import goodput_core

    cats = {k: after["categories"][k] - before["categories"].get(k, 0.0)
            for k in after["categories"]}
    wall = after["wall_s"] - before["wall_s"]
    toks = after["tokens"] - before["tokens"]
    good = sum(cats[c] for c in goodput_core.GOOD_CATEGORIES)
    return {"wall_s": round(wall, 6),
            "loop_s": round(loop_s, 6),
            "goodput_ratio": round(good / wall, 4) if wall > 0 else 0.0,
            "telescopes": goodput_core.telescopes(
                {"wall_s": wall, "categories": cats}),
            "categories": {k: round(v, 6) for k, v in cats.items()
                           if abs(v) > 1e-9},
            "tokens": toks, "tokens_expected": tokens_expected,
            "tokens_reconcile": toks == tokens_expected,
            "tokens_per_sec": round(toks / wall, 1) if wall > 0 else 0.0}


def bench_8b_rung(budget_s: float = 900.0, int8: bool = True,
                  prefetch: bool = True):
    """Llama-3-8B single-chip rung (BASELINE configs[2] / VERDICT r3 item 1).

    8B bf16 params (16.1GB) exceed the 15.75GB v5e HBM, so this exercises
    the ZeRO-Infinity STREAMED path (runtime/zero/stream_grad.py): weights
    live as host numpy, each layer's params H2D-stream per segment, and
    each layer's grads D2H-stream into host accumulators — no [model]-sized
    buffer (params OR grads) ever exists on device, which is also why the
    whole-program form cannot even compile here (a 16GB grad output cannot
    be placed).  Measured: fwd+bwd tokens/sec per chip, bounded on this
    runner by the relay's host<->device bandwidth — which the ISSUE 11
    streaming layer attacks: ``int8`` ships each layer as blockwise int8 +
    scales with a fused on-device dequant (~2x fewer relay bytes than
    bf16), ``prefetch`` double-buffers layer i+1's transfer under layer
    i's compute.  The record carries the effective relay MB/s (relay
    bytes / step wall, honest on a relay-bound rung) next to the
    BENCH_r05 14MB/s baseline.  The full CPU-Adam step is not timed: fp32
    master+moments for 8B are 96GB on top of the streaming buffers.
    """
    import numpy as np
    import ml_dtypes
    from jax.sharding import PartitionSpec as P

    t_start = time.perf_counter()
    try:
        from deepspeed_tpu.models import causal_lm
        from deepspeed_tpu.monitor.metrics import get_registry
        from deepspeed_tpu.runtime.zero.partition import (params_pspecs,
                                                          shardings_from_pspecs)
        from deepspeed_tpu.runtime.zero.stream_grad import StreamedFwdBwd

        mesh = build_mesh(devices=jax.devices()[:1])
        set_global_mesh(mesh)
        model = causal_lm("llama3-8b", mesh=mesh, remat=True)
        cfg = model.config
        micro, seq = 1, 1024

        # init on HOST, leaf by leaf (a device init would need 32GB fp32)
        rng = np.random.default_rng(0)
        abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.int32))
        def host_init(s):
            scale = 0.02 if len(s.shape) <= 2 else s.shape[-1] ** -0.5
            arr = (rng.standard_normal(s.shape, dtype=np.float32) * scale)
            return arr.astype(ml_dtypes.bfloat16)
        params_np = jax.tree.map(host_init, abstract)
        n_params = sum(int(x.size) for x in jax.tree.leaves(params_np))

        specs = params_pspecs(params_np, mesh, shard=False)
        seg = model.stream_segments()
        sfb = StreamedFwdBwd.from_param_specs(seg, specs, mesh, gas=1,
                                              use_dropout=False,
                                              int8=int8, prefetch=prefetch)
        # bf16 host accumulators (fp32 would be 32GB on top of the params)
        acc = jax.tree.map(lambda a: np.zeros(a.shape, ml_dtypes.bfloat16),
                           params_np)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (micro, seq), 0,
                                    cfg.vocab_size)
        key = jax.random.PRNGKey(2)
        registry = get_registry()
        was_enabled = registry.enabled
        registry.enable()
        try:
            loss = sfb.run(params_np, tokens, tokens, None, key, acc)
            loss0 = float(loss)           # compile + first step
            registry.reset()
            steps = 0
            t0 = time.perf_counter()
            while steps < 2 and (steps == 0
                                 or time.perf_counter() - t0 < budget_s):
                loss = sfb.run(params_np, tokens, tokens, None, key, acc)
                float(loss)
                steps += 1
            wall = time.perf_counter() - t0
            dt = wall / steps
            snap = registry.snapshot()
        finally:
            # a raise must not leave the process-global registry hot (the
            # 125M headline and later rungs run in this process)
            if not was_enabled:
                registry.disable()
        relay = snap.get("ds_offload_relay_bytes_total", {}) or {}
        h2d = relay.get('{dir="h2d"}', 0)
        d2h = relay.get('{dir="d2h"}', 0)
        tps = micro * seq / dt
        fpt = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq
        return {"status": "ok", "tokens_per_sec_fwd_bwd": round(tps, 2),
                "params_b": round(n_params / 1e9, 3),
                "micro_batch": micro, "seq": seq, "steps": steps,
                "step_ms": round(dt * 1e3, 1), "loss": round(loss0, 3),
                "mfu_fwd_bwd": round(tps * fpt / peak_flops(), 4),
                "int8_relay": bool(int8), "prefetch": bool(prefetch),
                "relay": {
                    "h2d_bytes_per_step": int(h2d / steps),
                    "d2h_bytes_per_step": int(d2h / steps),
                    "effective_MBps": round((h2d + d2h) / wall / 1e6, 2),
                    "prefetch_hits": int(snap.get(
                        "ds_offload_prefetch_hits_total", 0)),
                },
                "baseline_r05": {"tokens_per_sec_fwd_bwd": 0.31,
                                 "relay_MBps": 14.0,
                                 "note": "bf16 relay, 2026-07-30, same "
                                         "runner class"},
                "speedup_vs_r05": round(tps / 0.31, 2),
                "note": ("ZeRO-Infinity streamed fwd+bwd: host-resident "
                         "params stream per layer H2D, grads stream per "
                         "layer D2H into host accumulators; bounded by the "
                         "relay's host<->device bandwidth on this runner. "
                         "Optimizer step not timed: 96GB fp32 Adam states "
                         "(int8_masters would cut that to ~24GB)")}
    except Exception as exc:  # the 125M headline must still be emitted
        return {"status": f"failed: {type(exc).__name__}",
                "error": str(exc)[:200],
                "elapsed_s": round(time.perf_counter() - t_start, 1)}


def bench_streamed_rung(steps: int = 3, warmup: int = 1,
                        tiny: bool = None) -> dict:
    """Offload streaming ablation (ISSUE 11 / ROADMAP item 3): the SAME
    streamed-offload training workload with the bf16 relay vs the int8
    relay (+ int8 host masters), prefetch on both sides.

    Per side: tokens/s, relay bytes per step by direction, effective
    relay MB/s (bytes / wall — on a relay-bound rung the two are equal),
    prefetch hits, final loss.  Headlines: ``streamed_speedup`` (int8 /
    bf16 tokens/s — the acceptance number on relay-bound hardware),
    ``relay_bytes_ratio`` (bf16 / int8 H2D bytes, machine-independent),
    ``loss_parity`` vs a plain NON-offloaded engine at the same seed
    (rtol 5e-2 — int8 masters are a lossy code, the bound is the
    contract), and the device-profile ``gap_share`` on the offload path
    (``ds_profile_gap`` semantics — the overlap headroom the prefetch is
    eating).  On CPU runners the model scales to smoke size (mechanics +
    byte ratios are what the CPU row pins; absolute rates need TPU)."""
    import gc

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import get_registry

    t_start = time.perf_counter()
    try:
        on_tpu = jax.default_backend() != "cpu"
        if tiny is None:
            tiny = not on_tpu
        mesh = build_mesh(devices=jax.devices()[:1])
        set_global_mesh(mesh)
        if tiny:
            over = dict(num_layers=4, hidden_size=128, intermediate_size=256,
                        num_heads=4, num_kv_heads=4, vocab_size=512,
                        max_seq_len=128)
            micro, seq = 2, 64
        else:
            over = {}
            micro, seq = 1, 1024
        registry = get_registry()
        was_enabled = registry.enabled
        registry.enable()
        results = {}
        losses = {}
        gap_share = None
        try:
            for side in ("plain", "bf16", "int8"):
                model = causal_lm("llama-1b4", mesh=mesh, **over)
                cfg_m = model.config
                zero = {"stage": 3}
                if side != "plain":
                    zero["offload_optimizer"] = {
                        "device": "cpu", "int8_masters": side == "int8"}
                    zero["offload_param"] = {
                        "device": "cpu", "prefetch": True,
                        "int8_stream": side == "int8"}
                ds_config = {
                    "train_micro_batch_size_per_gpu": micro,
                    "gradient_accumulation_steps": 1,
                    "bf16": {"enabled": True},
                    "zero_optimization": zero,
                    "optimizer": {"type": "AdamW", "params": {"lr": 2e-4}},
                    "gradient_clipping": 1.0, "steps_per_print": 10**9}
                engine, _, _, _ = deepspeed_tpu.initialize(
                    model=model, config=ds_config, mesh=mesh,
                    rng=jax.random.PRNGKey(11))
                tokens = jax.random.randint(jax.random.PRNGKey(1),
                                            (micro, seq), 0,
                                            cfg_m.vocab_size)
                batch = (tokens, tokens)

                def one_step():
                    loss = engine.forward(batch)
                    engine.step()
                    return loss

                for _ in range(warmup):
                    one_step()
                registry.reset()
                t1 = time.perf_counter()
                loss = None
                for _ in range(steps):
                    loss = one_step()
                loss = float(loss)
                wall = time.perf_counter() - t1
                losses[side] = loss
                if side == "plain":
                    engine = model = None
                    gc.collect()
                    continue
                snap = registry.snapshot()
                relay = snap.get("ds_offload_relay_bytes_total", {}) or {}
                h2d = relay.get('{dir="h2d"}', 0)
                d2h = relay.get('{dir="d2h"}', 0)
                row = {
                    "tokens_per_sec": round(steps * micro * seq / wall, 1),
                    "step_ms": round(1e3 * wall / steps, 1),
                    "loss": round(loss, 5),
                    "h2d_bytes_per_step": int(h2d / steps),
                    "d2h_bytes_per_step": int(d2h / steps),
                    "relay_MBps": round((h2d + d2h) / wall / 1e6, 2),
                    "prefetch_hits": int(snap.get(
                        "ds_offload_prefetch_hits_total", 0)),
                    "relay_stall_s": round(
                        (snap.get("ds_offload_relay_seconds") or {}
                         ).get("sum", 0.0), 4),
                }
                if side == "int8":
                    # ds_profile_gap share on the offload path: a short
                    # device capture over the streamed step
                    dp = capture_device_profile(one_step, steps=2,
                                                tag="streamed")
                    if dp and dp.get("gap_share") is not None:
                        gap_share = dp["gap_share"]
                        row["device_profile"] = dp
                results[side] = row
                engine = model = None
                gc.collect()
        finally:
            if not was_enabled:
                registry.disable()
        bf16_b = results["bf16"]["h2d_bytes_per_step"]
        int8_b = results["int8"]["h2d_bytes_per_step"]
        plain = losses["plain"]
        parity = bool(np.isfinite(plain) and abs(losses["int8"] - plain)
                      <= 5e-2 * abs(plain))
        return {"status": "ok", "tiny": bool(tiny), "steps": steps,
                "micro_batch": micro, "seq": seq,
                "backend": jax.default_backend(),
                "bf16": results["bf16"], "int8": results["int8"],
                "loss_plain": round(plain, 5),
                "streamed_speedup": round(
                    results["int8"]["tokens_per_sec"]
                    / max(results["bf16"]["tokens_per_sec"], 1e-9), 3),
                "relay_bytes_ratio": round(bf16_b / max(int8_b, 1), 3),
                "loss_parity": parity,
                "gap_share": gap_share}
    except Exception as exc:
        return {"status": f"failed: {type(exc).__name__}",
                "error": str(exc)[:300],
                "elapsed_s": round(time.perf_counter() - t_start, 1)}


def bench_serving(num_requests: int = 64, num_slots: int = 8, qps: float = 50.0,
                  seed: int = 0, tiny: bool = False) -> dict:
    """Continuous-batching serving scenario: Poisson arrivals, mixed
    prompt/output lengths, reporting goodput tok/s and p50/p99 per-request
    latency for THREE systems replaying the identical arrival trace:

    - ``continuous`` — the PAGED ``ServingEngine`` at an HBM budget EQUAL
      to the fixed-slot layout (``kv_pool_tokens = num_slots * max_out``)
      but DOUBLE the slots: pages are allocated on demand, so the same KV
      memory admits ~2x concurrently-decoding requests, backed by LIFO
      preempt-and-requeue if the bimodal tail ever fills the pool — the
      paged-vs-fixed comparison is equal-HBM, not equal-slots;
    - ``fixed_slot`` — the PR 1 contiguous per-slot cache at ``num_slots``
      (each slot reserves the worst-case ``max_out`` whether used or not);
    - ``static`` — the static-batch ``InferenceEngine`` baseline at equal
      slot count (padded to the batch max prompt, decoded to the batch max
      output — the head-of-line + padding waste iteration-level
      scheduling removes).

    Goodput counts only the tokens each request ASKED for.  Each trace is
    warmed with TWO passes before the recorded third — grow-only cache
    reallocation drops compiled fns mid-first-pass, so one warm pass still
    leaves compiles in the record.  The ``metrics`` sub-object carries the
    paged engine's lifecycle histograms plus {kv_util, preemptions, pages}
    so the goodput delta lands with its memory attribution.
    """
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    rng = np.random.default_rng(seed)
    if tiny:  # CPU smoke scale (tests/perf/test_serving_bench.py)
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2,
                          hidden_size=128, intermediate_size=256, num_heads=4,
                          vocab_size=512)
        max_out, p_lo, p_hi, n_short, n_long = 64, 4, 24, (4, 12), (24, 32)
    else:
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304)
        max_out, p_lo, p_hi = 1024, 16, 256
        n_short, n_long = (16, 96), (192, 256)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    V = model.config.vocab_size

    prompts = [rng.integers(0, V, size=int(n)).astype(np.int32)
               for n in rng.integers(p_lo, p_hi + 1, size=num_requests)]
    # bimodal output lengths (chat-like: mostly short answers, a heavy
    # long tail) — the head-of-line + padding regime static batching pays
    # for and iteration-level scheduling does not; ALSO the regime where
    # fixed per-slot reservations are mostly dead weight (a 30-token reply
    # pins the same KV as a 2k one), which is the paged pool's win
    long_mask = rng.random(num_requests) < 0.25
    news = np.where(long_mask,
                    rng.integers(n_long[0], n_long[1] + 1, num_requests),
                    rng.integers(n_short[0], n_short[1] + 1,
                                 num_requests)).tolist()
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=num_requests))
    arrivals -= arrivals[0]  # first request arrives at t=0

    def percentiles(lat):
        return (round(float(np.percentile(lat, 50)), 4),
                round(float(np.percentile(lat, 99)), 4))

    # -- continuous batching: paged (equal HBM) vs fixed-slot ----------
    kv_budget = num_slots * max_out          # the fixed layout's KV tokens

    def make_serve(paged: bool, slots: int):
        cfg = {"dtype": "bfloat16", "max_out_tokens": max_out,
               "paged_kv_cache": paged}
        if paged:
            cfg["kv_pool_tokens"] = kv_budget
        s = deepspeed_tpu.init_serving(model, config=cfg, num_slots=slots,
                                       decode_block_tokens=8)
        s.set_params(params)
        return s

    def run_continuous(serve):
        t0 = time.perf_counter()
        reqs, i = [], 0
        while i < num_requests or serve.scheduler.has_work:
            now = time.perf_counter() - t0
            while i < num_requests and arrivals[i] <= now:
                reqs.append(serve.submit(prompts[i], max_new_tokens=news[i]))
                i += 1
            if not serve.scheduler.has_work:
                time.sleep(max(0.0, arrivals[i] - now))
                continue
            serve.step()
        makespan = time.perf_counter() - t0
        lat = [r.t_finish - (t0 + arrivals[j]) for j, r in enumerate(reqs)]
        toks = sum(len(r.output_tokens) for r in reqs)
        serve.scheduler.drain_finished()
        return toks, makespan, lat

    from deepspeed_tpu.monitor.metrics import get_registry
    from deepspeed_tpu.monitor.request_trace import get_request_tracer

    registry = get_registry()
    was_enabled = registry.enabled
    registry.enable()
    # per-request span tracing for the recorded pass: the ring must hold
    # the whole wave so tail attribution sees every request, not a sample
    tracer = get_request_tracer()
    tracer_was = tracer.enabled
    tracer_ring_was = tracer._ring.maxlen
    tracer.configure(ring=max(2 * num_requests, 256)).enable()
    sides = {}
    serving_metrics = {}
    try:
        # engines are built lazily per side so only ONE KV cache (paged
        # pool or fixed layout, each a full num_slots*max_out budget) is
        # resident at a time — the equal-HBM bench must not itself hold 2x
        for side, build in (("continuous",
                             lambda: make_serve(True, 2 * num_slots)),
                            ("fixed_slot",
                             lambda: make_serve(False, num_slots))):
            serve = build()
            run_continuous(serve)           # compile-warm passes
            run_continuous(serve)
            registry.reset()                # warm passes out of the record
            tracer.reset()
            toks_c, span_c, lat_c = run_continuous(serve)
            p50_c, p99_c = percentiles(lat_c)
            snap = registry.snapshot()
            util = snap.get("ds_serve_kv_cache_util_ratio") or {}
            sides[side] = {
                "goodput_tok_s": round(toks_c / span_c, 1),
                "tokens": toks_c, "makespan_s": round(span_c, 3),
                "p50_latency_s": p50_c, "p99_latency_s": p99_c,
                "slots": serve.num_slots,
                "kv_util": round(util.get("mean", 0.0), 3),
            }
            if side == "continuous":
                # serving-health metrics from the lifecycle registry
                # (host-side histograms over the RECORDED pass only) —
                # tracked per BENCH row so a goodput regression is
                # attributable to admission vs prefill vs decode vs pool
                # pressure, not just visible in the aggregate
                serving_metrics = {
                    "ttft_p50_s":
                        round(snap["ds_serve_ttft_seconds"]["p50"], 4),
                    "ttft_p99_s":
                        round(snap["ds_serve_ttft_seconds"]["p99"], 4),
                    "queue_wait_p99_s":
                        round(snap["ds_serve_queue_wait_seconds"]["p99"], 4),
                    "tpot_p50_s":
                        round(snap["ds_serve_tpot_seconds"]["p50"], 5),
                    "mean_slot_occupancy":
                        round(snap["ds_serve_occupancy_ratio"]["mean"], 3),
                    "kv_util": round(util.get("mean", 0.0), 3),
                    "preemptions":
                        int(snap.get("ds_serve_preempted_total", 0)),
                    "pages": {"pool": serve.pool.num_pages - 1,
                              "page_tokens": serve.pool.page,
                              "budget_tokens": kv_budget},
                }
                # per-request tail attribution over the recorded pass:
                # WHICH phase dominates the requests above the p99
                # latency cut (queue vs prefill vs decode vs preemption
                # wait) — the "why is my p99 slow" row for BENCH_r*.json
                ta = tracer.tail_attribution(p=0.99)
                serving_metrics["tail_attribution"] = {
                    "p": ta["p"], "n": ta["n"], "tail_n": ta["tail_n"],
                    "cut_s": round(ta["cut_s"], 4),
                    "dominant_phase": ta["dominant_phase"],
                    "phase_share": {k: round(v, 4) for k, v in
                                    ta["phase_share"].items()},
                    "exemplars": ta["exemplars"],
                }
                # device-true serving capture: a short burst of live
                # requests under the profiler, post-processed into the
                # decode dispatch-slack record (device decode time vs
                # host dispatch window — the sync-free path's headroom)
                for p, n in list(zip(prompts, news))[: serve.num_slots]:
                    serve.submit(p, max_new_tokens=min(int(n), 16))
                dp = capture_device_profile(serve.step, steps=4,
                                            tag="serving")
                serve.run()                 # drain the burst
                serve.scheduler.drain_finished()
                if dp:
                    serving_metrics["device_profile"] = dp
    finally:
        if not was_enabled:                 # a mid-bench raise must not
            registry.disable()              # leave the registry hot
        if not tracer_was:
            tracer.disable()
        tracer.configure(ring=tracer_ring_was)  # undo the wave-sized ring

    # -- static-batch baseline ----------------------------------------
    engine = deepspeed_tpu.init_inference(
        model, config={"dtype": "bfloat16", "max_out_tokens": max_out})
    engine.set_params(params)

    def run_static():
        t0 = time.perf_counter()
        lat, toks = [], 0
        for lo in range(0, num_requests, num_slots):
            hi = min(lo + num_slots, num_requests)
            # the batch cannot launch before its LAST member arrives
            wait = arrivals[hi - 1] - (time.perf_counter() - t0)
            if wait > 0:
                time.sleep(wait)
            S = max(len(p) for p in prompts[lo:hi])
            batch = np.zeros((hi - lo, S), np.int32)
            for r, p in enumerate(prompts[lo:hi]):
                batch[r, : len(p)] = p       # right-pad to the batch max
            out = engine.generate(batch, max_new_tokens=int(max(news[lo:hi])),
                                  do_sample=False)
            jax.block_until_ready(out)
            t_done = time.perf_counter() - t0
            lat += [t_done - arrivals[j] for j in range(lo, hi)]
            toks += int(sum(news[lo:hi]))    # requested tokens only
        return toks, time.perf_counter() - t0, lat

    run_static()                            # compile-warm passes (the first
    run_static()                            # still recompiles: cache growth
    toks_s, span_s, lat_s = run_static()    # drops compiled fns mid-pass)

    p50_s, p99_s = percentiles(lat_s)
    goodput_c = sides["continuous"]["goodput_tok_s"]
    goodput_f = sides["fixed_slot"]["goodput_tok_s"]
    return {
        "workload": {"num_requests": num_requests, "num_slots": num_slots,
                     "paged_slots": 2 * num_slots,
                     "kv_budget_tokens": kv_budget,
                     "qps": qps, "prompt_len": [p_lo, p_hi],
                     "new_tokens": {"short": list(n_short),
                                    "long": list(n_long), "p_long": 0.25},
                     "arrivals": "poisson", "seed": seed},
        "continuous": sides["continuous"],
        "metrics": serving_metrics,
        "fixed_slot": sides["fixed_slot"],
        "static": {"goodput_tok_s": round(toks_s / span_s, 1),
                   "tokens": toks_s, "makespan_s": round(span_s, 3),
                   "p50_latency_s": p50_s, "p99_latency_s": p99_s},
        "goodput_speedup": round(goodput_c / max(toks_s / span_s, 1e-9), 2),
        # the tentpole attribution: same KV HBM, 2x slots via paging
        "paged_vs_fixed_speedup": round(goodput_c / max(goodput_f, 1e-9), 2),
    }


def bench_prefix_serving(num_requests: int = 48, num_slots: int = 8,
                         qps: float = 50.0, seed: int = 0,
                         tiny: bool = False) -> dict:
    """Shared-prefix serving scenario: copy-on-write prefix caching on vs
    off on ONE identical trace (serving/prefix_cache.py — ROADMAP item 3).

    The trace is the regime the cache exists for: ~70% of requests open
    with one of two shared system prompts (multi-page), the rest are
    cold, output lengths are bimodal chat-like.  Both sides run the PAGED
    engine with identical slots/pool; the only delta is
    ``prefix_caching``.  Recorded per side: goodput, TTFT p50/p99, and
    ``prefill_tokens_computed`` (the host-countable savings — this is the
    first serving speedup PROVABLE on CPU, unlike the TPU-bandwidth-bound
    paged-goodput win).  Headline: ``prefill_savings_ratio`` (acceptance:
    >= 40% fewer prefill tokens computed with the cache on) +
    ``prefix_hit_ratio`` + ``outputs_token_identical`` (greedy outputs
    must not change — the correctness half of the acceptance bar).
    """
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import get_registry

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    rng = np.random.default_rng(seed + 7)
    if tiny:  # CPU smoke scale (tests/perf/test_serving_bench.py)
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2,
                          hidden_size=128, intermediate_size=256, num_heads=4,
                          vocab_size=512)
        max_out, page_tokens = 96, 16
        sys_len, tail = 48, (4, 12)
        n_short, n_long = (4, 10), (16, 24)
    else:
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304)
        max_out, page_tokens = 1024, 0
        sys_len, tail = 256, (16, 128)
        n_short, n_long = (16, 96), (192, 256)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    V = model.config.vocab_size

    system_prompts = [rng.integers(0, V, size=sys_len).astype(np.int32)
                      for _ in range(2)]
    shared_mask = rng.random(num_requests) < 0.7   # the 60-80% regime
    long_mask = rng.random(num_requests) < 0.25
    prompts, news = [], []
    for i in range(num_requests):
        t = rng.integers(0, V, size=int(rng.integers(tail[0], tail[1] + 1))
                         ).astype(np.int32)
        if shared_mask[i]:
            prompts.append(np.concatenate(
                [system_prompts[int(rng.integers(2))], t]))
        else:  # cold request: unique prompt, roughly half the system size
            prompts.append(rng.integers(
                0, V, size=sys_len // 2 + len(t)).astype(np.int32))
        news.append(int(rng.integers(n_long[0], n_long[1] + 1) if long_mask[i]
                        else rng.integers(n_short[0], n_short[1] + 1)))
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=num_requests))
    arrivals -= arrivals[0]

    def percentiles(lat):
        return (round(float(np.percentile(lat, 50)), 4),
                round(float(np.percentile(lat, 99)), 4))

    def make_serve(prefix_on: bool):
        s = deepspeed_tpu.init_serving(
            model, config={"dtype": "bfloat16", "max_out_tokens": max_out,
                           "kv_page_tokens": page_tokens,
                           "prefix_caching": prefix_on},
            num_slots=num_slots, decode_block_tokens=8)
        s.set_params(params)
        return s

    def run_trace(serve):
        t0 = time.perf_counter()
        reqs, i = [], 0
        while i < num_requests or serve.scheduler.has_work:
            now = time.perf_counter() - t0
            while i < num_requests and arrivals[i] <= now:
                reqs.append(serve.submit(prompts[i], max_new_tokens=news[i]))
                i += 1
            if not serve.scheduler.has_work:
                time.sleep(max(0.0, arrivals[i] - now))
                continue
            serve.step()
        makespan = time.perf_counter() - t0
        lat = [r.t_finish - (t0 + arrivals[j]) for j, r in enumerate(reqs)]
        outs = [list(r.output_tokens) for r in reqs]
        toks = sum(len(o) for o in outs)
        serve.scheduler.drain_finished()
        return toks, makespan, lat, outs

    registry = get_registry()
    was_enabled = registry.enabled
    registry.enable()
    sides, outputs = {}, {}
    try:
        for side, on in (("cache_on", True), ("cache_off", False)):
            serve = make_serve(on)
            run_trace(serve)            # compile-warm passes
            run_trace(serve)
            if on:
                # measure the INTRA-trace sharing win, not a replay of a
                # fully-warm cache: the warm passes served this same
                # trace, so without a clear even the cold prompts would
                # hit and the savings would read ~100%
                serve.prefix_cache.clear()
            registry.reset()
            toks, span, lat, outs = run_trace(serve)
            outputs[side] = outs
            p50, p99 = percentiles(lat)
            snap = registry.snapshot()
            ttft = snap.get("ds_serve_ttft_seconds") or {}
            entry = {
                "goodput_tok_s": round(toks / span, 1),
                "tokens": toks, "makespan_s": round(span, 3),
                "p50_latency_s": p50, "p99_latency_s": p99,
                "ttft_p50_s": round(ttft.get("p50", 0.0), 4),
                "ttft_p99_s": round(ttft.get("p99", 0.0), 4),
                "prefill_tokens_computed":
                    int(snap.get("ds_serve_prefill_tokens_total", 0)),
            }
            if on:
                hit = int(snap.get("ds_serve_prefix_hit_tokens_total", 0))
                miss = int(snap.get("ds_serve_prefix_miss_tokens_total", 0))
                entry["prefix_hit_ratio"] = round(
                    hit / max(hit + miss, 1), 4)
                entry["prefix_hit_tokens"] = hit
                entry["prefix_evictions"] = int(
                    snap.get("ds_serve_prefix_evictions_total", 0))
                entry["prefix_cache_pages"] = serve.pool.pages_cached
            sides[side] = entry
            serve.close()
    finally:
        if not was_enabled:             # a mid-bench raise must not leave
            registry.disable()          # the registry hot
    on_c = sides["cache_on"]["prefill_tokens_computed"]
    off_c = sides["cache_off"]["prefill_tokens_computed"]
    return {
        "workload": {"num_requests": num_requests, "num_slots": num_slots,
                     "qps": qps, "shared_prefix_frac": 0.7,
                     "system_prompt_tokens": sys_len,
                     "system_prompts": 2,
                     "new_tokens": {"short": list(n_short),
                                    "long": list(n_long), "p_long": 0.25},
                     "arrivals": "poisson", "seed": seed},
        "cache_on": sides["cache_on"],
        "cache_off": sides["cache_off"],
        # the acceptance pair: >= 0.4 savings, outputs unchanged
        "prefill_savings_ratio": round(1.0 - on_c / max(off_c, 1), 4),
        "outputs_token_identical": outputs["cache_on"] ==
                                   outputs["cache_off"],
        "prefix_hit_ratio": sides["cache_on"]["prefix_hit_ratio"],
        "prefix_goodput_speedup": round(
            sides["cache_on"]["goodput_tok_s"]
            / max(sides["cache_off"]["goodput_tok_s"], 1e-9), 2),
    }


def bench_host_tier_serving(num_requests: int = 32, num_slots: int = 4,
                            qps: float = 50.0, seed: int = 0,
                            tiny: bool = False) -> dict:
    """KV host tier at a THRASH-sized pool (ISSUE 11): the identical
    shared-prefix trace with ``kv_host_tier_pages`` off vs on, on a pool
    deliberately too small to keep cached history resident — the regime
    where PR 9's evict-to-drop forgot every cold prefix and the host tier
    keeps them promotable.

    Recorded per side: prefix hit ratio, prefill tokens computed,
    goodput, TTFT p99, demotes/promotes/host pages (tier side).
    Headlines: ``hit_ratio_on`` strictly above ``hit_ratio_off`` +
    ``outputs_token_identical`` (promotion is a byte-identical KV copy,
    so greedy outputs cannot change) — the acceptance pair."""
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import get_registry

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    rng = np.random.default_rng(seed + 13)
    if tiny:  # CPU smoke scale (tests/perf/test_serving_bench.py)
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2,
                          hidden_size=128, intermediate_size=256,
                          num_heads=4, vocab_size=512)
        max_out, page_tokens = 96, 16
        sys_len, tail = 32, (3, 8)
        n_short, n_long = (4, 8), (10, 16)
        # pool ~ live-slot working set: cached prefixes always under
        # pressure (the drop-vs-demote regime at smoke scale)
        n_prefixes, pool_tokens, host_pages = 4, num_slots * 80, 24
    else:
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304)
        max_out, page_tokens = 1024, 0
        sys_len, tail = 256, (16, 96)
        n_short, n_long = (16, 96), (192, 256)
        # pool = exactly the live-slot budget: every cached page is under
        # pressure the moment slots fill, so cached history always
        # evicts — the drop-vs-demote regime
        n_prefixes, pool_tokens, host_pages = 6, num_slots * 1024, 512
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    V = model.config.vocab_size

    sys_prompts = [rng.integers(0, V, size=sys_len).astype(np.int32)
                   for _ in range(n_prefixes)]
    long_mask = rng.random(num_requests) < 0.25
    prompts, news = [], []
    for i in range(num_requests):
        t = rng.integers(0, V, size=int(rng.integers(tail[0], tail[1] + 1))
                         ).astype(np.int32)
        # round-robin over MANY shared prefixes: each re-visit arrives
        # after the pool pressure evicted the prefix's pages
        prompts.append(np.concatenate([sys_prompts[i % n_prefixes], t]))
        news.append(int(rng.integers(n_long[0], n_long[1] + 1)
                        if long_mask[i]
                        else rng.integers(n_short[0], n_short[1] + 1)))
    arrivals = np.cumsum(rng.exponential(1.0 / qps, size=num_requests))
    arrivals -= arrivals[0]

    def make_serve(host_on: bool):
        s = deepspeed_tpu.init_serving(
            model, config={"dtype": "bfloat16", "max_out_tokens": max_out,
                           "kv_page_tokens": page_tokens,
                           "kv_pool_tokens": pool_tokens,
                           "kv_host_tier_pages": host_pages if host_on
                           else 0},
            num_slots=num_slots, decode_block_tokens=8)
        s.set_params(params)
        return s

    def run_trace(serve):
        t0 = time.perf_counter()
        reqs, i = [], 0
        while i < num_requests or serve.scheduler.has_work:
            now = time.perf_counter() - t0
            while i < num_requests and arrivals[i] <= now:
                reqs.append(serve.submit(prompts[i], max_new_tokens=news[i]))
                i += 1
            if not serve.scheduler.has_work:
                time.sleep(max(0.0, arrivals[i] - now))
                continue
            serve.step()
        makespan = time.perf_counter() - t0
        outs = [list(r.output_tokens) for r in reqs]
        serve.scheduler.drain_finished()
        return sum(len(o) for o in outs), makespan, outs

    registry = get_registry()
    was_enabled = registry.enabled
    registry.enable()
    sides, outputs = {}, {}
    try:
        for side, on in (("tier_off", False), ("tier_on", True)):
            serve = make_serve(on)
            run_trace(serve)            # compile-warm passes
            run_trace(serve)
            serve.prefix_cache.clear()  # measure intra-trace behavior
            registry.reset()
            toks, span, outs = run_trace(serve)
            outputs[side] = outs
            snap = registry.snapshot()
            hit = int(snap.get("ds_serve_prefix_hit_tokens_total", 0))
            miss = int(snap.get("ds_serve_prefix_miss_tokens_total", 0))
            ttft = snap.get("ds_serve_ttft_seconds") or {}
            sides[side] = {
                "goodput_tok_s": round(toks / span, 1),
                "makespan_s": round(span, 3),
                "ttft_p99_s": round(ttft.get("p99", 0.0), 4),
                "prefix_hit_ratio": round(hit / max(hit + miss, 1), 4),
                "prefix_hit_tokens": hit,
                "prefill_tokens_computed":
                    int(snap.get("ds_serve_prefill_tokens_total", 0)),
                "evictions": int(snap.get(
                    "ds_serve_prefix_evictions_total", 0)),
                "demotes": int(snap.get("ds_serve_kv_demote_total", 0)),
                "promotes": int(snap.get("ds_serve_kv_promote_total", 0)),
                "host_pages": int(snap.get("ds_serve_kv_host_pages", 0)),
            }
            serve.pool.check_no_leak()
            serve.prefix_cache.check_no_leak()
            serve.close()
    finally:
        if not was_enabled:
            registry.disable()
    return {
        "workload": {"num_requests": num_requests, "num_slots": num_slots,
                     "qps": qps, "shared_prefixes": n_prefixes,
                     "system_prompt_tokens": sys_len,
                     "pool_tokens": pool_tokens, "host_pages": host_pages,
                     "arrivals": "poisson", "seed": seed},
        "tier_off": sides["tier_off"],
        "tier_on": sides["tier_on"],
        "hit_ratio_on": sides["tier_on"]["prefix_hit_ratio"],
        "hit_ratio_off": sides["tier_off"]["prefix_hit_ratio"],
        "demotes": sides["tier_on"]["demotes"],
        "promotes": sides["tier_on"]["promotes"],
        "outputs_token_identical": outputs["tier_on"] ==
                                   outputs["tier_off"],
        "goodput_speedup": round(
            sides["tier_on"]["goodput_tok_s"]
            / max(sides["tier_off"]["goodput_tok_s"], 1e-9), 2),
    }


def bench_elastic_resume(steps_pre: int = 3, steps_post: int = 3,
                         seed: int = 0, tiny: bool = True) -> dict:
    """Elastic training resilience rung (docs/RESILIENCE.md "Elastic
    training"): save a crash-atomic checkpoint at world W, resume at W/2
    and 2W (clamped to the available device count), and record per resume
    world: RESUME LATENCY (the ``load_checkpoint`` wall — manifest
    verification, resharding reads, and the grad-accum-rescale step
    recompile), the wall time of the ``steps_post`` post-resume steps
    (the FIRST includes any rescale recompile; recorded as
    ``post_steps_s``), and STEPS-TO-RECOVER (post-resume steps whose
    eval loss deviates > 2% from the uninterrupted run's trajectory
    before the first match — 0 means the very first resumed step already
    tracks).  Headlines:
    ``resume_latency_s_max``, ``steps_to_recover_max``, ``loss_parity``
    (every compared step within rtol 1e-3)."""
    import numpy as np

    ndev = len(jax.devices())
    w_save = min(4, ndev)
    candidates = sorted({max(1, w_save // 2), min(ndev, w_save * 2)}
                        - {w_save})
    # the divisibility rule up front (docs/RESILIENCE.md): only worlds
    # that can preserve the recorded global batch are resumable; the
    # eval probe (8 rows) must shard over the world too
    tbs_probe = 1 * w_save * 2           # micro * w_save * gas (below)
    worlds = [w for w in candidates if tbs_probe % w == 0 and 8 % w == 0]
    if not worlds:
        return {"status": "skipped",
                "note": f"{ndev} device(s): no different elastic-valid "
                        "world to resume at"}
    layers, hidden = (2, 64) if tiny else (4, 256)
    seq = 32 if tiny else 128
    micro, gas = 1, 2
    tbs = micro * w_save * gas
    rng = np.random.default_rng(seed)
    data = rng.integers(0, 256, size=(16 * tbs, seq)).astype(np.int32)
    probe = data[:8]

    def make(devs, gas_cfg):
        mesh = build_mesh(devices=jax.devices()[:devs])
        set_global_mesh(mesh)
        model = causal_lm("llama-tiny", mesh=mesh, num_layers=layers,
                          hidden_size=hidden, intermediate_size=2 * hidden,
                          num_heads=2, num_kv_heads=2, vocab_size=256,
                          max_seq_len=seq, remat=False)
        cfg = {"train_batch_size": micro * devs * gas_cfg,
               "train_micro_batch_size_per_gpu": micro,
               "gradient_accumulation_steps": gas_cfg,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": 2},
               "steps_per_print": 10**9}
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=cfg, mesh=mesh,
            rng=jax.random.PRNGKey(seed))
        return engine

    def eval_loss(engine):
        engine.eval()
        try:
            return float(engine.forward((probe, probe)))
        finally:
            engine.train()

    def run_steps(engine, n, start=0):
        out = []
        for i in range(start, start + n):
            g = engine.config.gradient_accumulation_steps
            per = tbs // g
            for k in range(g):
                lo = (i * tbs + k * per) % (len(data) - per)
                engine.forward((data[lo:lo + per], data[lo:lo + per]))
            engine.step()
            out.append(eval_loss(engine))
        return out

    import tempfile

    with tempfile.TemporaryDirectory() as td:
        e = make(w_save, gas)
        run_steps(e, steps_pre)
        t0 = time.perf_counter()
        e.save_checkpoint(td, tag="elastic")
        save_s = time.perf_counter() - t0
        ref = run_steps(e, steps_post, start=steps_pre)

        resumes = {}
        parity = True
        for devs in worlds:
            # one bad world must not discard the others' measurements
            try:
                er = make(devs, gas)
                er.forward((data[:devs], data[:devs]))   # lazy-init state
                t0 = time.perf_counter()
                ckpt_dir, _ = er.load_checkpoint(td)
                load_s = time.perf_counter() - t0
                assert ckpt_dir is not None
                t0 = time.perf_counter()
                got = run_steps(er, steps_post, start=steps_pre)
                post_steps_s = time.perf_counter() - t0
            except Exception as exc:
                resumes[str(devs)] = {
                    "status": f"failed: {type(exc).__name__}",
                    "error": str(exc)[:160]}
                parity = False
                continue
            recover = 0
            for a, b in zip(ref, got):
                if abs(a - b) <= 0.02 * abs(a):
                    break
                recover += 1
            parity = parity and bool(np.allclose(ref, got, rtol=1e-3))
            resumes[str(devs)] = {
                "resume_latency_s": round(load_s, 4),
                "gas": er.config.gradient_accumulation_steps,
                "post_steps_s": round(post_steps_s, 4),
                "steps_to_recover": recover,
                "eval_loss_ref": [round(x, 6) for x in ref],
                "eval_loss_resumed": [round(x, 6) for x in got]}
        ok = [r for r in resumes.values() if "resume_latency_s" in r]
        if not ok:
            return {"status": "failed", "worlds": worlds,
                    "resumes": resumes}
        return {"status": "ok", "world_save": w_save, "worlds": worlds,
                "global_batch": tbs, "save_s": round(save_s, 4),
                "resume_latency_s_max": max(r["resume_latency_s"]
                                            for r in ok),
                "steps_to_recover_max": max(r["steps_to_recover"]
                                            for r in ok),
                "loss_parity": parity, "resumes": resumes}


def bench_fleet_chaos(num_requests: int = 24, num_slots: int = 2,
                      seed: int = 0, tiny: bool = False) -> dict:
    """Fleet resilience rung (ISSUE 13): the bimodal shared-prefix trace
    through the ROUTER over two live replicas, run twice — a clean pass,
    and a CHAOS pass where replica 1's serving loop is killed mid-trace
    and revived by a supervisor-style watcher (restart + resume; the
    in-process analog of ``tools/serve_supervisor.py``'s process
    restart).  Recorded per side: goodput, client-latency p50/p99, TTFT
    p99 (max over the replicas' registries), answered/shed counts.
    Headlines: ``goodput_retention`` (chaos/clean), ``restarts_observed``
    (must be >= 1 on the chaos side), ``answered_exactly_once`` +
    ``outputs_token_identical`` (every 200 matches ``generate()``;
    200 + 429 partition the trace — zero drops, zero duplicates)."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import MetricsRegistry
    from deepspeed_tpu.serving import Router, RouterServer
    from deepspeed_tpu.testing.chaos import crash_on_call

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    rng = np.random.default_rng(seed + 17)
    if tiny:  # CPU smoke scale (tests/perf/test_fleet_chaos_bench.py)
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2,
                          hidden_size=128, intermediate_size=256,
                          num_heads=4, vocab_size=512)
        max_out, page_tokens = 96, 16
        sys_len, tail = 32, (3, 8)
        n_short, n_long = (3, 6), (8, 12)
    else:
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304)
        max_out, page_tokens = 1024, 0
        sys_len, tail = 256, (16, 96)
        n_short, n_long = (16, 64), (128, 192)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    V = model.config.vocab_size

    shared = rng.integers(0, V, size=sys_len).astype(np.int32)
    long_mask = rng.random(num_requests) < 0.25
    prompts, news = [], []
    for i in range(num_requests):
        t = rng.integers(0, V, size=int(rng.integers(tail[0], tail[1] + 1))
                         ).astype(np.int32)
        if rng.random() < 0.7:
            prompts.append(np.concatenate([shared, t]))
        else:
            prompts.append(rng.integers(
                0, V, size=sys_len // 2 + len(t)).astype(np.int32))
        news.append(int(rng.integers(n_long[0], n_long[1] + 1)
                        if long_mask[i]
                        else rng.integers(n_short[0], n_short[1] + 1)))
    ref = deepspeed_tpu.init_inference(
        model, config={"dtype": "bfloat16", "max_out_tokens": max_out})
    ref.set_params(params)
    want = [[int(t) for t in np.asarray(ref.generate(
                p[None], max_new_tokens=n, do_sample=False))[0, len(p):]]
            for p, n in zip(prompts, news)]

    def run_side(kill: bool) -> dict:
        replicas = []
        router = front = None
        try:
            for _ in range(2):
                s = deepspeed_tpu.init_serving(
                    model, config={"dtype": "bfloat16",
                                   "max_out_tokens": max_out,
                                   "kv_page_tokens": page_tokens,
                                   "max_queue_depth": max(4, num_requests // 3),
                                   "shed_retry_after_s": 0.2},
                    num_slots=num_slots, decode_block_tokens=4,
                    metrics_port=0, registry=MetricsRegistry().enable(),
                    private_health=True, serve_loop=True)
                s.set_params(params)
                # warm the serving programs BEFORE the measured trace (one
                # long + one short prompt covers the pow2 prefill buckets +
                # the decode block): the recorded TTFT must not be compile
                # time
                warms = [s.submit(prompts[0], max_new_tokens=2),
                         s.submit(prompts[0][:20], max_new_tokens=2)]
                deadline = time.perf_counter() + 240
                while not all(w.done for w in warms) \
                        and time.perf_counter() < deadline:
                    time.sleep(0.005)
                s._registry.reset()
                replicas.append(s)
            router = Router(
                [f"r{i}={s.metrics_server.url}"
                 for i, s in enumerate(replicas)],
                registry=MetricsRegistry().enable(), dispatch_rounds=8,
                retry_backoff=0.02, poll_interval=0.05, request_timeout=120.0)
            router.refresh()
            router.start()
            front = RouterServer(router).start()
            results = [None] * num_requests
            client_lat = [None] * num_requests

            def client(i):
                # a well-behaved client: waits out 429 Retry-After and backs
                # off on router-level 503 (both mean "no answer produced") —
                # bounded retries, then the last status stands
                t0 = time.perf_counter()
                req = urllib.request.Request(
                    front.url + "/generate",
                    data=_json.dumps(
                        {"prompt": prompts[i].tolist(),
                         "max_new_tokens": news[i],
                         "session": f"sess-{i % 4}",
                         "timeout": 90}).encode(),
                    headers={"Content-Type": "application/json"})
                for _attempt in range(8):
                    try:
                        with urllib.request.urlopen(req, timeout=120) as resp:
                            results[i] = (resp.status, _json.load(resp))
                        break
                    except urllib.error.HTTPError as exc:
                        try:
                            body = _json.load(exc)
                        except Exception:
                            body = {}
                        results[i] = (exc.code, body)
                        if exc.code == 429:
                            time.sleep(min(float(
                                body.get("retry_after_s", 0.2)), 0.5))
                            continue
                        if exc.code == 503:
                            time.sleep(0.2)
                            continue
                        break
                    except OSError:
                        break
                client_lat[i] = time.perf_counter() - t0

            restarts = {"n": 0}
            stop = threading.Event()

            def watcher():
                while not stop.is_set():
                    for s in replicas:
                        if s._loop_crashed and not s._loop_alive():
                            time.sleep(0.1)
                            s.start_loop()
                            s.resume_admission()
                            restarts["n"] += 1
                    time.sleep(0.02)

            wt = threading.Thread(target=watcher, daemon=True)
            wt.start()
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(num_requests)]
            t0 = time.perf_counter()

            def launch_staggered():
                for t in threads:
                    t.start()
                    time.sleep(0.03)
                for t in threads:
                    t.join(timeout=240)

            try:
                if kill:
                    with crash_on_call(replicas[1], "step", n=3):
                        launch_staggered()
                else:
                    launch_staggered()
            finally:
                stop.set()
                wt.join(timeout=10)
            span = time.perf_counter() - t0
            answered, sheds, identical, toks = 0, 0, True, 0
            for i, r in enumerate(results):
                if r is None:
                    continue
                code, body = r
                if code == 200:
                    answered += 1
                    toks += len(body.get("tokens", []))
                    identical = identical and body.get("tokens") == want[i]
                elif code == 429:
                    sheds += 1
            ttft_p99 = 0.0
            for s in replicas:
                snap = s._registry.snapshot()
                ttft = snap.get("ds_serve_ttft_seconds") or {}
                ttft_p99 = max(ttft_p99, float(ttft.get("p99", 0.0)))
            lat = sorted(x for x in client_lat if x is not None)
            out = {
                "goodput_tok_s": round(toks / max(span, 1e-9), 1),
                "makespan_s": round(span, 3),
                "answered": answered, "shed_429": sheds,
                "exactly_once": answered + sheds == num_requests,
                "token_identical": identical,
                "ttft_p99_s": round(ttft_p99, 4),
                "client_p50_s": round(lat[len(lat) // 2], 4) if lat else 0.0,
                "client_p99_s": round(lat[(len(lat) * 99) // 100], 4)
                if lat else 0.0,
                "restarts_observed": restarts["n"],
                "router_retries": int(
                    router.registry.get("ds_router_retries_total").value),
            }
            return out
        finally:
            # a mid-side exception (client assertion, registry miss)
            # must not leak two live engines + loops + HTTP servers
            # into the rest of the bench run
            if front is not None:
                front.stop()
            if router is not None:
                router.stop()
            for s in replicas:
                s.close()

    clean = run_side(kill=False)
    chaos = run_side(kill=True)
    return {
        "workload": {"num_requests": num_requests, "num_slots": num_slots,
                     "replicas": 2, "shared_prefix_frac": 0.7,
                     "system_prompt_tokens": sys_len, "seed": seed},
        "clean": clean,
        "chaos": chaos,
        "goodput_retention": round(
            chaos["goodput_tok_s"] / max(clean["goodput_tok_s"], 1e-9), 3),
        "ttft_p99_clean_s": clean["ttft_p99_s"],
        "ttft_p99_chaos_s": chaos["ttft_p99_s"],
        "restarts_observed": chaos["restarts_observed"],
        "answered_exactly_once": clean["exactly_once"]
        and chaos["exactly_once"],
        "outputs_token_identical": clean["token_identical"]
        and chaos["token_identical"],
    }


def bench_disagg_serving(num_requests: int = 16, num_slots: int = 4,
                         seed: int = 0, tiny: bool = False) -> dict:
    """Disaggregated prefill/decode serving rung (ISSUE 19): the bimodal
    shared-prefix trace through the router over a MONOLITHIC fleet (2
    ``both`` replicas) and a ROLE-SPLIT fleet (2 prefill + 2 decode,
    int8 KV-page handoff over /kv_offer + /kv_adopt), each driven both
    with plain and with STREAMING ``/generate`` — the role-split ×
    streaming grid.  Recorded per cell: goodput, TTFT p50/p99 (engine
    histogram on the plain sides; client-observed first-chunk latency on
    the streaming sides — the user-visible number streaming exists for),
    token identity vs single-engine ``generate()``.  The role-split
    fleet additionally records the KV handoff ledger: wire bytes (int8 +
    scale planes) vs the dense twin, pages shipped/adopted.  Headlines:
    ``handoff_compression`` (dense/wire, ~2x at bf16), ``ttft_stream_
    over_total`` (first chunk lands well before the full answer), and
    the grid's ``outputs_token_identical`` conjunction."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import MetricsRegistry
    from deepspeed_tpu.serving import Router, RouterServer

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    rng = np.random.default_rng(seed + 23)
    if tiny:  # CPU smoke scale (tests/perf/test_disagg_serving_bench.py)
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2,
                          hidden_size=128, intermediate_size=256,
                          num_heads=4, vocab_size=512)
        max_out, page_tokens = 96, 16
        sys_len, tail = 32, (3, 8)
        n_short, n_long = (8, 16), (24, 32)
    else:
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304)
        max_out, page_tokens = 1024, 16
        sys_len, tail = 256, (16, 96)
        n_short, n_long = (16, 64), (128, 192)
    params = jax.jit(model.init)(jax.random.PRNGKey(seed))
    V = model.config.vocab_size

    shared = rng.integers(0, V, size=sys_len).astype(np.int32)
    long_mask = rng.random(num_requests) < 0.25
    prompts, news = [], []
    for i in range(num_requests):
        t = rng.integers(0, V, size=int(rng.integers(tail[0], tail[1] + 1))
                         ).astype(np.int32)
        if rng.random() < 0.7:
            prompts.append(np.concatenate([shared, t]))
        else:
            prompts.append(rng.integers(
                0, V, size=sys_len // 2 + len(t)).astype(np.int32))
        news.append(int(rng.integers(n_long[0], n_long[1] + 1)
                        if long_mask[i]
                        else rng.integers(n_short[0], n_short[1] + 1)))
    # quantize_kv_cache=True everywhere: the cache planes are int8 +
    # scale already, so the int8 wire handoff is LOSSLESS and the
    # role-split outputs must match this reference bit for bit
    cfg_common = {"dtype": "bfloat16", "max_out_tokens": max_out,
                  "kv_page_tokens": page_tokens,
                  "quantize_kv_cache": True}
    ref = deepspeed_tpu.init_inference(model, config=dict(cfg_common))
    ref.set_params(params)
    want = [[int(t) for t in np.asarray(ref.generate(
                p[None], max_new_tokens=n, do_sample=False))[0, len(p):]]
            for p, n in zip(prompts, news)]

    def run_fleet(role_split: bool) -> dict:
        replicas = []
        router = front = None
        roles = (["prefill", "prefill", "decode", "decode"] if role_split
                 else ["both", "both"])
        try:
            for role in roles:
                s = deepspeed_tpu.init_serving(
                    model, config=dict(cfg_common,
                                       max_queue_depth=num_requests + 4),
                    num_slots=num_slots, decode_block_tokens=4,
                    role=role, metrics_port=0,
                    registry=MetricsRegistry().enable(),
                    private_health=True, serve_loop=True)
                s.set_params(params)
                warms = [s.submit(prompts[0], max_new_tokens=2),
                         s.submit(prompts[0][:20], max_new_tokens=2)]
                deadline = time.perf_counter() + 240
                while not all(w.done for w in warms) \
                        and time.perf_counter() < deadline:
                    time.sleep(0.005)
                s._registry.reset()
                replicas.append(s)
            router = Router(
                [f"{r}{i}@{r}={s.metrics_server.url}"
                 for i, (r, s) in enumerate(zip(roles, replicas))],
                registry=MetricsRegistry().enable(), dispatch_rounds=8,
                retry_backoff=0.02, poll_interval=0.05,
                request_timeout=120.0)
            router.refresh()
            router.start()
            front = RouterServer(router).start()
            # warm the FULL dispatch paths through the front (every
            # prefill shape bucket, the handoff path, decode, the
            # stream relay) so the measured variants see steady-state
            # shapes, not XLA compiles
            _drive_trace(front, prompts, [4] * len(prompts), want,
                         False, replicas)
            _drive_trace(front, prompts[:2], news[:2], want[:2],
                         True, replicas)
            out = {}
            for stream in (False, True):
                for s in replicas:
                    s._registry.reset()
                    # the front warm-up filled the decode tries, and
                    # /kv_offer dedupes pages the receiver already
                    # holds — drop the decode-side tries so each
                    # measured variant re-exercises the handoff wire
                    # (XLA shapes stay warm; that was the warm-up's job)
                    if role_split and s.role == "decode":
                        s.prefix_cache.clear()
                router.registry.reset()
                out["stream" if stream else "plain"] = _drive_trace(
                    front, prompts, news, want, stream, replicas)
            # the role-split handoff ledger accumulates across BOTH
            # variants (each reset clears it, so scrape per variant)
            return out
        finally:
            if front is not None:
                front.stop()
            if router is not None:
                router.stop()
            for s in replicas:
                s.close()

    def _drive_trace(front, prompts, news, want, stream, replicas):
        results = [None] * len(prompts)
        client_lat = [None] * len(prompts)
        first_tok = [None] * len(prompts)

        def client(i):
            t0 = time.perf_counter()
            payload = {"prompt": prompts[i].tolist(),
                       "max_new_tokens": news[i],
                       "session": f"sess-{i % 4}", "timeout": 90}
            if stream:
                payload["stream"] = True
            req = urllib.request.Request(
                front.url + "/generate",
                data=_json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            for _attempt in range(8):
                try:
                    with urllib.request.urlopen(req, timeout=120) as resp:
                        if stream:
                            toks = []
                            for line in resp:
                                ev = _json.loads(line)
                                if ev.get("tokens"):
                                    if first_tok[i] is None:
                                        first_tok[i] = (time.perf_counter()
                                                        - t0)
                                    toks.extend(ev["tokens"])
                                elif ev.get("error"):
                                    results[i] = (int(ev.get("status")
                                                      or 503), ev)
                                    break
                                elif ev.get("done"):
                                    results[i] = (200, {"tokens": toks})
                                    break
                        else:
                            results[i] = (resp.status, _json.load(resp))
                    if results[i] is not None and results[i][0] != 503:
                        break
                except urllib.error.HTTPError as exc:
                    try:
                        body = _json.load(exc)
                    except Exception:
                        body = {}
                    results[i] = (exc.code, body)
                    if exc.code in (429, 503):
                        time.sleep(0.2)
                        continue
                    break
                except OSError:
                    break
            client_lat[i] = time.perf_counter() - t0

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
            time.sleep(0.03)
        for t in threads:
            t.join(timeout=240)
        span = time.perf_counter() - t0
        answered, identical, toks = 0, True, 0
        for i, r in enumerate(results):
            if r is None:
                continue
            code, body = r
            if code == 200:
                answered += 1
                toks += len(body.get("tokens", []))
                identical = identical and body.get("tokens") == want[i]
        ratios = []
        if stream:
            ft = sorted(x for x in first_tok if x is not None)
            ttft_p50 = ft[len(ft) // 2] if ft else 0.0
            ttft_p99 = ft[(len(ft) * 99) // 100] if ft else 0.0
            ratios = sorted(f / max(t, 1e-9)
                            for f, t in zip(first_tok, client_lat)
                            if f is not None and t is not None)
        else:
            ttft_p50 = ttft_p99 = 0.0
            for s in replicas:
                snap = s._registry.snapshot()
                h = snap.get("ds_serve_ttft_seconds") or {}
                ttft_p50 = max(ttft_p50, float(h.get("p50", 0.0)))
                ttft_p99 = max(ttft_p99, float(h.get("p99", 0.0)))
        rec = {"goodput_tok_s": round(toks / max(span, 1e-9), 1),
               "makespan_s": round(span, 3),
               "answered": answered,
               "token_identical": identical,
               "ttft_p50_s": round(ttft_p50, 4),
               "ttft_p99_s": round(ttft_p99, 4),
               "client_p50_s": round(sorted(
                   x for x in client_lat if x is not None)
                   [answered // 2], 4) if answered else 0.0}
        if ratios:
            # per-request TTFT / total-latency: the user-visible claim
            # streaming makes — the first chunk lands well before the
            # full answer (median of per-request ratios, not a ratio of
            # mismatched percentiles)
            rec["ttft_over_total_p50"] = round(
                ratios[len(ratios) // 2], 4)
        # KV handoff ledger (role-split fleets only; zero elsewhere)
        wire = dense = shipped = adopted = resumes = 0.0
        for s in replicas:
            snap = s._registry.snapshot()
            fam = snap.get("ds_serve_kv_handoff_bytes_total") or {}
            if isinstance(fam, dict):
                dense += float(fam.get('{dtype="dense"}', 0) or 0)
                wire += sum(float(v or 0) for k, v in fam.items()
                            if k != '{dtype="dense"}')
            shipped += float(snap.get(
                "ds_serve_kv_handoff_pages_total", 0) or 0)
            adopted += float(snap.get(
                "ds_serve_kv_adopted_pages_total", 0) or 0)
            resumes += float(snap.get(
                "ds_serve_stream_resumes_total", 0) or 0)
        if shipped:
            rec.update({"handoff_wire_bytes": int(wire),
                        "handoff_dense_bytes": int(dense),
                        "handoff_pages_shipped": int(shipped),
                        "handoff_pages_adopted": int(adopted)})
        if resumes:
            rec["stream_resumes"] = int(resumes)
        return rec

    mono = run_fleet(role_split=False)
    disagg = run_fleet(role_split=True)
    wire = disagg["stream"].get("handoff_wire_bytes", 0) \
        + disagg["plain"].get("handoff_wire_bytes", 0)
    dense = disagg["stream"].get("handoff_dense_bytes", 0) \
        + disagg["plain"].get("handoff_dense_bytes", 0)
    identical = all(side[v]["token_identical"]
                    for side in (mono, disagg) for v in ("plain", "stream"))
    ttft_over_total = disagg["stream"].get("ttft_over_total_p50", 0.0)
    return {
        "workload": {"num_requests": num_requests, "num_slots": num_slots,
                     "mono_replicas": 2, "prefill_replicas": 2,
                     "decode_replicas": 2, "shared_prefix_frac": 0.7,
                     "system_prompt_tokens": sys_len,
                     "kv_page_tokens": page_tokens, "seed": seed},
        "mono": mono,
        "disagg": disagg,
        "handoff_compression": round(dense / wire, 3) if wire else 0.0,
        "handoff_wire_bytes": int(wire),
        "handoff_dense_bytes": int(dense),
        # like-for-like: role-split vs monolithic, both streaming (the
        # plain sides ride in the record for the off-axis of the grid)
        "disagg_goodput_ratio": round(
            disagg["stream"]["goodput_tok_s"]
            / max(mono["stream"]["goodput_tok_s"], 1e-9), 3),
        # streaming's reason to exist: the first chunk lands well before
        # the full answer (TTFT < total latency, client-observed)
        "ttft_stream_over_total": ttft_over_total,
        "outputs_token_identical": identical,
    }


def bench_overlap_rung(steps: int = 4, warmup: int = 2) -> dict:
    """ZeRO-3 compute/collective overlap on/off ablation on the 1.34B
    training scenario (ROADMAP open item 1; runtime/zero/overlap.py).

    Runs the SAME workload twice over an fsdp mesh spanning every local
    device — once with GSPMD-placed collectives (``overlap_comm: false``),
    once with the layer-chunked explicit schedule (``overlap_comm: true``)
    — and records per side: tokens/sec, MFU (live ``ds_train_mfu`` gauge),
    and the device-profile ``gap_share`` / ``gap_plus_comm_share`` (the
    exact numbers the overlap schedule is supposed to shrink).  The headline
    ``overlap_speedup`` plus the two device-phase rows land in BENCH_JSON.

    On CPU runners the 1.34B architecture is scaled to smoke size (the
    bucket structure, collective schedule, and phase accounting are what
    the CPU row exercises — absolute rates are not comparable to TPU).
    Needs >1 device for the fsdp collectives to exist; the parent launches
    this in a child process so a CPU parent can force a virtual 8-device
    mesh without re-initializing its own backend.
    """
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import get_registry

    t0 = time.perf_counter()
    try:
        devs = jax.devices()
        if len(devs) < 2:
            return {"status": "skipped: needs >1 device for fsdp "
                              "collectives", "devices": len(devs)}
        on_tpu = jax.default_backend() != "cpu"
        W = len(devs)
        mesh = build_mesh(fsdp=W, devices=devs)
        set_global_mesh(mesh)
        if on_tpu:
            over = {}
            micro, accum, seq = 2, 2, 1024
            bucket_layers = 2
        else:
            over = dict(num_layers=4, hidden_size=128,
                        intermediate_size=256, num_heads=4, num_kv_heads=4,
                        vocab_size=512, max_seq_len=128)
            micro, accum, seq = 1, 2, 64
            bucket_layers = 1
        registry = get_registry()
        results = {}
        n_params = 0
        for side, overlap in (("off", False), ("on", True)):
            model = causal_lm("llama-1b4", mesh=mesh, **over)
            cfg_m = model.config
            ds_config = {
                "train_micro_batch_size_per_gpu": micro,
                "gradient_accumulation_steps": accum,
                "bf16": {"enabled": bool(on_tpu)},
                "optimizer": {"type": "AdamW", "params": {"lr": 2e-4}},
                "gradient_clipping": 1.0,
                "zero_optimization": {
                    "stage": 3, "overlap_comm": overlap,
                    "overlap_bucket_layers": bucket_layers,
                    "stage3_param_persistence_threshold": 0},
                "comms_logger": {"enabled": True},
                "steps_per_print": 10**9,
            }
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, config=ds_config, mesh=mesh,
                rng=jax.random.PRNGKey(11))
            tokens = jax.random.randint(
                jax.random.PRNGKey(1), (accum, micro * W, seq), 0,
                cfg_m.vocab_size)
            batch = (tokens, tokens)
            for _ in range(warmup):
                engine.train_step(batch)
            if overlap and not engine._overlap:
                # a silent fallback here would benchmark off-vs-off and
                # report a bogus ~1.0x speedup with loss_parity true
                return {"status": "failed: overlap_comm did not activate "
                                  "on the 'on' side",
                        "reason": engine._overlap_reason}
            sync(engine.state.params)
            registry.reset()
            engine._flops_meter.reset_clock()
            t1 = time.perf_counter()
            for _ in range(steps):
                engine.train_step(batch)
            sync(engine.state.params)
            dt = (time.perf_counter() - t1) / steps
            n_params = sum(x.size for x in
                           jax.tree.leaves(engine.state.params))
            tps = accum * micro * W * seq / dt
            row = {"tokens_per_sec": round(tps, 1),
                   "step_ms": round(dt * 1e3, 1),
                   "overlap_active": bool(engine._overlap),
                   "loss": round(float(engine._last_loss), 6)}
            tm = collect_train_metrics(registry)
            if tm.get("mfu") is not None:
                row["mfu"] = round(tm["mfu"], 5)
            dp = capture_device_profile(
                lambda: engine.train_step(batch), steps=2,
                tag=f"overlap_{side}")
            if dp and "per_step" in dp:
                row["gap_share"] = dp.get("gap_share")
                per = dp["per_step"]
                win = sum(per.values())
                if win > 0:
                    row["gap_plus_comm_share"] = round(
                        (per["gap_s"] + per["comm_s"]) / win, 4)
                row["device_profile"] = dp
            # comm_s with an explicit source label (ROADMAP bench-honesty
            # note): device-true per-step seconds when a perfetto capture
            # exists (the same spans that fill ds_comm_<op>_device_seconds),
            # else the analytic comm-plan priced at the assumed link
            # bandwidth — never a silent 0 on CPU runners.
            dev_comm = ((dp or {}).get("per_step") or {}).get("comm_s", 0.0)
            if dev_comm > 0.0:
                row["comm_s"] = round(dev_comm, 6)
                row["comm_s_source"] = "device"
            else:
                from deepspeed_tpu.monitor.goodput_core import (
                    analytic_comm_seconds)

                plan = engine._comm_plan or {}
                gbps = engine._gp_comm_gbps
                row["comm_s"] = round(
                    analytic_comm_seconds(plan.get("micro"), gbps) * accum
                    + analytic_comm_seconds(plan.get("boundary"), gbps), 6)
                row["comm_s_source"] = "analytic"
            results[side] = row
            engine = model = None
            import gc

            gc.collect()
        speedup = (results["on"]["tokens_per_sec"]
                   / max(results["off"]["tokens_per_sec"], 1e-9))
        return {"status": "ok", "zero_stage": 3, "devices": W,
                "backend": jax.default_backend(),
                "params_b": round(n_params / 1e9, 4),
                "micro_batch": micro, "grad_accum": accum, "seq": seq,
                "steps": steps, "bucket_layers": bucket_layers,
                "off": results["off"], "on": results["on"],
                "overlap_speedup": round(speedup, 3),
                "loss_parity": bool(np.allclose(
                    results["on"]["loss"], results["off"]["loss"],
                    rtol=1e-3)),
                "scaled_for_cpu": not on_tpu}
    except Exception as exc:
        return {"status": f"failed: {type(exc).__name__}",
                "error": str(exc)[:300],
                "elapsed_s": round(time.perf_counter() - t0, 1)}


def _run_child_rung(env_key: str) -> dict:
    """Run one bench rung in a child process keyed by ``env_key`` (the
    env var naming the child's JSON output file — ``main`` dispatches on
    it): a CPU parent gets a virtual 8-device mesh via XLA_FLAGS (which
    must be set before jax initializes — impossible in-process), and on
    TPU a child abort cannot kill the 125M headline (same isolation
    story as the 1.34B ladder)."""
    import subprocess
    import sys
    import tempfile

    fd, out = tempfile.mkstemp(suffix=".json")
    os.close(fd)
    os.unlink(out)
    env = dict(os.environ, **{env_key: out})
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=8 "
            "--xla_cpu_enable_concurrency_optimized_scheduler=false "
            + env.get("XLA_FLAGS", ""))
    try:
        proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                              env=env, timeout=1800, capture_output=True,
                              text=True)
        try:
            with open(out) as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return {"status": f"failed: child exited {proc.returncode} "
                              "without a result",
                    "stderr_tail": proc.stderr[-400:]}
    except subprocess.TimeoutExpired:
        return {"status": "failed: child timeout (1800s)"}


def _run_overlap_subprocess() -> dict:
    return _run_child_rung("DSTPU_BENCH_OVERLAP_OUT")


def bench_quant_comm(steps: int = 3, warmup: int = 1) -> dict:
    """Dense vs int8 quantized-collective ablation (ROADMAP item 2;
    comm/collectives_q.py — ZeRO++ arXiv:2306.10209, EQuARX
    arXiv:2506.17615).

    Two opted-in call-site families on the same tiny-LM workload over
    every local device, each run dense then quantized:

    - ``all_reduce`` — the ZeRO stage-1 boundary gradient sync on a dp
      mesh: dense GSPMD psum vs the engine's manual ``q_all_reduce``
      (error feedback ON — the convergence-safe configuration);
    - ``gather_rs`` — the overlap schedule's per-bucket forward gathers
      + AD-transpose reduce-scatters at ZeRO stage 3 on an fsdp mesh:
      dense vs int8 transport.

    Per side: tokens/s + final loss.  Per quantized op: wire bytes vs
    dense-equivalent bytes — BOTH series recorded on the same trace
    (``ds_comm_<op>_bytes_total`` / ``ds_comm_<op>_dense_bytes_total``)
    — plus the busbw gauge when populated.  Headlines: per-op
    ``compression`` (dense/wire, the ~2-4x acceptance number) and per-
    family ``loss_parity``.  CPU-meaningful: bytes and parity are
    backend-independent; rates are not comparable to TPU.
    """
    import gc

    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import get_registry

    t0 = time.perf_counter()
    devs = jax.devices()
    if len(devs) < 2:
        return {"status": "skipped: needs >1 device for collectives",
                "devices": len(devs)}
    W = len(devs)
    on_tpu = jax.default_backend() != "cpu"
    registry = get_registry()

    def fam_sum(metrics, name) -> float:
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return float(sum(x for x in v.values()
                             if isinstance(x, (int, float))))
        return float(v or 0)

    def snapshot() -> dict:
        return json.loads(registry.statz_json())["metrics"]

    if on_tpu:
        over = {}
        micro, accum, seq = 2, 2, 512
    else:
        over = dict(num_layers=4, hidden_size=128, intermediate_size=256,
                    num_heads=4, vocab_size=512, max_seq_len=128)
        micro, accum, seq = 1, 2, 64

    def run_side(mesh_kw, stage, overlap, quant_cfg, q_active_check):
        mesh = build_mesh(devices=devs, **mesh_kw)
        set_global_mesh(mesh)
        model = causal_lm("gpt2-small", mesh=mesh, **over)
        ds_config = {
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": accum,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-4}},
            "gradient_clipping": 1.0,
            "bf16": {"enabled": bool(on_tpu)},
            "zero_optimization": {
                "stage": stage, "overlap_comm": overlap,
                "overlap_bucket_layers": 1,
                "stage3_param_persistence_threshold": 0},
            "comms_logger": {"enabled": True},
            "steps_per_print": 10**9,
        }
        if quant_cfg:
            ds_config["comm_quantization"] = quant_cfg
        registry.reset()
        from deepspeed_tpu.comm.comm import comms_logger
        comms_logger.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=ds_config, mesh=mesh,
            rng=jax.random.PRNGKey(11))
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (accum, micro * W, seq), 0,
                                    model.config.vocab_size)
        batch = (tokens, tokens)
        for _ in range(warmup):
            engine.train_step(batch)
        if quant_cfg:
            err = q_active_check(engine)
            if err:
                return None, {"status": f"failed: {err}"}
        sync(engine.state.params)
        t1 = time.perf_counter()
        for _ in range(steps):
            engine.train_step(batch)
        sync(engine.state.params)
        dt = (time.perf_counter() - t1) / steps
        row = {"tokens_per_sec": round(accum * micro * W * seq / dt, 1),
               "step_ms": round(dt * 1e3, 1),
               "loss": round(float(engine._last_loss), 6)}
        metrics = snapshot()
        engine = model = None
        gc.collect()
        return row, metrics

    def check_qcomm_grads(engine):
        if not engine._qcomm_grads:
            return ("comm_quantization.grad_all_reduce did not activate: "
                    f"{engine._qcomm_grads_reason}")
        return None

    def check_overlap_q(engine):
        if not engine._overlap:
            return f"overlap_comm did not activate: {engine._overlap_reason}"
        plan = engine._comm_plan or {"micro": []}
        if not any(e[0].startswith("q_") for e in plan["micro"]):
            return "overlap comm plan carries no quantized entries"
        return None

    families = {}
    compression = {}
    parity = {}
    for fam, mesh_kw, stage, overlap, qcfg, check, q_ops, dense_op in (
            ("all_reduce", {"dp": W}, 1, False,
             {"grad_all_reduce": True, "error_feedback": True},
             check_qcomm_grads, ("q_all_reduce",), "all_reduce"),
            ("gather_rs", {"fsdp": W}, 3, True,
             {"all_gather": True, "reduce_scatter": True},
             check_overlap_q, ("q_all_gather", "q_reduce_scatter"),
             "all_gather")):
        dense_row, dense_metrics = run_side(mesh_kw, stage, overlap, None,
                                            check)
        if dense_row is None:
            return dense_metrics
        q_row, q_metrics = run_side(mesh_kw, stage, overlap, qcfg, check)
        if q_row is None:
            return q_metrics
        ops = {}
        for op in q_ops:
            wire = fam_sum(q_metrics, f"ds_comm_{op}_bytes_total")
            dense_eq = fam_sum(q_metrics,
                               f"ds_comm_{op}_dense_bytes_total")
            entry = {"wire_bytes": int(wire),
                     "dense_bytes": int(dense_eq)}
            if wire and dense_eq:
                entry["compression"] = round(dense_eq / wire, 3)
                compression[op] = entry["compression"]
            busbw = q_metrics.get(f"ds_comm_{op}_busbw_gbps")
            if busbw:
                entry["busbw_gbps"] = round(float(busbw), 3)
            ops[op] = entry
        dense_bytes_observed = fam_sum(
            dense_metrics, f"ds_comm_{dense_op}_bytes_total")
        lp = abs(q_row["loss"] - dense_row["loss"]) \
            <= 0.05 * max(abs(dense_row["loss"]), 1e-9)
        parity[fam] = bool(lp)
        families[fam] = {
            "dense": dict(dense_row,
                          dense_op_bytes=int(dense_bytes_observed)),
            "int8": q_row, "ops": ops, "loss_parity": bool(lp),
            "speedup": round(q_row["tokens_per_sec"]
                             / max(dense_row["tokens_per_sec"], 1e-9), 4)}
    return {"status": "ok", "devices": W,
            "backend": jax.default_backend(),
            "steps": steps, "micro_batch": micro, "grad_accum": accum,
            "seq": seq,
            "compression": compression,
            "loss_parity": parity,
            "families": families,
            "elapsed_s": round(time.perf_counter() - t0, 1)}


def _run_quant_comm_subprocess() -> dict:
    return _run_child_rung("DSTPU_BENCH_QUANTCOMM_OUT")


def bench_pipe(steps: int = 3, warmup: int = 1) -> dict:
    """Dense vs int8 stage-boundary ablation for the full-manual pipeline
    (ISSUE 16; runtime/pipe/spmd.py — the 1F1B fused schedule with
    ppermute boundary rings).

    pp in {2, 4} over all local devices (fsdp absorbs the rest), each
    depth run with a dense fp32 boundary then the int8 carry codec
    (``comm_quantization.pipeline``).  Per side: tokens/s + final loss;
    per rung: the ANALYTIC schedule bubble share ((pp-1)/T, T =
    M + 2(pp-1) for 1F1B) and the engine-committed boundary byte ledger —
    ``ds_comm_ppermute_bytes_total`` dense vs
    ``ds_comm_q_ppermute_bytes_total`` + its dense-twin series on the
    quantized side.  Headlines: per-rung ``compression`` (dense-
    equivalent / wire, the >=2x acceptance number at fp32 — ~3.9x for
    int8 codes + fp32 block scales) and ``loss_parity``.  CPU-meaningful:
    bytes, bubble share and parity are backend-independent; rates are
    not comparable to TPU.
    """
    import gc

    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm
    from deepspeed_tpu.monitor.metrics import get_registry

    t0 = time.perf_counter()
    devs = jax.devices()
    if len(devs) < 4:
        return {"status": "skipped: needs >=4 devices for pp x fsdp",
                "devices": len(devs)}
    W = len(devs)
    on_tpu = jax.default_backend() != "cpu"
    registry = get_registry()

    def fam_sum(metrics, name) -> float:
        v = metrics.get(name, 0)
        if isinstance(v, dict):
            return float(sum(x for x in v.values()
                             if isinstance(x, (int, float))))
        return float(v or 0)

    # fp32 end to end (no bf16): the acceptance pin is the fp32 boundary's
    # ~3.9x int8 compression, and parity tolerances assume fp32 math
    if on_tpu:
        over = {}
        micro, accum, seq, M = 2, 2, 512, 4
    else:
        over = dict(num_layers=4, hidden_size=128, intermediate_size=256,
                    num_heads=4, num_kv_heads=2, vocab_size=512,
                    max_seq_len=128)
        micro, accum, seq, M = 1, 2, 64, 4

    def run_side(pp, quant):
        mesh = build_mesh(pp=pp, fsdp=W // pp, devices=devs)
        set_global_mesh(mesh)
        model = causal_lm("llama-tiny", mesh=mesh, pp_schedule="1f1b",
                          pp_microbatches=M, **over)
        ds_config = {
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": accum,
            "optimizer": {"type": "AdamW", "params": {"lr": 2e-4}},
            "gradient_clipping": 1.0,
            "zero_optimization": {"stage": 1},
            "comms_logger": {"enabled": True},
            "steps_per_print": 10**9,
        }
        if quant:
            ds_config["comm_quantization"] = {"pipeline": True}
        registry.reset()
        from deepspeed_tpu.comm.comm import comms_logger
        comms_logger.reset()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=ds_config, mesh=mesh,
            rng=jax.random.PRNGKey(11))
        if quant and not engine.module.config.pp_boundary_q:
            return None, {"status": "failed: comm_quantization.pipeline "
                                    "did not arm pp_boundary_q"}
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (accum, micro * W, seq), 0,
                                    model.config.vocab_size)
        batch = (tokens, tokens)
        for _ in range(warmup):
            engine.train_step(batch)
        sync(engine.state.params)
        t1 = time.perf_counter()
        for _ in range(steps):
            engine.train_step(batch)
        sync(engine.state.params)
        dt = (time.perf_counter() - t1) / steps
        row = {"tokens_per_sec": round(accum * micro * W * seq / dt, 1),
               "step_ms": round(dt * 1e3, 1),
               "loss": round(float(engine._last_loss), 6)}
        metrics = json.loads(registry.statz_json())["metrics"]
        engine = model = None
        gc.collect()
        return row, metrics

    rungs = {}
    compression = {}
    parity = {}
    bubble = {}
    for pp in (2, 4):
        if W % pp or W // pp < 1:
            continue
        dense_row, dense_metrics = run_side(pp, False)
        if dense_row is None:
            return dense_metrics
        q_row, q_metrics = run_side(pp, True)
        if q_row is None:
            return q_metrics
        wire = fam_sum(q_metrics, "ds_comm_q_ppermute_bytes_total")
        dense_eq = fam_sum(q_metrics,
                           "ds_comm_q_ppermute_dense_bytes_total")
        key = f"pp{pp}"
        if wire and dense_eq:
            compression[key] = round(dense_eq / wire, 3)
        # 1F1B schedule: T = M + 2(pp-1) ticks, pp-1 of them idle per stage
        bubble[key] = round((pp - 1) / (M + 2 * (pp - 1)), 4)
        lp = abs(q_row["loss"] - dense_row["loss"]) \
            <= 0.05 * max(abs(dense_row["loss"]), 1e-9)
        parity[key] = bool(lp)
        rungs[key] = {
            "dense": dict(dense_row, boundary_bytes=int(fam_sum(
                dense_metrics, "ds_comm_ppermute_bytes_total"))),
            "int8": dict(q_row, boundary_bytes=int(wire),
                         dense_equiv_bytes=int(dense_eq)),
            "loss_parity": bool(lp),
            "speedup": round(q_row["tokens_per_sec"]
                             / max(dense_row["tokens_per_sec"], 1e-9), 4)}
    return {"status": "ok", "devices": W,
            "backend": jax.default_backend(),
            "steps": steps, "micro_batch": micro, "grad_accum": accum,
            "seq": seq, "microbatches": M, "schedule": "1f1b",
            "compression": compression,
            "loss_parity": parity,
            "bubble_share": bubble,
            "rungs": rungs,
            "elapsed_s": round(time.perf_counter() - t0, 1)}


def _run_pipe_subprocess() -> dict:
    return _run_child_rung("DSTPU_BENCH_PIPE_OUT")


# micro=4 exceeds what the AOT compiler will place at 48 layers (probed:
# fwd+grad compile-OOMs); micro=2 compiles under every policy
LADDER_1B4 = [("mlp_dots", 2), ("dots", 2), ("full", 2), ("full", 1)]


def bench_1b4_rung(policy: str, micro: int, steps: int = 6, warmup: int = 2):
    """ONE rung of the 1.34B ladder (VERDICT r4 item 1: a measured >1B
    tokens/sec + MFU on the real chip; BASELINE north-star is
    tokens/sec/chip at >1B scale).

    Recipe: 15.75GB HBM fits 1.34B params by dropping the fp32 master (bf16
    state + stochastic-rounding updates, ``bf16.master_weights=false``;
    the init program emits bf16 directly so no fp32 tree ever
    materializes), int8 blockwise Adam states (Adam8bit), bf16 gradient
    accumulation, and remat.  Persistent bytes/param: 2 (params) + 2 (acc)
    + ~2.06 (int8 m+v+scales) ~= 6.1 -> ~8.2GB, leaving ~7GB for
    transients + activations.

    The parent walks the (policy, micro) ladder one SUBPROCESS per rung —
    a failed rung's HBM dies with its process instead of poisoning the
    next rung's attempt.
    """
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    t0 = time.perf_counter()
    try:
        mesh = build_mesh(devices=jax.devices()[:1])
        set_global_mesh(mesh)
        accum = 32 // micro  # ~32k tokens/step regardless of micro
        seq = 1024
        model = causal_lm("llama-1b4", mesh=mesh)
        cfg = model.config
        ds_config = {
            "train_batch_size": micro * accum,
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": accum,
            "bf16": {"enabled": True, "master_weights": False},
            "data_types": {"grad_accum_dtype": "bf16"},
            "optimizer": {"type": "Adam8bit",
                          "params": {"lr": 2e-4, "weight_decay": 0.1}},
            "gradient_clipping": 1.0,
            "activation_checkpointing": {"enabled": True, "policy": policy},
            "comms_logger": {"enabled": True},
            "steps_per_print": 10**9,
        }
        from deepspeed_tpu.monitor.metrics import get_registry

        registry = get_registry()
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config=ds_config,
                                                   mesh=mesh)
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (accum, micro, seq), 0, cfg.vocab_size)
        batch = (tokens, tokens)
        for _ in range(warmup):
            engine.train_step(batch)
        sync(engine.state.params)
        registry.reset()
        engine._flops_meter.reset_clock()
        t1 = time.perf_counter()
        for _ in range(steps):
            engine.train_step(batch)
        sync(engine.state.params)
        dt = (time.perf_counter() - t1) / steps
        n_params = sum(x.size for x in jax.tree.leaves(engine.state.params))
        tps = micro * accum * seq / dt
        fpt = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq
        mfu = tps * fpt / peak_flops()
        return {"status": "ok", "tokens_per_sec": round(tps, 1),
                "mfu": round(mfu, 4), "params_b": round(n_params / 1e9, 3),
                "micro_batch": micro, "grad_accum": accum, "seq": seq,
                "steps": steps, "step_ms": round(dt * 1e3, 1),
                "metrics": collect_train_metrics(registry),
                "remat_policy": policy,
                "recipe": "bf16 state + stochastic rounding (no fp32 "
                          "master), Adam8bit int8 m/v, bf16 grad accum",
                "loss_final": round(float(engine._last_loss), 3)}
    except Exception as exc:
        msg = str(exc)
        oom = ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
               or "out of memory" in msg)
        return {"status": "oom" if oom else f"failed: {type(exc).__name__}",
                "error": msg[:300],
                "ladder": f"{policy}/micro={micro}",
                "elapsed_s": round(time.perf_counter() - t0, 1)}


def bench_decode(steps: int = 512) -> dict:
    """Decode throughput microbench (VERDICT r4 item 1: the fused Pallas
    decode path).  Rows: GPT-2 125M as bf16 / int8(+int8 KV) / batch-8,
    plus the 1.34B llama-1b4 single-stream (the >1B serving rung).

    Two numbers per row:
    - ``tokens_per_sec`` (raw): one timed generate() including the relay's
      fixed per-call costs — directly comparable to BENCH_r04.
    - ``steady_tokens_per_sec``: per-token rate from differencing a long
      and a short generation, which cancels the runner's fixed per-call
      overhead (~0.2s of tunnel dispatch + scalar-fetch RTT that a local
      TPU-VM server would not pay; xplane traces show the decode loop
      itself runs gapless on device).
    """
    import deepspeed_tpu
    from deepspeed_tpu.models import causal_lm

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)
    out = {}
    rows = (
        ("bf16", "gpt2-small", {"vocab_size": 50304}, 1,
         {"dtype": "bfloat16"}),
        # unfused control: same model/methodology with kernel injection off,
        # so the fused-path speedup is self-contained in this record
        ("bf16_unfused", "gpt2-small", {"vocab_size": 50304}, 1,
         {"dtype": "bfloat16", "use_fused_decode": False}),
        ("int8", "gpt2-small", {"vocab_size": 50304}, 1,
         {"dtype": "int8", "quantize_kv_cache": True}),
        # int8 weights on the FUSED path (dequant in-kernel; bf16 KV) —
        # halves the per-token weight reads of the kernel-injected decode
        ("int8w_fused", "gpt2-small", {"vocab_size": 50304}, 1,
         {"dtype": "int8"}),
        ("bf16_b8", "gpt2-small", {"vocab_size": 50304}, 8,
         {"dtype": "bfloat16"}),
        # >1B serving: 1.34B fits HBM as bf16 (2.7GB) with room for the
        # decode transients
        ("llama1b4_bf16", "llama-1b4", {"remat": False}, 1,
         {"dtype": "bfloat16"}),
        # the decode-bandwidth headline: 1.34B int8 weights on the fused
        # path halve the per-token weight reads
        ("llama1b4_int8w", "llama-1b4", {"remat": False}, 1,
         {"dtype": "int8"}),
    )
    short = steps // 4
    for name, preset, model_over, batch, cfg_over in rows:
        for attempt in (1, 2):
            try:
                model = causal_lm(preset, mesh=mesh, **model_over)
                params = jax.jit(model.init)(jax.random.PRNGKey(0))
                engine = deepspeed_tpu.init_inference(
                    model, config={"max_out_tokens": 2048, **cfg_over})
                engine.set_params(params)
                prompt = jax.random.randint(jax.random.PRNGKey(1),
                                            (batch, 16), 0,
                                            model.config.vocab_size)
                # TWO warmup calls per length, LONG length first (the short
                # warmup would otherwise allocate a small cache that the
                # long one evicts along with the compiled programs): the
                # first call per length compiles against the fresh
                # (uncommitted) cache/rng, the second recompiles against
                # the committed steady-state layouts the loop outputs
                # carry — only call 3+ measures the cached program
                for n in (steps, short):
                    for _ in range(2):
                        sync(engine.generate(prompt, max_new_tokens=n,
                                             do_sample=False))

                def timed(n, reps=2):
                    best = 1e9
                    for _ in range(reps):
                        t0 = time.perf_counter()
                        sync(engine.generate(prompt, max_new_tokens=n,
                                             do_sample=False))
                        best = min(best, time.perf_counter() - t0)
                    return best

                t_short, dt = timed(short), timed(steps)
                per_tok = (dt - t_short) / (steps - short)
                out[name] = {"tokens_per_sec": round(batch * steps / dt, 1),
                             "steady_tokens_per_sec":
                                 round(batch / per_tok, 1),
                             "steady_ms_per_token": round(1e3 * per_tok, 3),
                             "fixed_call_overhead_s":
                                 round(t_short - short * per_tok, 3),
                             "new_tokens": steps, "batch": batch,
                             "kernel_injected":
                                 engine._dparams is not None,
                             "ms_per_token": round(1e3 * dt / steps, 2)}
                if attempt > 1:  # a flaky-relay retry is part of the record
                    out[name]["attempts"] = attempt
                break
            except Exception as exc:
                msg = str(exc)
                out[name] = {"status": f"failed: {type(exc).__name__}",
                             "error": msg[:200], "attempts": attempt}
                if ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
                        or "out of memory" in msg):
                    out[name]["status"] = "oom"
                    break  # deterministic: retrying just wastes minutes
                transient = ("response body closed" in msg
                             or "read body" in msg or "UNAVAILABLE" in msg)
                if not transient:
                    break  # deterministic failure: don't re-pay init+compile
                # else: retry once — the relay occasionally drops a compile
                # RPC mid-flight ("response body closed")
            finally:
                engine = params = model = None
                import gc

                gc.collect()
    out["note"] = ("bf16/bf16_b8/int8w_fused/llama1b4 run the kernel-"
                   "injected fused Pallas decode (4 launches/layer; "
                   "int8w_fused dequantizes in-kernel); int8 (int8 KV) runs "
                   "the unfused fallback; steady_* differencing cancels the "
                   "relay's fixed per-call cost (see bench_decode docstring)")
    return out


def _run_1b4_subprocess() -> dict:
    """Walk the 1.34B ladder, one CHILD PROCESS per rung: a failed rung's
    HBM (and any hard device fault — the remote-tunnel runtime can abort
    the process) dies with its child instead of poisoning the next rung or
    the 125M headline."""
    import subprocess
    import sys
    import tempfile

    attempts = []
    for policy, micro in LADDER_1B4:
        fd, out = tempfile.mkstemp(suffix=".json")
        os.close(fd)
        os.unlink(out)  # child creates it; absence = child died early
        env = dict(os.environ, DSTPU_BENCH_1B4_OUT=out,
                   DSTPU_BENCH_1B4_LADDER=f"{policy},{micro}")
        try:
            proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                                  env=env, timeout=1800, capture_output=True,
                                  text=True)
            try:
                with open(out) as fh:
                    result = json.load(fh)
            except (OSError, json.JSONDecodeError):
                result = {"status": f"failed: child exited {proc.returncode} "
                                    "without a (complete) result",
                          "ladder": f"{policy}/micro={micro}",
                          "stderr_tail": proc.stderr[-400:]}
        except subprocess.TimeoutExpired:
            result = {"status": "failed: child timeout (1800s)",
                      "ladder": f"{policy}/micro={micro}"}
        if result.get("status") == "ok":
            if attempts:
                result["ladder_attempts"] = attempts
            return result
        if result.get("status", "").startswith("skipped"):
            return result
        attempts.append({k: result.get(k) for k in
                         ("status", "ladder", "error", "elapsed_s",
                          "stderr_tail") if result.get(k)})
    return {"status": "failed: no ladder rung succeeded",
            "ladder_attempts": attempts}


def bench_continuous_profiler() -> dict:
    """Continuous-profiler rung (ISSUE 20): arm the always-on profiler on
    a tiny training loop at a forced cadence (capture every 2 steps,
    1-step windows, duty cap lifted) and report what the SCHEDULED path
    produced with no operator ``/profilez`` in the loop: the history-ring
    window count, the latest window's per-scope per-step device-seconds,
    whether the phase lanes stay under the per-step wall, and the
    window-over-window differ verdict.  The scheduler, ring, and differ
    are host-side mechanisms, so the CPU smoke row is meaningful; on the
    TPU runner the same rung exercises real device captures."""
    import shutil
    import tempfile

    from deepspeed_tpu.profiling.continuous import HistoryRing, diff_windows

    hist = tempfile.mkdtemp(prefix="dstpu_bench_cprof_")
    t_start = time.perf_counter()
    try:
        mesh = build_mesh(devices=jax.devices()[:1])
        set_global_mesh(mesh)
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2,
                          hidden_size=128, intermediate_size=512,
                          num_heads=4, vocab_size=2048)
        ds_config = {
            "train_batch_size": 2,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "steps_per_print": 10**9,
            "continuous_profiler": {
                "enabled": True, "every_steps": 2, "every_seconds": 3600.0,
                "capture_steps": 1, "max_duty_cycle": 1.0,
                "history_dir": hist, "max_windows": 8},
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model, config=ds_config, mesh=mesh)
        rng = jax.random.PRNGKey(7)
        tokens = jax.random.randint(rng, (1, 2, 128), 0, 2048)
        batch = (tokens, tokens)
        ring = HistoryRing(hist)
        n = 0
        while n < 16 and len(ring.paths()) < 2:
            engine.train_step(batch)
            n += 1
        sync(engine.state.params)
        if engine._cprof is not None:
            engine._cprof.close()      # abandon any in-flight window
        wins = ring.latest(4)
        if len(wins) < 2:
            return {"status": f"failed: {len(wins)} windows after {n} steps"}
        prev, cur = wins[-2], wins[-1]
        phase_s = sum(cur["scopes"].get(k, 0.0) for k in
                      ("fwd_bwd", "optimizer", "comm", "other", "gap"))
        per_step_wall = cur["window_s"] / max(1, cur["steps"])
        return {
            "status": "ok",
            "windows": len(ring.paths()),
            "train_steps": n,
            "wall_s": round(time.perf_counter() - t_start, 3),
            "latest": {
                "seq": cur["seq"], "steps": cur["steps"],
                "window_ms": round(1e3 * cur["window_s"], 2),
                "busy_ratio": round(cur["busy_ratio"], 4),
                "coverage_ratio": round(cur["coverage_ratio"], 4),
                "overhead_ratio": round(cur["overhead_ratio"], 4),
                "degraded": cur["degraded"],
                "top_scopes_ms": {
                    k: round(1e3 * v, 3) for k, v in
                    sorted(cur["scopes"].items(), key=lambda kv: -kv[1])[:4]},
            },
            # the five phase lanes partition the per-step wall exactly;
            # float slack only (acceptance: scope sums <= window wall)
            "phases_within_wall": bool(phase_s <= per_step_wall * 1.001),
            "regressions_vs_prev": [r["scope"] for r in
                                    diff_windows(prev, cur)],
        }
    finally:
        shutil.rmtree(hist, ignore_errors=True)


def main():
    if os.environ.get("DSTPU_BENCH_EMIT_ONLY"):
        # subprocess pin for the stdout contract (tests/unit/
        # test_metrics.py): emit a synthetic record through the REAL
        # final-line path and exit — the last stdout line must be the
        # parseable bare BENCH_JSON summary, with nothing after it
        record = {"metric": "emit_selftest", "value": 0.0,
                  "unit": "tokens/sec", "vs_baseline": 0.0,
                  "detail": {"mfu": 0.0, "backend": jax.default_backend(),
                             "note": "DSTPU_BENCH_EMIT_ONLY=1",
                             # oversized filler: the cap must truncate
                             # blocks, never the line
                             "metrics": {"filler": "x" * 4000}}}
        emit_summary(record, None)
        return
    if os.environ.get("DSTPU_BENCH_1B4_OUT"):
        # child mode: run ONE ladder rung, write the result, exit
        if jax.default_backend() == "cpu":
            result = {"status": "skipped: cpu backend"}
        else:
            policy, micro = os.environ["DSTPU_BENCH_1B4_LADDER"].split(",")
            result = bench_1b4_rung(policy, int(micro))
        with open(os.environ["DSTPU_BENCH_1B4_OUT"], "w") as fh:
            json.dump(result, fh)
        return
    if os.environ.get("DSTPU_BENCH_OVERLAP_OUT"):
        # child mode: overlap on/off ablation over all local devices (the
        # CPU parent hands this child a virtual 8-device mesh)
        result = bench_overlap_rung()
        with open(os.environ["DSTPU_BENCH_OVERLAP_OUT"], "w") as fh:
            json.dump(result, fh)
        return
    if os.environ.get("DSTPU_BENCH_QUANTCOMM_OUT"):
        # child mode: dense vs int8 quantized-collective ablation
        result = bench_quant_comm()
        with open(os.environ["DSTPU_BENCH_QUANTCOMM_OUT"], "w") as fh:
            json.dump(result, fh)
        return
    if os.environ.get("DSTPU_BENCH_PIPE_OUT"):
        # child mode: pipeline dense-vs-int8 boundary ablation
        result = bench_pipe()
        with open(os.environ["DSTPU_BENCH_PIPE_OUT"], "w") as fh:
            json.dump(result, fh)
        return

    # The >1B rung runs in a child process BEFORE the parent initializes the
    # TPU client (two live clients on the tunnel conflict; and a child abort
    # must not kill the headline).  Env heuristic only — the child verifies
    # the real backend itself.
    rung_1b4 = None
    if os.environ.get("JAX_PLATFORMS", "").lower() != "cpu" \
            and os.environ.get("DSTPU_BENCH_SKIP_1B4") != "1":
        rung_1b4 = _run_1b4_subprocess()

    # overlap on/off ablation (ROADMAP item 1 mechanical acceptance): runs
    # on CPU too — the child gets its own virtual multi-device mesh
    rung_overlap = None
    if os.environ.get("DSTPU_BENCH_SKIP_OVERLAP") != "1":
        rung_overlap = _run_overlap_subprocess()

    # quantized-collective dense-vs-int8 ablation (ROADMAP item 2
    # acceptance: per-op bytes ~2-4x down with loss parity); CPU-meaningful
    rung_quant_comm = None
    if os.environ.get("DSTPU_BENCH_SKIP_QUANTCOMM") != "1":
        rung_quant_comm = _run_quant_comm_subprocess()

    # pipeline dense-vs-int8 boundary ablation (ISSUE 16 acceptance: >=2x
    # fewer boundary bytes at loss parity, bubble share recorded);
    # CPU-meaningful for bytes/parity
    rung_pipe = None
    if os.environ.get("DSTPU_BENCH_SKIP_PIPE") != "1":
        rung_pipe = _run_pipe_subprocess()

    on_tpu = jax.default_backend() != "cpu"

    # streamed-offload relay ablation (ISSUE 11 / ROADMAP item 3): bf16 vs
    # int8 relay on the same streamed workload; runs on CPU at smoke scale
    rung_streamed = None
    if os.environ.get("DSTPU_BENCH_SKIP_STREAMED") != "1":
        rung_streamed = bench_streamed_rung()

    # elastic resume: world-size-change restore latency + steps-to-recover
    # (ISSUE 14); meaningful on CPU too — resharding reads + gas-rescale
    # recompile are host-side costs
    rung_elastic = None
    if os.environ.get("DSTPU_BENCH_SKIP_ELASTIC") != "1":
        try:
            rung_elastic = bench_elastic_resume(tiny=not on_tpu)
        except Exception as exc:
            rung_elastic = {"status": f"failed: {type(exc).__name__}",
                            "error": str(exc)[:200]}

    # continuous-profiler rung (ISSUE 20): the scheduled-capture path end
    # to end — >=2 history windows, per-scope device-seconds under the
    # window wall, differ verdict — with no operator /profilez in the
    # loop; host-side mechanism, so CPU-meaningful
    rung_cprof = None
    if os.environ.get("DSTPU_BENCH_SKIP_CPROF") != "1":
        try:
            rung_cprof = bench_continuous_profiler()
        except Exception as exc:
            rung_cprof = {"status": f"failed: {type(exc).__name__}",
                          "error": str(exc)[:200]}

    mesh = build_mesh(devices=jax.devices()[:1])
    set_global_mesh(mesh)

    if on_tpu:
        # micro-batch 12 is the measured sweet spot under mlp_dots + dense
        # CE; deep accumulation amortizes the optimizer step.  Vocab padded
        # 50257 -> 50304 (multiple of 128) for MXU tiling — standard
        # practice (Megatron/DeepSpeed GPT-2 runs pad the same way).
        # dense CE (ce_chunk=0) measured 6% faster than the blockwise path
        # at this size — the [B,S,V] fp32 logits transient fits HBM and
        # skips the chunk scan's recompute.
        micro, accum, seq, steps, warmup = 12, 16, 1024, 8, 2
        model = causal_lm("gpt2-small", mesh=mesh, vocab_size=50304, ce_chunk=0)
    else:  # dev smoke path
        micro, accum, seq, steps, warmup = 2, 1, 256, 3, 1
        model = causal_lm("gpt2-small", mesh=mesh, num_layers=2, hidden_size=128,
                          intermediate_size=512, num_heads=4, vocab_size=2048)
    batch = micro * accum
    cfg = model.config

    ds_config = {
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": accum,
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "optimizer": {"type": "AdamW", "params": {"lr": 6e-4, "weight_decay": 0.1}},
        "gradient_clipping": 1.0,
        # "mlp_dots": attention residuals persist (the flash kernel never
        # re-runs in backward) while the MLP half remats with matmul outputs
        # saved — measured the fastest policy on v5e at this size.
        "activation_checkpointing": {"enabled": True, "policy": "mlp_dots"},
        # model profile printed once during warmup (XLA cost analysis)
        "flops_profiler": {"enabled": True, "profile_step": 2},
        # training-side telemetry: ds_comm_* per-collective accounting +
        # ds_train_tflops/mfu + ds_mem_* (collect_train_metrics reads these)
        "comms_logger": {"enabled": True},
        "steps_per_print": 10**9,
    }
    from deepspeed_tpu.monitor.metrics import get_registry

    registry = get_registry()
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=ds_config, mesh=mesh)

    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (accum, micro, seq), 0, cfg.vocab_size)
    batch_data = (tokens, tokens)  # stacked [gas, micro, seq] for train_step

    # measure the fixed host-fetch round-trip to subtract from the loop
    tiny = jax.jit(lambda a: a + 1)
    z = jnp.ones((8, 8))
    sync(tiny(z))
    t0 = time.perf_counter()
    sync(tiny(z))
    overhead = time.perf_counter() - t0

    def one_step():
        # fused path: ONE dispatch for the whole step (scan over microbatches
        # + update in a single XLA program)
        engine.train_step(batch_data)

    for _ in range(warmup):
        one_step()
    sync(engine.state.params)
    registry.reset()            # warm passes (compiles included) off the record
    engine._flops_meter.reset_clock()
    # run-level goodput ledger bracketing the measured window.  Snapshot
    # DELTAS, so a supervisor-provided ledger (DSTPU_RUNLEDGER) is
    # observed rather than clobbered; a bench-owned enable stays
    # in-memory (no jsonl path).
    gp = engine._goodput
    gp_owned = not gp.enabled
    if gp_owned:
        gp.enable(run_id="bench-train", role="train")
    gp_before = gp.snapshot()

    t0 = time.perf_counter()
    for _ in range(steps):
        one_step()
    sync(engine.state.params)
    # Raw wall time (conservative); the measured fetch round-trip is reported
    # separately in detail for comparison.
    dt = time.perf_counter() - t0
    rung_goodput = goodput_window(gp_before, gp.snapshot(), dt,
                                  steps * batch * seq)
    if gp_owned:
        gp.disable()
    train_metrics = collect_train_metrics(registry)
    # device-true phase breakdown over a 2-step post-measurement capture
    # (the /profilez analysis, attached per BENCH row so the gap/overlap
    # headroom and device-vs-analytic comm attribution travel with the
    # throughput number)
    dev_profile = capture_device_profile(one_step, steps=2, tag="train")
    if dev_profile:
        train_metrics["device_profile"] = dev_profile

    # The 8B rung is opt-in (DSTPU_BENCH_8B=1): on this runner the 16GB
    # host-tiered param tree must travel through the remote-device relay,
    # which takes tens of minutes before the first step — far past any
    # bench budget.  The default emits the measured capability status; the
    # param-streaming mechanism itself is exercised by tests/unit/
    # test_param_offload.py on the CPU mesh and by small real-TPU programs.
    if on_tpu and os.environ.get("DSTPU_BENCH_8B") == "1":
        rung_8b = bench_8b_rung()
    elif on_tpu:
        rung_8b = {"status": "skipped by default: one streamed fwd+bwd step "
                             "takes ~56min through this runner's relay; set "
                             "DSTPU_BENCH_8B=1 to rerun",
                   "measured_once": {
                       "status": "ok", "tokens_per_sec_fwd_bwd": 0.31,
                       "step_ms": 3352468.0, "loss": 11.762,
                       "note": "2026-07-30 on this runner: 8B (16.1GB bf16 "
                               "> 15.75GB HBM) trains fwd+bwd on ONE chip "
                               "via the streamed per-layer path; the rate "
                               "is the relay's ~14MB/s effective host<->"
                               "device bandwidth (~48GB moved per "
                               "micro-batch), not TPU compute"},
                   "params_b": 8.03, "hbm_needed_gb": 16.1,
                   "hbm_present_gb": 15.75}
    else:
        rung_8b = None

    # decode microbench (engine freed above keeps HBM available: the train
    # engine's state remains live, but 125M leaves plenty)
    rung_decode = bench_decode() if on_tpu else None

    # continuous-batching serving scenario (Poisson arrivals, mixed
    # lengths) vs the static-batch baseline at equal slot count
    if on_tpu:
        try:
            rung_serving = bench_serving()
        except Exception as exc:
            rung_serving = {"status": f"failed: {type(exc).__name__}",
                            "error": str(exc)[:200]}
        # shared-prefix trace: prefix caching on/off (prefill-token
        # savings are host-counted, so this row is also meaningful on
        # the CPU smoke path — tests/perf runs it tiny)
        try:
            rung_prefix = bench_prefix_serving()
        except Exception as exc:
            rung_prefix = {"status": f"failed: {type(exc).__name__}",
                           "error": str(exc)[:200]}
        # thrash-sized prefix cache: host tier on/off hit-ratio row
        try:
            rung_host_tier = bench_host_tier_serving()
        except Exception as exc:
            rung_host_tier = {"status": f"failed: {type(exc).__name__}",
                              "error": str(exc)[:200]}
        # fleet resilience: goodput + TTFT p99 through the router with
        # and without one replica kill + supervisor restart mid-trace
        try:
            rung_fleet_chaos = bench_fleet_chaos()
        except Exception as exc:
            rung_fleet_chaos = {"status": f"failed: {type(exc).__name__}",
                                "error": str(exc)[:200]}
        # disaggregated prefill/decode: role-split × streaming grid,
        # int8 KV-page handoff wire bytes vs the dense twin
        try:
            rung_disagg = bench_disagg_serving()
        except Exception as exc:
            rung_disagg = {"status": f"failed: {type(exc).__name__}",
                           "error": str(exc)[:200]}
    else:
        rung_serving = None
        rung_prefix = None
        rung_host_tier = None
        rung_fleet_chaos = None
        rung_disagg = None

    tokens_per_step = batch * seq
    tps = steps * tokens_per_step / dt
    n_params = sum(x.size for x in jax.tree.leaves(engine.state.params))
    # fwd+bwd FLOPs/token: 6N matmul + 12*L*D*S attention (causal halves it).
    flops_per_token = 6 * n_params + 6 * cfg.num_layers * cfg.hidden_size * seq
    mfu = tps * flops_per_token / peak_flops()
    record = ({
        "metric": "gpt2_125m_train_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.40, 4),
        "baseline_def": "mfu / 0.40 MFU north-star target (BASELINE.json "
                        "published no measured reference number)",
        "detail": {"mfu": round(mfu, 4), "params_m": round(n_params / 1e6, 2),
                   "batch": batch, "micro_batch": micro, "grad_accum": accum,
                   "seq": seq, "steps": steps,
                   "step_ms": round(1e3 * dt / steps, 2),
                   "fetch_overhead_ms": round(1e3 * overhead, 2),
                   "flops_model": "6N + 6*L*D*S per token (dense causal; "
                                  "remat recompute not counted)",
                   "mfu_analysis": (
                       "xplane trace (r5): the step is device-gapless; "
                       "matmul fusions 47% (head GEMM ~89% of peak), Pallas "
                       "kernels 33% (flash bwd measured at parity with "
                       "jax's in-tree TPU kernel; Pallas norms faster than "
                       "XLA-fused norms), data formatting 9%, loop fusions "
                       "7%. The gap to the 1.34B rung's 0.60 MFU is "
                       "architectural: GPT-2-small's head_dim=64 underfills "
                       "the 128-wide MXU contraction in attention, and "
                       "S=1024 attention is a larger share at D=768. "
                       "Probed and rejected by measurement: no-remat "
                       "(0.42, HBM pressure), mlp_only (0.44), XLA norms "
                       "(0.43), XLA attention (compile-OOM), 256-token "
                       "fwd flash blocks (0.42 in-context despite 1.6x "
                       "standalone), micro 8/16 (0.43/0.45)."),
                   "backend": jax.default_backend(),
                   "device": getattr(jax.devices()[0], "device_kind", "?"),
                   # training-health metrics (the serving record's analog):
                   # live tflops/mfu gauges, peak HBM, top collectives
                   **({"metrics": train_metrics} if train_metrics else {}),
                   **({"goodput": rung_goodput} if rung_goodput else {}),
                   **({"cprof": rung_cprof} if rung_cprof else {}),
                   **({"llama_1b4": rung_1b4} if rung_1b4 else {}),
                   **({"overlap_1b4": rung_overlap} if rung_overlap
                      else {}),
                   **({"quant_comm": rung_quant_comm} if rung_quant_comm
                      else {}),
                   **({"pipe": rung_pipe} if rung_pipe else {}),
                   **({"llama3_8b": rung_8b} if rung_8b else {}),
                   **({"decode_125m": rung_decode} if rung_decode else {}),
                   **({"serving_125m": rung_serving} if rung_serving
                      else {}),
                   **({"prefix_serving_125m": rung_prefix} if rung_prefix
                      else {}),
                   **({"host_tier_serving": rung_host_tier}
                      if rung_host_tier else {}),
                   **({"fleet_chaos": rung_fleet_chaos}
                      if rung_fleet_chaos else {}),
                   **({"disagg_serving": rung_disagg}
                      if rung_disagg else {}),
                   **({"elastic_resume": rung_elastic}
                      if rung_elastic else {}),
                   **({"streamed_offload": rung_streamed}
                      if rung_streamed else {})},
    })
    emit_summary(record, rung_serving)


# Hard byte cap on the bare final stdout line.  BENCH_r05 recorded
# ``"parsed": null`` because the runner reads (and truncates around ~2000
# chars) the LAST stdout line: an oversized summary line truncates into
# non-JSON and the whole record is lost.  The cap is enforced by
# progressively dropping the bulkiest optional sub-objects (everything
# still rides, in full, in the first-line record).
BENCH_SUMMARY_MAX_CHARS = 1800


def _strip_bulky(obj):
    """Drop per-capture payloads (device_profile) from a summary
    sub-object — they belong to the record line, not the capped final
    line."""
    if isinstance(obj, dict):
        return {k: _strip_bulky(v) for k, v in obj.items()
                if k != "device_profile"}
    return obj


def run_metadata() -> dict:
    """THE run-environment stamp every BENCH_JSON block carries (one
    shared helper, so no block can drift): git sha, jax/jaxlib versions,
    platform, and the summary ``schema_version`` — ``tools/perf_ledger.py``
    uses it to label a cross-rung perf move that coincides with an
    ENVIRONMENT change (toolchain bump, different backend) instead of
    blaming the code.  Bump ``schema_version`` when the summary's block
    shapes change incompatibly."""
    meta = {"schema_version": 1}
    try:
        import subprocess

        meta["git_sha"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip() or None
    except Exception:
        meta["git_sha"] = None
    try:
        import jaxlib

        meta["jax"] = jax.__version__
        meta["jaxlib"] = jaxlib.version.__version__
        meta["platform"] = jax.default_backend()
    except Exception:
        pass
    return meta


def summary_lines(record: dict, rung_serving) -> list:
    """The machine-readable tail of the bench stdout: a human-greppable
    ``BENCH_JSON:``-prefixed line followed by the SAME summary as a bare
    JSON object on the FINAL line — the runner ``json.loads``-parses the
    last stdout line into its ``parsed`` field (a prefixed final line
    parses to nothing, and an oversized line truncates to garbage — both
    are the BENCH_r05 ``"parsed": null`` bug).  The bare line is capped
    at :data:`BENCH_SUMMARY_MAX_CHARS`; tests/unit/test_metrics.py
    round-trips the last line and pins the cap with a real subprocess
    (``DSTPU_BENCH_EMIT_ONLY``)."""
    summary = {"metric": record["metric"], "value": record["value"],
               "unit": record["unit"], "vs_baseline": record["vs_baseline"],
               "mfu": record["detail"]["mfu"],
               "backend": record["detail"]["backend"]}
    # environment stamp (schema_version, git sha, jax/jaxlib, platform):
    # perf_ledger separates toolchain moves from code regressions
    summary["run_meta"] = run_metadata()
    if record["detail"].get("metrics"):
        summary["train_metrics"] = _strip_bulky(record["detail"]["metrics"])
    ov = record["detail"].get("overlap_1b4")
    if ov and "overlap_speedup" in ov:
        # the ROADMAP item 1 acceptance row: both ablation sides' device
        # phase shares + MFU travel with the headline speedup
        summary["overlap_speedup"] = ov["overlap_speedup"]
        summary["overlap_ablation"] = {
            side: {k: ov[side][k] for k in
                   ("tokens_per_sec", "mfu", "gap_share",
                    "gap_plus_comm_share", "comm_s", "comm_s_source",
                    "loss")
                   if k in ov[side]}
            for side in ("off", "on")}
        summary["overlap_loss_parity"] = ov.get("loss_parity")
    gpb = record["detail"].get("goodput")
    if gpb:
        # the ISSUE 18 run-level goodput row: measured-window wall-clock
        # attribution (ratio + nonzero categories), the telescoping bit,
        # and the exact token reconciliation against the headline
        summary["goodput"] = {
            "goodput_ratio": gpb["goodput_ratio"],
            "telescopes": gpb["telescopes"],
            "tokens_reconcile": gpb["tokens_reconcile"],
            "tokens_per_sec": gpb["tokens_per_sec"],
            "categories": gpb["categories"],
        }
    if rung_serving and "goodput_speedup" in rung_serving:
        summary["serving_goodput_tok_s"] = \
            rung_serving["continuous"]["goodput_tok_s"]
        summary["serving_goodput_speedup"] = rung_serving["goodput_speedup"]
        summary["serving_p99_latency_s"] = \
            rung_serving["continuous"]["p99_latency_s"]
        # equal-HBM paged-vs-fixed attribution (the paged-KV tentpole row)
        if rung_serving.get("paged_vs_fixed_speedup") is not None:
            summary["serving_paged_vs_fixed"] = \
                rung_serving["paged_vs_fixed_speedup"]
        # serving-health row (TTFT/queue-wait/occupancy from the metrics
        # registry) so BENCH_r*.json tracks latency attribution, not just
        # aggregate goodput
        summary["serving_metrics"] = _strip_bulky(
            rung_serving.get("metrics"))
    pf = record["detail"].get("prefix_serving_125m")
    if pf and "prefill_savings_ratio" in pf:
        # the prefix-caching acceptance row: prefill-token savings (>=
        # 0.4 target), hit ratio, and the token-identity bit travel with
        # the headline (docs/OBSERVABILITY.md "Serving — prefix cache")
        summary["serving_prefix"] = {
            "prefill_savings_ratio": pf["prefill_savings_ratio"],
            "prefix_hit_ratio": pf["prefix_hit_ratio"],
            "outputs_token_identical": pf["outputs_token_identical"],
            "goodput_speedup": pf["prefix_goodput_speedup"],
            "ttft_p99_on_s": pf["cache_on"]["ttft_p99_s"],
            "ttft_p99_off_s": pf["cache_off"]["ttft_p99_s"],
        }
    qc = record["detail"].get("quant_comm")
    if qc and qc.get("status") == "ok":
        # the ROADMAP item 2 acceptance row: per-op compression (dense-
        # equivalent bytes / wire bytes, both from ONE trace) + per-family
        # loss parity + throughput ratios travel with the headline
        summary["quant_comm"] = {
            "compression": qc["compression"],
            "loss_parity": qc["loss_parity"],
            "speedup": {fam: f["speedup"]
                        for fam, f in qc["families"].items()},
        }
    pi = record["detail"].get("pipe")
    if pi and pi.get("status") == "ok":
        # the ISSUE 16 pipeline acceptance row: per-depth boundary
        # compression (dense-equivalent / wire bytes off the engine's
        # analytic ledger), loss parity, the analytic 1F1B bubble share
        # and the dense-vs-int8 throughput ratio travel with the headline
        summary["pipe"] = {
            "compression": pi["compression"],
            "loss_parity": pi["loss_parity"],
            "bubble_share": pi["bubble_share"],
            "speedup": {r: v["speedup"] for r, v in pi["rungs"].items()},
        }
    st = record["detail"].get("streamed_offload")
    if st and st.get("status") == "ok":
        # the ISSUE 11 streamed-rung acceptance row: relay MB/s + bytes
        # ratio + speedup + loss parity travel with the headline
        summary["streamed_offload"] = {
            k: st[k] for k in ("streamed_speedup", "relay_bytes_ratio",
                               "loss_parity", "gap_share")
            if st.get(k) is not None}
        summary["streamed_offload"]["relay_MBps"] = {
            side: st[side].get("relay_MBps")
            for side in ("bf16", "int8") if isinstance(st.get(side), dict)}
    ht = record["detail"].get("host_tier_serving")
    if ht and "hit_ratio_on" in ht:
        # the KV-host-tier acceptance row: strictly-higher hit ratio at a
        # thrash-sized pool, with token-identical outputs
        summary["serving_host_tier"] = {
            k: ht[k] for k in ("hit_ratio_on", "hit_ratio_off",
                               "outputs_token_identical", "demotes",
                               "promotes", "goodput_speedup")
            if ht.get(k) is not None}
    fc = record["detail"].get("fleet_chaos")
    if fc and "goodput_retention" in fc:
        # the ISSUE 13 resilience row: goodput/TTFT with vs without a
        # replica kill + supervisor restart mid-trace, and the
        # exactly-once / token-identity acceptance bits
        summary["fleet_chaos"] = {
            "goodput_retention": fc["goodput_retention"],
            "goodput_clean_tok_s": fc["clean"]["goodput_tok_s"],
            "goodput_chaos_tok_s": fc["chaos"]["goodput_tok_s"],
            "ttft_p99_clean_s": fc["ttft_p99_clean_s"],
            "ttft_p99_chaos_s": fc["ttft_p99_chaos_s"],
            "restarts_observed": fc["restarts_observed"],
            "shed_429": fc["chaos"]["shed_429"],
            "answered_exactly_once": fc["answered_exactly_once"],
            "outputs_token_identical": fc["outputs_token_identical"],
        }
    dg = record["detail"].get("disagg_serving")
    if dg and "handoff_compression" in dg:
        # the ISSUE 19 disaggregation row: role-split goodput vs the
        # monolithic fleet, user-visible TTFT from streaming, int8 KV
        # handoff wire bytes vs the dense twin, and token identity
        # across the whole role-split × streaming grid
        summary["disagg_serving"] = {
            "disagg_goodput_ratio": dg["disagg_goodput_ratio"],
            "ttft_stream_p50_s": dg["disagg"]["stream"]["ttft_p50_s"],
            "ttft_stream_over_total": dg["ttft_stream_over_total"],
            "handoff_compression": dg["handoff_compression"],
            "outputs_token_identical": dg["outputs_token_identical"],
        }
    er = record["detail"].get("elastic_resume")
    if er and er.get("status") == "ok":
        # the ISSUE 14 elastic-training acceptance row: resume latency +
        # steps-to-recover across the world change, with loss parity
        summary["elastic_resume"] = {
            "resume_latency_s": er["resume_latency_s_max"],
            "steps_to_recover": er["steps_to_recover_max"],
            "loss_parity": er["loss_parity"],
            "world_save": er["world_save"],
            "worlds": er["worlds"],
        }
    line = json.dumps(summary, separators=(",", ":"))
    # enforce the final-line cap: drop the bulkiest optional blocks first
    # (the record line keeps everything); the minimal summary always fits
    for victim in ("serving_metrics", "train_metrics", "overlap_ablation",
                   "goodput", "serving_prefix", "streamed_offload",
                   "serving_host_tier", "fleet_chaos", "disagg_serving",
                   "elastic_resume", "quant_comm", "pipe", "run_meta"):
        if len(line) <= BENCH_SUMMARY_MAX_CHARS:
            break
        if summary.pop(victim, None) is not None:
            summary.setdefault("truncated", []).append(victim)
            line = json.dumps(summary, separators=(",", ":"))
    return ["BENCH_JSON: " + line, line]


def emit_summary(record: dict, rung_serving) -> None:
    """THE bench stdout contract: the full record line, the
    ``BENCH_JSON:``-prefixed summary, then the SAME summary as the
    literal LAST stdout line — every line flushed, and nothing may print
    after this (the runner parses the final line).  ``main`` calls this
    as its last statement."""
    import sys

    print(json.dumps(record), flush=True)
    for line in summary_lines(record, rung_serving):
        print(line, flush=True)
    sys.stdout.flush()


if __name__ == "__main__":
    main()
