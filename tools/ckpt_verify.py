#!/usr/bin/env python
"""Offline checkpoint manifest verifier — audit a checkpoint dir from any
box (no jax import, like fleet_dump).

    python tools/ckpt_verify.py /ckpts            # a save dir of tags
    python tools/ckpt_verify.py /ckpts/global_step100   # one tag
    python tools/ckpt_verify.py --fast /ckpts     # existence+size only
    python tools/ckpt_verify.py --deep /ckpts     # + per-chunk sha256
    python tools/ckpt_verify.py --json /ckpts     # machine-readable
    python tools/ckpt_verify.py --selftest        # tier-1 wired

Checks each tag's ``MANIFEST.json`` (docs/RESILIENCE.md schema: per-file
size + sha256, world_size, zero_stage, format version) against the bytes
on disk, reports which tag the ``latest`` pointer names, and flags
leftover ``tmp.<tag>`` staging debris from crashed saves (harmless — the
next save clears it — but a large one is reclaimable space).

``--deep`` additionally re-hashes every CHUNK the sharded payload's
``index_p*.json`` records (the per-chunk sha256 the writer stores), so a
flipped bit is reported with the offending shard path AND pytree leaf —
and index-vs-file structural drift (out-of-range chunks, under-covered
leaves from missing shard files) is caught even when every file hash
matches its manifest entry.

Exit status: 0 when the checkpoint the loader would pick (``latest``, or
the single dir given) verifies valid — including when ``latest`` is
corrupt but an older valid tag exists for the walk-back; 1 when nothing
valid is loadable; 2 on usage errors.

States per tag: ``valid`` | ``corrupt`` (manifest contradicted by disk)
| ``no_manifest`` (pre-manifest save: loadable but unverifiable) |
``missing``.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from metrics_dump import render_table  # noqa: E402


def _load_atomic():
    """The repo's stdlib-only atomic-checkpoint module WITHOUT importing
    the ``deepspeed_tpu`` package (whose ``__init__`` pulls in jax):
    reuse it when already loaded (tests), else exec by file path."""
    mod = sys.modules.get("deepspeed_tpu.runtime.checkpoint_engine.atomic")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "runtime", "checkpoint_engine",
                        "atomic.py")
    spec = importlib.util.spec_from_file_location("_ds_ckpt_atomic", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


atomic = _load_atomic()


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for name in files:
            try:
                total += os.path.getsize(os.path.join(root, name))
            except OSError:
                pass
    return total


def verify_tag(save_dir: str, tag: str, level: str) -> Dict[str, object]:
    path = os.path.join(save_dir, tag)
    st = atomic.verify_dir(path, level="full" if level == "deep" else level)
    state, problems = st.state, list(st.problems)
    if level == "deep" and state in ("valid", "corrupt"):
        # chunk-level pass: even for a tag the manifest already convicts,
        # the deep report NAMES the offending shard/leaf
        deep_problems = atomic.deep_verify(path)
        if deep_problems:
            state = "corrupt"
            problems.extend(deep_problems)
    entry: Dict[str, object] = {"tag": tag, "state": state,
                                "problems": problems,
                                "bytes": _dir_bytes(path)}
    if st.manifest:
        entry["files"] = len(st.manifest.get("files", {}))
        for k in ("world_size", "zero_stage", "global_steps",
                  "format_version"):
            if k in st.manifest:
                entry[k] = st.manifest[k]
    return entry


def audit(save_dir: str, level: str = "full") -> Dict[str, object]:
    """Verify every tag in a save dir; the report the table/JSON render."""
    latest = atomic.read_latest(save_dir)
    tags = atomic.list_tags(save_dir)
    if latest and latest not in tags:
        tags = [latest] + tags            # dangling pointer: show it
    entries = [verify_tag(save_dir, t, level) for t in tags]
    debris = [n for n in (os.listdir(save_dir)
                          if os.path.isdir(save_dir) else [])
              if n.startswith((atomic.TMP_PREFIX, atomic.TRASH_PREFIX))]
    valid = [e["tag"] for e in entries if e["state"] == "valid"]
    loadable: Optional[str] = None
    if latest in valid:
        loadable = latest
    elif valid:
        loadable = valid[0]               # the loader's walk-back target
    return {"save_dir": save_dir, "latest": latest, "loadable": loadable,
            "level": level, "tags": entries,
            "stage_debris": [{"name": n,
                              "bytes": _dir_bytes(os.path.join(save_dir, n))}
                             for n in sorted(debris)]}


def render(report: Dict[str, object]) -> str:
    rows: List[List[str]] = []
    latest = report["latest"]
    for e in report["tags"]:
        mark = " <- latest" if e["tag"] == latest else ""
        rows.append([str(e["tag"]) + mark, str(e["state"]),
                     str(e.get("files", "")), f"{e['bytes']:,}",
                     "; ".join(e["problems"][:2])])
    for d in report["stage_debris"]:
        what = ("crashed-publish leftovers (next save's GC sweeps)"
                if d["name"].startswith(atomic.TRASH_PREFIX)
                else "crashed save leftovers (next save clears)")
        rows.append([d["name"], "stage-debris", "", f"{d['bytes']:,}", what])
    lines = list(render_table(["tag", "state", "files", "bytes", "detail"],
                              rows))
    if report["loadable"]:
        suffix = ("" if report["loadable"] == latest
                  else f" (walk-back: latest={latest!r} is not valid)")
        lines.append(f"loadable: {report['loadable']}{suffix}")
    else:
        lines.append("loadable: NONE — no tag verifies valid")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# selftest (tier-1 wired: tests/unit/test_resilience.py)
# ---------------------------------------------------------------------------


def _make_tag(save_dir: str, tag: str, payload: bytes) -> str:
    path = os.path.join(save_dir, tag)
    os.makedirs(os.path.join(path, "model_states"))
    with open(os.path.join(path, "model_states", "shard_p0.bin"), "wb") as fh:
        fh.write(payload)
    with open(os.path.join(path, "client_state.json"), "w") as fh:
        json.dump({"client_state": {}}, fh)
    atomic.write_manifest(path, tag, extra={"world_size": 1,
                                            "zero_stage": 0})
    return path


def selftest() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        a = _make_tag(td, "global_step1", b"x" * 2048)
        b = _make_tag(td, "global_step2", b"y" * 2048)
        atomic.write_latest(td, "global_step2")
        rep = audit(td)
        assert rep["latest"] == "global_step2"
        assert rep["loadable"] == "global_step2"
        assert all(e["state"] == "valid" for e in rep["tags"]), rep

        # torn tail: size check catches it even at --fast
        with open(os.path.join(b, "model_states", "shard_p0.bin"),
                  "rb+") as fh:
            fh.truncate(100)
        rep = audit(td, level="fast")
        by = {e["tag"]: e["state"] for e in rep["tags"]}
        assert by["global_step2"] == "corrupt"
        assert rep["loadable"] == "global_step1"      # the walk-back target

        # restore size, flip one bit: only a full checksum pass catches it
        with open(os.path.join(b, "model_states", "shard_p0.bin"),
                  "rb+") as fh:
            fh.write(b"y" * 2048)
            fh.seek(512)
            fh.write(b"z")
        assert audit(td, level="fast")["loadable"] == "global_step2"
        rep = audit(td, level="full")
        assert rep["loadable"] == "global_step1"
        bad = [e for e in rep["tags"] if e["tag"] == "global_step2"][0]
        assert any("checksum" in p for p in bad["problems"])

        # stage debris is reported, never treated as a tag
        os.makedirs(os.path.join(td, atomic.TMP_PREFIX + "global_step3"))
        rep = audit(td)
        assert [d["name"] for d in rep["stage_debris"]] == \
            ["tmp.global_step3"]
        assert all(e["tag"] != "tmp.global_step3" for e in rep["tags"])

        # missing latest target: dangling pointer shows as missing,
        # walk-back still finds step1
        import shutil

        shutil.rmtree(b)
        rep = audit(td)
        by = {e["tag"]: e["state"] for e in rep["tags"]}
        assert by["global_step2"] == "missing"
        assert rep["loadable"] == "global_step1"

        table = render(rep)
        assert "global_step1" in table and "walk-back" in table

        # no manifest at all (legacy layout): unverifiable, not loadable
        # by the verifier's standard (the engine may still accept it)
        os.remove(os.path.join(a, atomic.MANIFEST_NAME))
        rep = audit(td)
        assert rep["loadable"] is None
        assert any(e["state"] == "no_manifest" for e in rep["tags"])

    # --deep: per-chunk hashes name the offending shard + leaf, and
    # structural drift (index pointing past the file) is caught
    import hashlib
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        tag = os.path.join(td, "global_step9")
        ms = os.path.join(tag, "model_states")
        os.makedirs(ms)
        raw_a, raw_b = b"\x01" * 256, b"\x02" * 128
        with open(os.path.join(ms, "shard_p0.bin"), "wb") as fh:
            fh.write(raw_a + raw_b)
        index = {"['w']": {"shape": [64], "dtype": "float32",
                           "chunks": [{"index": [[0, 64]],
                                       "file": "shard_p0.bin", "offset": 0,
                                       "nbytes": 256,
                                       "sha256": hashlib.sha256(raw_a)
                                       .hexdigest()}]},
                 "['b']": {"shape": [32], "dtype": "float32",
                           "chunks": [{"index": [[0, 32]],
                                       "file": "shard_p0.bin",
                                       "offset": 256, "nbytes": 128,
                                       "sha256": hashlib.sha256(raw_b)
                                       .hexdigest()}]}}
        with open(os.path.join(ms, "index_p0.json"), "w") as fh:
            json.dump(index, fh)
        atomic.write_manifest(tag, "global_step9",
                              extra={"world_size": 1, "zero_stage": 0})
        atomic.write_latest(td, "global_step9")
        assert atomic.deep_verify(tag) == []
        rep = audit(td, level="deep")
        assert rep["loadable"] == "global_step9"

        # flip a bit inside leaf 'b''s chunk: --deep names shard AND leaf
        with open(os.path.join(ms, "shard_p0.bin"), "rb+") as fh:
            fh.seek(300)
            fh.write(b"\xff")
        probs = atomic.deep_verify(tag)
        assert any("['b']" in p and "shard_p0.bin" in p
                   and "chunk checksum" in p for p in probs), probs
        assert not any("['w']" in p for p in probs), probs
        rep = audit(td, level="deep")
        assert rep["tags"][0]["state"] == "corrupt"
        assert rep["loadable"] is None
        # plain --fast never looks inside the chunks (size unchanged)
        assert audit(td, level="fast")["loadable"] == "global_step9"

        # structural drift: an index chunk pointing past the shard file
        with open(os.path.join(ms, "shard_p0.bin"), "rb+") as fh:
            fh.truncate(200)
        probs = atomic.deep_verify(tag)
        assert any("outside shard file" in p for p in probs), probs
        assert any("under-covered" in p for p in probs), probs
    print("ckpt_verify selftest: OK")
    return 0


# ---------------------------------------------------------------------------


def main(argv: List[str]) -> int:
    args = [a for a in argv[1:] if not a.startswith("--")]
    flags = {a for a in argv[1:] if a.startswith("--")}
    if "--selftest" in flags:
        return selftest()
    if not args or "--help" in flags or "-h" in argv[1:]:
        print(__doc__.strip())
        return 0 if args else 2
    target = args[0]
    level = ("deep" if "--deep" in flags
             else "fast" if "--fast" in flags else "full")
    if os.path.exists(os.path.join(target, atomic.MANIFEST_NAME)):
        # a single tag dir: report it alone
        save_dir, tag = os.path.split(os.path.abspath(target.rstrip("/")))
        entry = verify_tag(save_dir, tag, level)
        report = {"save_dir": save_dir, "latest": None,
                  "loadable": tag if entry["state"] == "valid" else None,
                  "level": level, "tags": [entry], "stage_debris": []}
    elif os.path.isdir(target):
        report = audit(target, level=level)
    else:
        print(f"no such directory: {target}", file=sys.stderr)
        return 2
    if "--json" in flags:
        print(json.dumps(report, sort_keys=True, default=str))
    else:
        print(render(report))
    return 0 if report["loadable"] else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
