#!/usr/bin/env python
"""Offline device-truth report from a jax profiler trace directory.

The same analysis ``/profilez`` runs on a live engine
(``deepspeed_tpu/profiling/device_trace.py``), pointed at a trace on disk:

    python tools/trace_report.py /tmp/ds_trace            # terminal tables
    python tools/trace_report.py /tmp/ds_trace --steps 2  # per-step columns
    python tools/trace_report.py /tmp/ds_trace --json     # machine-readable
    python tools/trace_report.py --timeline export.json   # span-lane render
    python tools/trace_report.py --history profile_history  # continuous ring

``--timeline`` renders a TRACE-EVENT EXPORT instead of a device trace:
anything emitted through the repo's shared perfetto envelope — a
replica's ``/requestz?format=perfetto`` request spans, a training
process's ``/requestz?kind=train&format=perfetto`` step timeline, the
router's hop export, or a ``fleet_dump --trace`` merged session — goes
through ONE render path (lane summary + recent slices + instants), so
train and serve timelines read identically.

Accepts any directory containing a ``perfetto_trace.json.gz`` (captures
made with ``profile_trace`` + this repo's perfetto flag, ``/profilez``, or
the watchdog) or a direct path to the file.  Shows the phase breakdown
(fwd_bwd / optimizer / comm / other / gap — gap is device idle, the
overlap headroom), the device-true per-collective table, and the serving
dispatch-slack numbers when ``ds_serve_*`` ranges are present.

``--selftest`` writes a bundled synthetic trace to a temp dir and runs
the full parse + render on it, asserting the phase partition (wired as a
tier-1 unit test so this offline tool cannot silently rot).

Zero dependencies beyond the repo's stdlib-only modules — **no jax
import** (the analysis module loads by file path, the fleet_dump idiom;
dslint rule DSL003 pins the whole closure): the trace file itself is
plain gzip'd trace-event JSON, so a scraped ``/profilez`` capture can be
analyzed on an operator box with no jax install.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_device_trace():
    """The device-truth post-processor, WITHOUT jax: when the package is
    already imported in this process, reuse its module (one broker, one
    registry); otherwise load ``device_trace.py`` by file path under STUB
    parent packages, so the jax-pulling ``deepspeed_tpu/__init__`` never
    executes — device_trace and its stdlib-only dependency chain
    (monitor.comms / flight_recorder / metrics, utils.logging) use
    relative imports precisely so this works (dslint rule DSL003 keeps
    that closure jax-free)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.profiling import device_trace

        return device_trace
    mod = sys.modules.get("_dst.profiling.device_trace")
    if mod is not None:
        return mod
    import importlib.util
    import types

    # PRIVATE root name ("_dst", like router's "_ds_router"): registering
    # stubs under the real package names would shadow a later genuine
    # `import deepspeed_tpu` in this process with contentless modules
    pkg_dir = os.path.join(_REPO, "deepspeed_tpu")
    for name, sub in (("_dst", None),
                      ("_dst.monitor", "monitor"),
                      ("_dst.utils", "utils"),
                      ("_dst.profiling", "profiling")):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = [os.path.join(pkg_dir, sub) if sub else pkg_dir]
            sys.modules[name] = stub
    path = os.path.join(pkg_dir, "profiling", "device_trace.py")
    spec = importlib.util.spec_from_file_location(
        "_dst.profiling.device_trace", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_dst.profiling.device_trace"] = mod
    spec.loader.exec_module(mod)
    return mod


device_trace = _load_device_trace()


def _load_continuous():
    """The continuous-profiler offline half (history ring + window
    differ + render), same no-jax contract: reuse the live module when
    the package is imported, else load ``continuous.py`` by file path
    under the ``_dst`` stubs (its relative ``from .device_trace import``
    resolves against the module loaded above)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.profiling import continuous

        return continuous
    mod = sys.modules.get("_dst.profiling.continuous")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(_REPO, "deepspeed_tpu", "profiling", "continuous.py")
    spec = importlib.util.spec_from_file_location(
        "_dst.profiling.continuous", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_dst.profiling.continuous"] = mod
    spec.loader.exec_module(mod)
    return mod


continuous = _load_continuous()


def _table(header: List[str], rows: List[List[str]]) -> str:
    table = [header] + rows
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def render(summary: dict) -> str:
    out = [f"trace: {summary['source']}"]
    if summary["degraded"]:
        out.append("NOTE: no device rows in this trace — the breakdown "
                   "below is HOST-range attribution (degraded mode)")
    elif summary.get("host_scoped"):
        out.append("host-bracketed scopes (device durations, host-range "
                   "assignment): " + ", ".join(summary["host_scoped"]))
    steps = summary.get("steps")
    window = summary["window_s"]
    busy = summary["device_busy_s"]
    out.append(f"window {_fmt_s(window)}"
               + (f" over {steps} step(s)" if steps else "")
               + f", device busy {_fmt_s(busy)}"
               + (f" ({100 * busy / window:.1f}%)" if window else ""))
    ph = summary["phases"]
    per = summary.get("per_step")
    rows = []
    for key in ("fwd_bwd_s", "optimizer_s", "comm_s", "other_s", "gap_s"):
        name = key[:-2]
        share = 100 * ph[key] / window if window else 0.0
        rows.append([name, _fmt_s(ph[key]), f"{share:.1f}%",
                     _fmt_s(per[key]) if per else ""])
    out.append("")
    out.append(_table(["phase", "total", "share", "per-step"], rows))
    cd = summary.get("comm_device") or {}
    if cd:
        crows = [[op, str(rec["count"]), _fmt_s(rec["seconds"]),
                  _fmt_s(rec["max_s"])]
                 for op, rec in sorted(cd.items(),
                                       key=lambda kv: -kv[1]["seconds"])]
        out.append("")
        out.append("device-true collectives (union per scope; compare with "
                   "the analytic ds_comm_*_seconds attribution):")
        out.append(_table(["collective", "spans", "device_s", "max_span"],
                          crows))
    serve = summary.get("serve")
    if serve:
        out.append("")
        out.append(
            f"serving: {serve['decode_blocks']} decode block(s), host "
            f"dispatch {_fmt_s(serve['decode_host_s'])}, device "
            f"{_fmt_s(serve['decode_device_s'])}, dispatch slack "
            f"{_fmt_s(serve['dispatch_slack_s'])}"
            + (f"; prefill host {_fmt_s(serve['prefill_host_s'])} / "
               f"device {_fmt_s(serve['prefill_device_s'])}"
               if serve.get("prefill_host_s") else ""))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# --timeline: render any shared-envelope trace-event export (serve request
# spans, train step timeline, router hops, fleet_dump --trace merges)
# ---------------------------------------------------------------------------


def load_timeline(path: str) -> dict:
    """A trace-event export file (plain or gzipped JSON; a bare event
    list is wrapped)."""
    import gzip

    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        doc = {"traceEvents": doc}
    return doc


def render_timeline(doc: dict, recent: int = 24) -> str:
    """ONE code path over the repo's shared perfetto envelope: lanes
    (process:thread) summarized by span count/total duration/window,
    the most recent ``recent`` slices named with their trace ids, and
    instant events listed — whether the export came from a serving
    request tracer, a training step timeline, a router hop log, or a
    merged fleet session."""
    evs = [e for e in (doc.get("traceEvents") or [])
           if isinstance(e, dict)]
    pname = {}
    tname = {}
    for e in evs:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pname[e.get("pid")] = (e.get("args") or {}).get("name", "")
        elif e.get("name") == "thread_name":
            tname[(e.get("pid"), e.get("tid"))] = \
                (e.get("args") or {}).get("name", "")

    def lane(e):
        p = pname.get(e.get("pid"), f"pid {e.get('pid')}")
        t = tname.get((e.get("pid"), e.get("tid")), f"tid {e.get('tid')}")
        return f"{p}:{t}"

    spans = [e for e in evs if e.get("ph") == "X"]
    instants = [e for e in evs if e.get("ph") == "i"]
    other = doc.get("otherData") or {}
    out = [f"timeline: {len(spans)} span(s), {len(instants)} instant(s) "
           f"across {len(pname) or 1} process(es)"]
    if other.get("clock_anchor_unix") is not None:
        out.append(f"clock: anchor_unix={other['clock_anchor_unix']}"
                   + (f" source={other['clock_source']}"
                      if other.get("clock_source") else "")
                   + (f" reference={other['reference']}"
                      if other.get("reference") else ""))
    lanes = {}
    for e in spans:
        rec = lanes.setdefault(lane(e), [0, 0.0, float("inf"), 0.0])
        rec[0] += 1
        rec[1] += float(e.get("dur") or 0.0)
        ts = float(e.get("ts") or 0.0)
        rec[2] = min(rec[2], ts)
        rec[3] = max(rec[3], ts + float(e.get("dur") or 0.0))
    rows = [[name, str(c), _fmt_s(tot * 1e-6),
             _fmt_s(max(0.0, hi - lo) * 1e-6)]
            for name, (c, tot, lo, hi) in sorted(lanes.items())]
    if rows:
        out.append("")
        out.append(_table(["lane", "spans", "busy", "window"], rows))
    if spans:
        srows = []
        for e in sorted(spans, key=lambda e: float(e.get("ts") or 0.0)
                        )[-max(0, recent):]:
            args = e.get("args") or {}
            srows.append([str(e.get("name")), lane(e),
                          f"{float(e.get('ts') or 0.0):.1f}",
                          _fmt_s(float(e.get("dur") or 0.0) * 1e-6),
                          str(args.get("trace", ""))[:8]])
        out.append("")
        out.append(_table(["span", "lane", "ts_us", "dur", "trace"],
                          srows))
    for e in sorted(instants, key=lambda e: float(e.get("ts") or 0.0)):
        out.append(f"@{float(e.get('ts') or 0.0):.1f}us {e.get('name')} "
                   f"{json.dumps(e.get('args') or {}, sort_keys=True)}")
    return "\n".join(out)


def _selftest_trace(path: str) -> str:
    """Bundled synthetic fixture: one device process with two 100us steps
    (fwd_bwd ops with a nested all_gather, an optimizer fusion on the
    name-scope lane, a trailing reduce_scatter, 10us idle), plus a host
    dispatch range — the exact shapes the classifier must keep parsing."""
    import gzip

    def meta(pid, pname, threads):
        evs = [{"ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": pname}}]
        for tid, tname in threads:
            evs.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
        return evs

    def x(name, pid, tid, ts, dur, args=None):
        e = {"ph": "X", "name": name, "pid": pid, "tid": tid,
             "ts": float(ts), "dur": float(dur)}
        if args:
            e["args"] = args
        return e

    evs = meta(1, "/device:TPU:0", [(10, "XLA Ops"),
                                    (11, "TensorFlow Name Scope")])
    evs += meta(2, "/host:CPU", [(20, "python")])
    for base in (0, 100):
        evs.append(x("fusion.1", 1, 10, base, 20,
                     {"tf_op": "jit_step/ds_fwd_bwd/fusion.1"}))
        evs.append(x("all-gather.2", 1, 10, base + 20, 20,
                     {"tf_op": "jit_step/ds_fwd_bwd/ds_comm_all_gather/"
                               "ag.2"}))
        evs.append(x("fusion.3", 1, 10, base + 40, 20,
                     {"tf_op": "jit_step/ds_fwd_bwd/fusion.3"}))
        evs.append(x("fusion.4", 1, 10, base + 60, 20))
        evs.append(x("ds_optimizer_step", 1, 11, base + 60, 20))
        evs.append(x("reduce-scatter.5", 1, 10, base + 80, 10,
                     {"tf_op": "jit_step/ds_comm_reduce_scatter/rs.5"}))
        evs.append(x("ds_fwd_bwd", 2, 20, base, 55))
    p = os.path.join(path, "perfetto_trace.json.gz")
    with gzip.open(p, "wt") as fh:
        json.dump({"displayTimeUnit": "ns", "traceEvents": evs}, fh)
    return p


def selftest() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(
            prefix="ds_trace_report_selftest_") as d:
        return _selftest_in(d)


def _selftest_in(d: str) -> int:
    _selftest_trace(d)
    summary = device_trace.summarize_trace(d, steps=2)
    ph = summary["phases"]
    assert not summary["degraded"], summary
    # the five phases partition the window exactly (the core invariant)
    assert abs(sum(ph.values()) - summary["window_s"]) < 1e-12, summary
    assert abs(ph["fwd_bwd_s"] - 80e-6) < 1e-12, ph      # 2 x (60-20)us
    assert abs(ph["comm_s"] - 60e-6) < 1e-12, ph         # 2 x (20+10)us
    assert abs(ph["gap_s"] - 10e-6) < 1e-12, ph          # inter-step idle
    assert summary["window_lo_us"] == 0.0
    assert summary["window_hi_us"] == 190.0
    assert "all_gather" in summary["comm_device"]
    text = render(summary)
    assert "fwd_bwd" in text and "all_gather" in text
    print(text)
    # --timeline: the SAME renderer over a serve-shaped and a
    # train-shaped export (the shared-envelope contract)
    serve_doc = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "ds_requests"}},
        {"ph": "M", "pid": 1, "tid": 3, "name": "thread_name",
         "args": {"name": "req 3"}},
        {"ph": "X", "pid": 1, "tid": 3, "ts": 10.0, "dur": 40.0,
         "name": "decode", "args": {"trace": "ab" * 16}}],
        "otherData": {"clock_anchor_unix": 10.0,
                      "clock_source": "process"}}
    train_doc = {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "ds_train_steps"}},
        {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
         "args": {"name": "steps"}},
        {"ph": "X", "pid": 1, "tid": 1, "ts": 0.0, "dur": 100.0,
         "name": "step 1", "args": {"step": 1, "bubble_share": 0.25}},
        {"ph": "i", "pid": 1, "tid": 4, "ts": 50.0, "s": "t",
         "name": "anomaly_skip", "args": {"step": 1}}],
        "otherData": {"clock_anchor_unix": 10.0,
                      "clock_source": "process"}}
    st = render_timeline(serve_doc)
    assert "ds_requests:req 3" in st and "decode" in st \
        and "abababab" in st, st
    tt = render_timeline(train_doc)
    assert "ds_train_steps:steps" in tt and "step 1" in tt \
        and "anomaly_skip" in tt, tt
    print(tt)
    # --history: a two-window ring with a seeded comm regression must
    # name the scope; a clean twin must stay quiet (the golden-fixture
    # contract the live differ shares)
    hist = os.path.join(d, "profile_history")
    base = {"engine": "train", "step": 10, "steps": 2, "window_s": 0.2,
            "device_busy_s": 0.18, "busy_ratio": 0.9,
            "coverage_ratio": 0.01, "overhead_ratio": 0.004,
            "scopes": {"fwd_bwd": 0.06, "optimizer": 0.01,
                       "comm": 0.02, "other": 0.005, "gap": 0.005}}
    ring = continuous.HistoryRing(hist)
    ring.append(json.loads(json.dumps(base)))
    slow = json.loads(json.dumps(base))
    slow["step"] = 20
    slow["scopes"]["comm"] = 0.04          # +100% > 25% tolerance
    ring.append(slow)
    ht = render_history(hist)
    assert "REGRESSIONS" in ht and "comm:" in ht, ht
    clean = os.path.join(d, "profile_history_clean")
    cring = continuous.HistoryRing(clean)
    cring.append(json.loads(json.dumps(base)))
    cring.append(json.loads(json.dumps(base)))
    assert "no regressions" in render_history(clean)
    print(ht)
    print("trace_report selftest: OK")
    return 0


def render_history(directory: str, n: int = 2) -> str:
    """The newest continuous-profiler windows from a ``profile_history/``
    ring directory (docs/OBSERVABILITY.md "Continuous profiling"): the
    latest window rendered in full, plus the window-over-window differ
    verdict against its predecessor — the same differ that fires the
    ``prof_regression`` flight event on the live engine."""
    windows = continuous.HistoryRing(directory).latest(max(2, n))
    if not windows:
        return (f"(no ds_prof_window_*.json in {directory} — is the "
                "continuous profiler enabled?)")
    out = [continuous.render_window(windows[-1])]
    if len(windows) >= 2:
        regs = continuous.diff_windows(windows[-2], windows[-1])
        if regs:
            out.append("")
            out.append("REGRESSIONS vs window "
                       f"#{windows[-2].get('seq', '?')}:")
            for r in regs:
                out.append(f"  {r['scope']}: {r['prev_s'] * 1e3:.4f}ms -> "
                           f"{r['cur_s'] * 1e3:.4f}ms per step "
                           f"(+{100 * r['rel']:.1f}%, tol "
                           f"{100 * r['tol']:.0f}%)")
        else:
            out.append(f"no regressions vs window "
                       f"#{windows[-2].get('seq', '?')}")
    return "\n".join(out)


def main(argv: List[str]) -> int:
    import argparse

    if "--selftest" in argv[1:]:
        return selftest()
    ap = argparse.ArgumentParser(
        description="device-truth report from a jax profiler trace")
    ap.add_argument("trace", help="trace dir (or perfetto_trace.json.gz)")
    ap.add_argument("--steps", type=int, default=None,
                    help="steps in the captured window (per-step column; "
                         "inferred from ds_optimizer_step spans when absent)")
    ap.add_argument("--timeline", action="store_true",
                    help="render the argument as a trace-event EXPORT "
                         "(/requestz perfetto, step timeline, or a "
                         "fleet_dump --trace merge) instead of a device "
                         "trace dir")
    ap.add_argument("--history", action="store_true",
                    help="render the argument as a continuous-profiler "
                         "profile_history/ ring directory: newest window "
                         "+ window-over-window regression verdict")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of tables")
    ns = ap.parse_args(argv[1:])
    if ns.history:
        if ns.json:
            windows = continuous.HistoryRing(ns.trace).latest(2)
            print(json.dumps(
                {"windows": windows,
                 "regressions": (continuous.diff_windows(*windows[-2:])
                                 if len(windows) >= 2 else [])},
                sort_keys=True))
        else:
            print(render_history(ns.trace))
        return 0
    if ns.timeline:
        try:
            doc = load_timeline(ns.trace)
        except (OSError, ValueError) as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        if ns.json:
            print(json.dumps(doc, sort_keys=True))
        else:
            print(render_timeline(doc))
        return 0
    try:
        summary = device_trace.summarize_trace(ns.trace, steps=ns.steps)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if ns.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
