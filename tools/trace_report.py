#!/usr/bin/env python
"""Offline device-truth report from a jax profiler trace directory.

The same analysis ``/profilez`` runs on a live engine
(``deepspeed_tpu/profiling/device_trace.py``), pointed at a trace on disk:

    python tools/trace_report.py /tmp/ds_trace            # terminal tables
    python tools/trace_report.py /tmp/ds_trace --steps 2  # per-step columns
    python tools/trace_report.py /tmp/ds_trace --json     # machine-readable

Accepts any directory containing a ``perfetto_trace.json.gz`` (captures
made with ``profile_trace`` + this repo's perfetto flag, ``/profilez``, or
the watchdog) or a direct path to the file.  Shows the phase breakdown
(fwd_bwd / optimizer / comm / other / gap — gap is device idle, the
overlap headroom), the device-true per-collective table, and the serving
dispatch-slack numbers when ``ds_serve_*`` ranges are present.

``--selftest`` writes a bundled synthetic trace to a temp dir and runs
the full parse + render on it, asserting the phase partition (wired as a
tier-1 unit test so this offline tool cannot silently rot).

Zero dependencies beyond the repo's stdlib-only modules — **no jax
import** (the analysis module loads by file path, the fleet_dump idiom;
dslint rule DSL003 pins the whole closure): the trace file itself is
plain gzip'd trace-event JSON, so a scraped ``/profilez`` capture can be
analyzed on an operator box with no jax install.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_device_trace():
    """The device-truth post-processor, WITHOUT jax: when the package is
    already imported in this process, reuse its module (one broker, one
    registry); otherwise load ``device_trace.py`` by file path under STUB
    parent packages, so the jax-pulling ``deepspeed_tpu/__init__`` never
    executes — device_trace and its stdlib-only dependency chain
    (monitor.comms / flight_recorder / metrics, utils.logging) use
    relative imports precisely so this works (dslint rule DSL003 keeps
    that closure jax-free)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.profiling import device_trace

        return device_trace
    mod = sys.modules.get("_dst.profiling.device_trace")
    if mod is not None:
        return mod
    import importlib.util
    import types

    # PRIVATE root name ("_dst", like router's "_ds_router"): registering
    # stubs under the real package names would shadow a later genuine
    # `import deepspeed_tpu` in this process with contentless modules
    pkg_dir = os.path.join(_REPO, "deepspeed_tpu")
    for name, sub in (("_dst", None),
                      ("_dst.monitor", "monitor"),
                      ("_dst.utils", "utils"),
                      ("_dst.profiling", "profiling")):
        if name not in sys.modules:
            stub = types.ModuleType(name)
            stub.__path__ = [os.path.join(pkg_dir, sub) if sub else pkg_dir]
            sys.modules[name] = stub
    path = os.path.join(pkg_dir, "profiling", "device_trace.py")
    spec = importlib.util.spec_from_file_location(
        "_dst.profiling.device_trace", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_dst.profiling.device_trace"] = mod
    spec.loader.exec_module(mod)
    return mod


device_trace = _load_device_trace()


def _table(header: List[str], rows: List[List[str]]) -> str:
    table = [header] + rows
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def render(summary: dict) -> str:
    out = [f"trace: {summary['source']}"]
    if summary["degraded"]:
        out.append("NOTE: no device rows in this trace — the breakdown "
                   "below is HOST-range attribution (degraded mode)")
    elif summary.get("host_scoped"):
        out.append("host-bracketed scopes (device durations, host-range "
                   "assignment): " + ", ".join(summary["host_scoped"]))
    steps = summary.get("steps")
    window = summary["window_s"]
    busy = summary["device_busy_s"]
    out.append(f"window {_fmt_s(window)}"
               + (f" over {steps} step(s)" if steps else "")
               + f", device busy {_fmt_s(busy)}"
               + (f" ({100 * busy / window:.1f}%)" if window else ""))
    ph = summary["phases"]
    per = summary.get("per_step")
    rows = []
    for key in ("fwd_bwd_s", "optimizer_s", "comm_s", "other_s", "gap_s"):
        name = key[:-2]
        share = 100 * ph[key] / window if window else 0.0
        rows.append([name, _fmt_s(ph[key]), f"{share:.1f}%",
                     _fmt_s(per[key]) if per else ""])
    out.append("")
    out.append(_table(["phase", "total", "share", "per-step"], rows))
    cd = summary.get("comm_device") or {}
    if cd:
        crows = [[op, str(rec["count"]), _fmt_s(rec["seconds"]),
                  _fmt_s(rec["max_s"])]
                 for op, rec in sorted(cd.items(),
                                       key=lambda kv: -kv[1]["seconds"])]
        out.append("")
        out.append("device-true collectives (union per scope; compare with "
                   "the analytic ds_comm_*_seconds attribution):")
        out.append(_table(["collective", "spans", "device_s", "max_span"],
                          crows))
    serve = summary.get("serve")
    if serve:
        out.append("")
        out.append(
            f"serving: {serve['decode_blocks']} decode block(s), host "
            f"dispatch {_fmt_s(serve['decode_host_s'])}, device "
            f"{_fmt_s(serve['decode_device_s'])}, dispatch slack "
            f"{_fmt_s(serve['dispatch_slack_s'])}"
            + (f"; prefill host {_fmt_s(serve['prefill_host_s'])} / "
               f"device {_fmt_s(serve['prefill_device_s'])}"
               if serve.get("prefill_host_s") else ""))
    return "\n".join(out)


def _selftest_trace(path: str) -> str:
    """Bundled synthetic fixture: one device process with two 100us steps
    (fwd_bwd ops with a nested all_gather, an optimizer fusion on the
    name-scope lane, a trailing reduce_scatter, 10us idle), plus a host
    dispatch range — the exact shapes the classifier must keep parsing."""
    import gzip

    def meta(pid, pname, threads):
        evs = [{"ph": "M", "pid": pid, "name": "process_name",
                "args": {"name": pname}}]
        for tid, tname in threads:
            evs.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
        return evs

    def x(name, pid, tid, ts, dur, args=None):
        e = {"ph": "X", "name": name, "pid": pid, "tid": tid,
             "ts": float(ts), "dur": float(dur)}
        if args:
            e["args"] = args
        return e

    evs = meta(1, "/device:TPU:0", [(10, "XLA Ops"),
                                    (11, "TensorFlow Name Scope")])
    evs += meta(2, "/host:CPU", [(20, "python")])
    for base in (0, 100):
        evs.append(x("fusion.1", 1, 10, base, 20,
                     {"tf_op": "jit_step/ds_fwd_bwd/fusion.1"}))
        evs.append(x("all-gather.2", 1, 10, base + 20, 20,
                     {"tf_op": "jit_step/ds_fwd_bwd/ds_comm_all_gather/"
                               "ag.2"}))
        evs.append(x("fusion.3", 1, 10, base + 40, 20,
                     {"tf_op": "jit_step/ds_fwd_bwd/fusion.3"}))
        evs.append(x("fusion.4", 1, 10, base + 60, 20))
        evs.append(x("ds_optimizer_step", 1, 11, base + 60, 20))
        evs.append(x("reduce-scatter.5", 1, 10, base + 80, 10,
                     {"tf_op": "jit_step/ds_comm_reduce_scatter/rs.5"}))
        evs.append(x("ds_fwd_bwd", 2, 20, base, 55))
    p = os.path.join(path, "perfetto_trace.json.gz")
    with gzip.open(p, "wt") as fh:
        json.dump({"displayTimeUnit": "ns", "traceEvents": evs}, fh)
    return p


def selftest() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(
            prefix="ds_trace_report_selftest_") as d:
        return _selftest_in(d)


def _selftest_in(d: str) -> int:
    _selftest_trace(d)
    summary = device_trace.summarize_trace(d, steps=2)
    ph = summary["phases"]
    assert not summary["degraded"], summary
    # the five phases partition the window exactly (the core invariant)
    assert abs(sum(ph.values()) - summary["window_s"]) < 1e-12, summary
    assert abs(ph["fwd_bwd_s"] - 80e-6) < 1e-12, ph      # 2 x (60-20)us
    assert abs(ph["comm_s"] - 60e-6) < 1e-12, ph         # 2 x (20+10)us
    assert abs(ph["gap_s"] - 10e-6) < 1e-12, ph          # inter-step idle
    assert summary["window_lo_us"] == 0.0
    assert summary["window_hi_us"] == 190.0
    assert "all_gather" in summary["comm_device"]
    text = render(summary)
    assert "fwd_bwd" in text and "all_gather" in text
    print(text)
    print("trace_report selftest: OK")
    return 0


def main(argv: List[str]) -> int:
    import argparse

    if "--selftest" in argv[1:]:
        return selftest()
    ap = argparse.ArgumentParser(
        description="device-truth report from a jax profiler trace")
    ap.add_argument("trace", help="trace dir (or perfetto_trace.json.gz)")
    ap.add_argument("--steps", type=int, default=None,
                    help="steps in the captured window (per-step column; "
                         "inferred from ds_optimizer_step spans when absent)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of tables")
    ns = ap.parse_args(argv[1:])
    try:
        summary = device_trace.summarize_trace(ns.trace, steps=ns.steps)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if ns.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
