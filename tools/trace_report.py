#!/usr/bin/env python
"""Offline device-truth report from a jax profiler trace directory.

The same analysis ``/profilez`` runs on a live engine
(``deepspeed_tpu/profiling/device_trace.py``), pointed at a trace on disk:

    python tools/trace_report.py /tmp/ds_trace            # terminal tables
    python tools/trace_report.py /tmp/ds_trace --steps 2  # per-step columns
    python tools/trace_report.py /tmp/ds_trace --json     # machine-readable

Accepts any directory containing a ``perfetto_trace.json.gz`` (captures
made with ``profile_trace`` + this repo's perfetto flag, ``/profilez``, or
the watchdog) or a direct path to the file.  Shows the phase breakdown
(fwd_bwd / optimizer / comm / other / gap — gap is device idle, the
overlap headroom), the device-true per-collective table, and the serving
dispatch-slack numbers when ``ds_serve_*`` ranges are present.

Needs this repo (and its jax dependency) importable; the trace file
itself is plain gzip'd trace-event JSON, parsed with stdlib only.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from deepspeed_tpu.profiling import device_trace  # noqa: E402


def _table(header: List[str], rows: List[List[str]]) -> str:
    table = [header] + rows
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    lines = []
    for i, r in enumerate(table):
        lines.append("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    return "\n".join(lines)


def _fmt_s(v: float) -> str:
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.3f}ms"
    return f"{v * 1e6:.1f}us"


def render(summary: dict) -> str:
    out = [f"trace: {summary['source']}"]
    if summary["degraded"]:
        out.append("NOTE: no device rows in this trace — the breakdown "
                   "below is HOST-range attribution (degraded mode)")
    elif summary.get("host_scoped"):
        out.append("host-bracketed scopes (device durations, host-range "
                   "assignment): " + ", ".join(summary["host_scoped"]))
    steps = summary.get("steps")
    window = summary["window_s"]
    busy = summary["device_busy_s"]
    out.append(f"window {_fmt_s(window)}"
               + (f" over {steps} step(s)" if steps else "")
               + f", device busy {_fmt_s(busy)}"
               + (f" ({100 * busy / window:.1f}%)" if window else ""))
    ph = summary["phases"]
    per = summary.get("per_step")
    rows = []
    for key in ("fwd_bwd_s", "optimizer_s", "comm_s", "other_s", "gap_s"):
        name = key[:-2]
        share = 100 * ph[key] / window if window else 0.0
        rows.append([name, _fmt_s(ph[key]), f"{share:.1f}%",
                     _fmt_s(per[key]) if per else ""])
    out.append("")
    out.append(_table(["phase", "total", "share", "per-step"], rows))
    cd = summary.get("comm_device") or {}
    if cd:
        crows = [[op, str(rec["count"]), _fmt_s(rec["seconds"]),
                  _fmt_s(rec["max_s"])]
                 for op, rec in sorted(cd.items(),
                                       key=lambda kv: -kv[1]["seconds"])]
        out.append("")
        out.append("device-true collectives (union per scope; compare with "
                   "the analytic ds_comm_*_seconds attribution):")
        out.append(_table(["collective", "spans", "device_s", "max_span"],
                          crows))
    serve = summary.get("serve")
    if serve:
        out.append("")
        out.append(
            f"serving: {serve['decode_blocks']} decode block(s), host "
            f"dispatch {_fmt_s(serve['decode_host_s'])}, device "
            f"{_fmt_s(serve['decode_device_s'])}, dispatch slack "
            f"{_fmt_s(serve['dispatch_slack_s'])}"
            + (f"; prefill host {_fmt_s(serve['prefill_host_s'])} / "
               f"device {_fmt_s(serve['prefill_device_s'])}"
               if serve.get("prefill_host_s") else ""))
    return "\n".join(out)


def main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="device-truth report from a jax profiler trace")
    ap.add_argument("trace", help="trace dir (or perfetto_trace.json.gz)")
    ap.add_argument("--steps", type=int, default=None,
                    help="steps in the captured window (per-step column; "
                         "inferred from ds_optimizer_step spans when absent)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary instead of tables")
    ns = ap.parse_args(argv[1:])
    try:
        summary = device_trace.summarize_trace(ns.trace, steps=ns.steps)
    except FileNotFoundError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if ns.json:
        print(json.dumps(summary, indent=1, sort_keys=True))
    else:
        print(render(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
