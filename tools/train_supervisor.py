#!/usr/bin/env python
"""Training supervisor: restart a crashed training process with bounded
retries + exponential backoff, resuming from the newest valid checkpoint.

    python tools/train_supervisor.py --max-restarts 5 -- \\
        python train.py --deepspeed_config ds_config.json
    python tools/train_supervisor.py --selftest          # tier-1 wired

The training script is responsible for calling
``engine.load_checkpoint(save_dir)`` at startup (no tag — the engine
walks back to the newest VALID tag, docs/RESILIENCE.md) and carrying its
dataloader position in ``client_state`` so resume is step-accurate.  The
supervisor's contract is deliberately thin:

- **exit 0** — training completed; the supervisor exits 0.
- **exit PREEMPT (default 243,** ``DS_PREEMPT_EXIT_CODE``**)** — the child
  took its SIGTERM emergency save and left on purpose
  (``runtime/preemption.py``); restart IMMEDIATELY (no backoff) and do
  NOT count it against the crash budget — preemptions are routine
  scheduling events, and abandoning a healthy job after N of them would
  defeat the whole layer.
- **any other nonzero exit** — a crash; restart after exponential backoff
  (``backoff_base * 2^n``, capped at ``backoff_max``) until
  ``max_restarts`` CRASH restarts are exhausted, then exit with the
  child's code.
- **SIGTERM to the supervisor** — forwarded to the child (its grace
  window runs); when the child exits, the supervisor exits with the
  child's code WITHOUT restarting (the whole job is being preempted).

Each incarnation sees ``DS_SUPERVISOR_RESTART=<n>`` (0 on the first run)
so training scripts/tests can behave differently per incarnation.

Zero dependencies beyond the stdlib — no jax import, so the supervisor
runs on any box (the ``fleet_dump`` / ``ckpt_verify`` rule).
``--selftest`` exercises the retry/backoff/preempt state machine against
synthetic children and is wired into tier-1.

The restart/backoff ladder itself lives in the SHARED
``deepspeed_tpu/elasticity/supervisor.py`` (``RestartPolicy``) so this
tool and ``tools/serve_supervisor.py`` cannot drift apart on the
exit-code contract.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional


def _load_supervisor_core():
    """The shared restart-ladder module: via the package when it is
    importable in this process, else exec'd by file path (operator box,
    no jax — the ``tools/router.py`` loader idiom)."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.elasticity import supervisor

        return supervisor
    mod = sys.modules.get("_ds_supervisor_core")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "elasticity", "supervisor.py")
    spec = importlib.util.spec_from_file_location("_ds_supervisor_core", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_supervisor_core"] = mod
    spec.loader.exec_module(mod)
    return mod


_core = _load_supervisor_core()
RestartPolicy = _core.RestartPolicy
PREEMPT_EXIT_CODE = _core.PREEMPT_EXIT_CODE

SIGTERM_GRACE_S = 30.0


def _load_goodput_core():
    """The goodput-ledger row schema (monitor/goodput_core.py), loaded
    the same jax-free way as the supervisor core: supervisors append
    their restart decisions to the run ledger so ``stitch`` can show WHY
    each ``restart_downtime`` gap exists."""
    if "deepspeed_tpu" in sys.modules:
        from deepspeed_tpu.monitor import goodput_core

        return goodput_core
    mod = sys.modules.get("_ds_goodput_core")
    if mod is not None:
        return mod
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "deepspeed_tpu", "monitor", "goodput_core.py")
    spec = importlib.util.spec_from_file_location("_ds_goodput_core", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_ds_goodput_core"] = mod
    spec.loader.exec_module(mod)
    return mod


class TrainSupervisor:
    """Restart-on-crash loop around one training process (module
    docstring has the exit-code contract)."""

    def __init__(self, cmd: List[str], max_restarts: int = 3,
                 backoff_base: float = 1.0, backoff_max: float = 60.0,
                 preempt_exit_code: int = PREEMPT_EXIT_CODE,
                 env: Optional[Dict[str, str]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 grace_s: float = SIGTERM_GRACE_S,
                 healthy_reset_s: Optional[float] = None,
                 status_file: Optional[str] = None,
                 runledger: Optional[str] = None,
                 run_id: Optional[str] = None):
        if not cmd:
            raise ValueError("no child command given")
        self.cmd = list(cmd)
        # the shared restart ladder (elasticity/supervisor.py): strict
        # PR 8 semantics by default — every crash burns budget; the
        # OPT-IN --healthy-reset-s knob forgives the ladder after a long
        # healthy incarnation (a job that crashes once a day must not
        # exhaust a lifetime budget — the serve_supervisor long-horizon
        # mode, now available train-side too)
        self.policy = RestartPolicy(max_restarts=max_restarts,
                                    backoff_base=backoff_base,
                                    backoff_max=backoff_max,
                                    preempt_exit_code=preempt_exit_code,
                                    healthy_reset_s=healthy_reset_s)
        self.max_restarts = self.policy.max_restarts
        self.backoff_base = self.policy.backoff_base
        self.backoff_max = self.policy.backoff_max
        self.preempt_exit_code = self.policy.preempt_exit_code
        self.base_env = dict(env if env is not None else os.environ)
        self.sleep = sleep
        self.grace_s = grace_s
        self.status_file = status_file
        # goodput-ledger channel: every incarnation appends to ONE jsonl
        # (DSTPU_RUNLEDGER) under ONE run identity (DSTPU_RUN_ID), and the
        # supervisor writes its restart decisions there too — stitch()
        # folds them back into one run timeline (restart gaps become
        # `restart_downtime`)
        self.runledger = runledger or self.base_env.get("DSTPU_RUNLEDGER")
        self.run_id = (run_id or self.base_env.get("DSTPU_RUN_ID")
                       or (f"run-{os.getpid()}-{int(time.time())}"
                           if self.runledger else None))
        self._terminating = False
        self._child: Optional[subprocess.Popen] = None
        self._state = "idle"
        self._last_exit_code: Optional[int] = None
        self._restart_times: List[float] = []

    def _write_status(self, state: str) -> None:
        """Supervisor truth as JSON (--status-file): ladder counters,
        child state, restart timestamps — read by operators/fleet_dump
        instead of scraped from logs."""
        self._state = state
        if self.status_file is None:
            return
        child = self._child
        _core.write_status(self.status_file, {
            "kind": "train_supervisor",
            "state": state,           # running|backoff|done|given_up|terminated
            "pid": os.getpid(),
            "child_pid": child.pid if child is not None else None,
            "incarnation": self.restarts,
            "last_exit_code": self._last_exit_code,
            "restart_times_unix": list(self._restart_times),
            "ladder": self.policy.counters(),
            "cmd": self.cmd,
        })

    def _ledger_append(self, event: str, **extra) -> None:
        """Restart-decision row into the run ledger jsonl (no-op without
        --runledger / DSTPU_RUNLEDGER)."""
        if not self.runledger:
            return
        gp = _load_goodput_core()
        gp.append_row(self.runledger, gp.supervisor_row(
            self.run_id, event, time.time(),
            supervisor="train_supervisor", incarnation=self.restarts,
            **extra))

    # counters live on the shared policy (one mutation site per exit);
    # the PR 8 attribute surface stays intact for callers/tests
    @property
    def restarts(self) -> int:
        return self.policy.restarts

    @property
    def crash_restarts(self) -> int:
        return self.policy.crash_restarts

    @property
    def preempt_restarts(self) -> int:
        return self.policy.preempt_restarts

    @property
    def backoffs(self) -> List[float]:
        return self.policy.backoffs

    # -- signal forwarding ----------------------------------------------
    def _forward_sigterm(self, _sig, _frame):
        self._terminating = True
        child = self._child
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass

    def _log(self, msg: str) -> None:
        print(f"[train_supervisor] {msg}", file=sys.stderr, flush=True)

    # -- main loop -------------------------------------------------------
    def run(self) -> int:
        prev = None
        try:
            prev = signal.signal(signal.SIGTERM, self._forward_sigterm)
        except ValueError:           # non-main thread (tests)
            prev = None
        try:
            return self._run()
        finally:
            if prev is not None:
                try:
                    signal.signal(signal.SIGTERM, prev)
                except ValueError:
                    pass

    def _run(self) -> int:
        last_code = 0
        while True:
            if self._terminating:
                # SIGTERM landed between incarnations (e.g. during a
                # backoff sleep): spawning now would create a child that
                # never got the forwarded signal and dies by SIGKILL with
                # no emergency save — the job is being preempted, stop
                self._log("terminated during the restart window; not "
                          "spawning a new incarnation")
                self._write_status("terminated")
                return last_code or 143
            env = dict(self.base_env)
            env["DS_SUPERVISOR_RESTART"] = str(self.restarts)
            env["DS_PREEMPT_EXIT_CODE"] = str(self.preempt_exit_code)
            if self.runledger:
                env["DSTPU_RUNLEDGER"] = self.runledger
                env["DSTPU_RUN_ID"] = self.run_id
            cmdline = " ".join(self.cmd).replace("\n", "\\n")
            if len(cmdline) > 160:
                cmdline = cmdline[:157] + "..."
            self._log(f"starting (incarnation {self.restarts}): {cmdline}")
            self._child = subprocess.Popen(self.cmd, env=env)
            self._write_status("running")
            t_spawn = time.monotonic()
            code = self._wait_child()
            self._child = None
            self._last_exit_code = code
            last_code = code
            if self._terminating and code != 0:
                self._log(f"supervisor was terminated; child exited "
                          f"{code} — not restarting")
                self._write_status("terminated")
                return code
            # ran_s feeds the opt-in healthy_reset_s ladder forgiveness
            decision = self.policy.decide(
                code, ran_s=time.monotonic() - t_spawn)
            if decision.action == "done":
                self._log(f"child completed (restarts={self.restarts})")
                self._write_status("done")
                self._ledger_append("done", exit_code=code)
                return 0
            if decision.action == "give_up":
                self._log(f"max_restarts={self.max_restarts} crash "
                          f"restarts exhausted; giving up with exit code "
                          f"{code}")
                self._write_status("given_up")
                self._ledger_append("give_up", exit_code=code)
                return code
            self._restart_times.append(time.time())
            self._ledger_append("restart", decision=decision.kind,
                                exit_code=code,
                                backoff_s=(0.0 if decision.kind == "preempt"
                                           else decision.delay))
            if decision.kind == "preempt":
                # a clean emergency save was taken: restart immediately;
                # preemptions are routine scheduling events and do NOT
                # burn the crash budget (a child that lies about 243
                # without actually saving is operator error)
                self._log(f"child preempted (exit {code}, emergency save "
                          f"taken): restart #{self.restarts}, no backoff")
                continue
            self._log(f"child crashed (exit {code}): restart "
                      f"#{self.restarts} after {decision.delay:g}s backoff; "
                      f"training should resume from the newest valid "
                      f"checkpoint")
            self._write_status("backoff")
            self.sleep(decision.delay)

    def _wait_child(self) -> int:
        child = self._child
        assert child is not None
        while True:
            try:
                return child.wait(timeout=0.5)
            except subprocess.TimeoutExpired:
                if self._terminating:
                    # grace window: SIGTERM was forwarded; escalate only
                    # past the deadline
                    try:
                        return child.wait(timeout=self.grace_s)
                    except subprocess.TimeoutExpired:
                        self._log("grace window expired; killing child")
                        child.kill()
                        return child.wait()


# ---------------------------------------------------------------------------
# selftest (tier-1 wired: tests/unit/test_supervisor.py)
# ---------------------------------------------------------------------------


def _counter_child(tmp: str, fail_times: int, fail_code: int = 7) -> List[str]:
    """A child that exits ``fail_code`` its first ``fail_times`` runs
    (counted in a state file), then 0."""
    prog = (
        "import os,sys\n"
        f"p = {tmp!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        f"sys.exit({fail_code} if n < {fail_times} else 0)\n")
    return [sys.executable, "-c", prog]


def selftest() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        # crash twice, then succeed: two backoffs, doubling
        sleeps: List[float] = []
        sup = TrainSupervisor(_counter_child(os.path.join(td, "a"), 2),
                              max_restarts=3, backoff_base=0.01,
                              sleep=sleeps.append)
        assert sup.run() == 0
        assert sup.restarts == 2 and sup.crash_restarts == 2
        assert sleeps == [0.01, 0.02], sleeps

        # budget exhausted: the child's code comes back
        sup = TrainSupervisor(_counter_child(os.path.join(td, "b"), 99),
                              max_restarts=1, backoff_base=0.0,
                              sleep=lambda _s: None)
        assert sup.run() == 7 and sup.restarts == 1

        # preemption exit: restart without backoff or crash budget
        sup = TrainSupervisor(
            _counter_child(os.path.join(td, "c"), 1,
                           fail_code=PREEMPT_EXIT_CODE),
            max_restarts=3, backoff_base=5.0, sleep=sleeps.append)
        n_sleeps = len(sleeps)
        assert sup.run() == 0
        assert sup.preempt_restarts == 1 and sup.crash_restarts == 0
        assert len(sleeps) == n_sleeps      # no backoff slept

        # preemptions beyond max_restarts still restart (only CRASHES
        # burn the budget): 3 preempt exits with max_restarts=1
        sup = TrainSupervisor(
            _counter_child(os.path.join(td, "c2"), 3,
                           fail_code=PREEMPT_EXIT_CODE),
            max_restarts=1, backoff_base=5.0, sleep=sleeps.append)
        assert sup.run() == 0
        assert sup.preempt_restarts == 3 and sup.crash_restarts == 0
        assert len(sleeps) == n_sleeps

        # SIGTERM latched between incarnations: no new child is spawned
        sup = TrainSupervisor(_counter_child(os.path.join(td, "c3"), 0),
                              max_restarts=3, sleep=lambda _s: None)
        sup._terminating = True
        assert sup.run() == 143
        assert not os.path.exists(os.path.join(td, "c3")), \
            "a child was spawned after termination latched"

        # backoff cap
        sup = TrainSupervisor(_counter_child(os.path.join(td, "d"), 4),
                              max_restarts=4, backoff_base=1.0,
                              backoff_max=2.5, sleep=lambda _s: None)
        assert sup.run() == 0
        assert sup.backoffs == [1.0, 2.0, 2.5, 2.5]

        # DS_SUPERVISOR_RESTART is visible per incarnation
        marker = os.path.join(td, "e")
        prog = ("import os,sys\n"
                f"open({marker!r}, 'a').write("
                "os.environ['DS_SUPERVISOR_RESTART'] + ',')\n"
                "sys.exit(0 if os.environ['DS_SUPERVISOR_RESTART'] == '1' "
                "else 3)\n")
        sup = TrainSupervisor([sys.executable, "-c", prog], max_restarts=2,
                              backoff_base=0.0, sleep=lambda _s: None)
        assert sup.run() == 0
        assert open(marker).read() == "0,1,"

        # --status-file: supervisor truth lands as readable JSON (ladder
        # counters + terminal state + restart timestamps), atomically
        import json as _json

        status = os.path.join(td, "status.json")
        sup = TrainSupervisor(_counter_child(os.path.join(td, "f"), 2),
                              max_restarts=3, backoff_base=0.0,
                              sleep=lambda _s: None, status_file=status)
        assert sup.run() == 0
        st = _json.load(open(status))
        assert st["kind"] == "train_supervisor" and st["state"] == "done"
        assert st["ladder"]["crash_restarts"] == 2
        assert len(st["restart_times_unix"]) == 2
        assert st["updated_unix"] > 0
        assert not [n for n in os.listdir(td)
                    if n.startswith("status.json.tmp")]

        # opt-in healthy_reset_s: a long-enough incarnation forgives the
        # crash ladder (ran_s is wall time here, so use a tiny threshold
        # and a child that sleeps past it before crashing)
        slow_crash = os.path.join(td, "g")
        prog = ("import os,sys,time\n"
                f"p = {slow_crash!r}\n"
                "n = int(open(p).read()) if os.path.exists(p) else 0\n"
                "open(p, 'w').write(str(n + 1))\n"
                "time.sleep(0.05)\n"
                "sys.exit(7 if n < 3 else 0)\n")
        sup = TrainSupervisor([sys.executable, "-c", prog], max_restarts=1,
                              backoff_base=0.0, sleep=lambda _s: None,
                              healthy_reset_s=0.01)
        # 3 crashes with max_restarts=1 would give up under the strict
        # ladder; every incarnation ran "healthy" long enough to forgive
        assert sup.run() == 0
        assert sup.crash_restarts >= 1

        # --runledger: the run identity reaches every incarnation and the
        # supervisor's restart decisions land as `supervisor` jsonl rows
        ledger = os.path.join(td, "runledger.jsonl")
        marker = os.path.join(td, "h_env")
        prog = ("import os,sys\n"
                f"open({marker!r}, 'a').write("
                "os.environ['DSTPU_RUN_ID'] + ',')\n"
                "assert os.environ['DSTPU_RUNLEDGER']\n"
                "sys.exit(0 if os.environ['DS_SUPERVISOR_RESTART'] == '1' "
                "else 3)\n")
        sup = TrainSupervisor([sys.executable, "-c", prog], max_restarts=2,
                              backoff_base=0.0, sleep=lambda _s: None,
                              runledger=ledger, run_id="selftest-run")
        assert sup.run() == 0
        assert open(marker).read() == "selftest-run,selftest-run,"
        gp = _load_goodput_core()
        rows = gp.read_rows(ledger)
        kinds = [(r["kind"], r.get("event")) for r in rows]
        assert ("supervisor", "restart") in kinds, kinds
        assert ("supervisor", "done") in kinds, kinds
        assert all(r["run_id"] == "selftest-run" for r in rows)
    print("train_supervisor selftest: OK")
    return 0


# ---------------------------------------------------------------------------


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv if argv is None else argv)
    if "--selftest" in argv[1:]:
        return selftest()
    parser = argparse.ArgumentParser(
        prog="train_supervisor",
        description="Restart a crashed training process with bounded "
                    "retries + exponential backoff (resume from the newest "
                    "valid checkpoint).")
    parser.add_argument("--max-restarts", type=int, default=3)
    parser.add_argument("--backoff-base", type=float, default=1.0,
                        help="first crash backoff in seconds (doubles per "
                             "crash)")
    parser.add_argument("--backoff-max", type=float, default=60.0)
    parser.add_argument("--preempt-exit-code", type=int,
                        default=PREEMPT_EXIT_CODE,
                        help="child exit code meaning 'preempted after a "
                             "clean emergency save' (restart immediately)")
    parser.add_argument("--healthy-reset-s", type=float, default=None,
                        help="OPT-IN ladder forgiveness: an incarnation "
                             "that ran at least this long resets the crash "
                             "budget (default: strict — every crash burns "
                             "it)")
    parser.add_argument("--status-file", default=None,
                        help="write supervisor truth (ladder counters, "
                             "child state, restart timestamps) as JSON to "
                             "this path on every state change")
    parser.add_argument("--runledger", default=None,
                        help="goodput-ledger jsonl path: exported to every "
                             "incarnation as DSTPU_RUNLEDGER (+ a shared "
                             "DSTPU_RUN_ID) and appended with the "
                             "supervisor's restart decisions, so "
                             "tools/goodput_report.py stitches the whole "
                             "run across restarts (defaults to the "
                             "DSTPU_RUNLEDGER env var)")
    parser.add_argument("--run-id", default=None,
                        help="run identity for --runledger rows (default: "
                             "DSTPU_RUN_ID env or a generated id)")
    parser.add_argument("cmd", nargs=argparse.REMAINDER,
                        help="-- followed by the training command")
    args = parser.parse_args(argv[1:])
    cmd = args.cmd[1:] if args.cmd[:1] == ["--"] else args.cmd
    if not cmd:
        parser.error("no training command given (… -- python train.py …)")
    sup = TrainSupervisor(cmd, max_restarts=args.max_restarts,
                          backoff_base=args.backoff_base,
                          backoff_max=args.backoff_max,
                          preempt_exit_code=args.preempt_exit_code,
                          healthy_reset_s=args.healthy_reset_s,
                          status_file=args.status_file,
                          runledger=args.runledger, run_id=args.run_id)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
